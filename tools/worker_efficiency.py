"""Cross-worker fanout efficiency measurement (VERDICT r4 item 6).

Pins subscribers and publishers to SPECIFIC workers via the per-worker
direct ports (WorkerGroup(direct_base=...)) and measures deliveries/s
for three placements:

  local1  — 1 worker, subs+pubs on it (baseline T1)
  local2  — 2 workers, subs+pubs both pinned to worker0 (group overhead
            without the hop; worker1 idle)
  cross2  — 2 workers, subs on worker0, pubs on worker1 (100% of
            deliveries take the cross-worker hop)

plus a microbenchmark of the hop's ingredients (cluster codec encode /
decode of a representative publish frame, loopback TCP round trip).

The cores→throughput model these numbers validate (README workers
section): with per-delivery local CPU cost L and hop cost H, a k-core
k-worker deployment with cross fraction f (uniform placement: (k-1)/k)
delivers per-worker efficiency e = L / (L + f*H) and total throughput
k * e * T1. On THIS 1-core container all processes share one core, so
cross2/local1 directly measures L/(L+H) — the hop-cost ratio c = H/L
falls out of it and must agree with the codec+RTT microbenchmark.

Usage: python tools/worker_efficiency.py [--secs 20] [--subs 16]
            [--pubs 4] [--qos 1] [--window 32] [--json out.json]
"""
import argparse
import json
import multiprocessing as mp
import os
import socket
import struct
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.loadtest import _client_proc  # noqa: E402


def run_scenario(n_workers: int, sub_worker: int, pub_worker: int,
                 secs: float, n_subs: int, n_pubs: int, qos: int,
                 window: int) -> dict:
    from vernemq_tpu.broker.workers import WorkerGroup

    direct_base = 24300
    group = WorkerGroup(n_workers, port=24290, cluster_base=24270,
                        direct_base=direct_base, allow_anonymous=True,
                        systree_enabled=False)
    group.start()
    # poll the direct ports: spawn workers can take 5-10s to boot (full
    # package import per process) — a fixed sleep aborts slow boots
    deadline = time.time() + 60
    for w in range(n_workers):
        while time.time() < deadline:
            try:
                socket.create_connection(
                    ("127.0.0.1", direct_base + w), 0.5).close()
                break
            except OSError:
                time.sleep(0.25)
        else:
            group.stop()
            raise SystemExit(f"worker {w} never came up")
    time.sleep(1.0)  # mesh formation after the last listener is up
    try:
        ctx = mp.get_context("spawn")
        out_q = ctx.Queue()
        sub_port = direct_base + sub_worker
        pub_port = direct_base + pub_worker
        # subscribers in one shard process, publishers in another, so
        # the harness is not the GIL bottleneck it measures around
        ps = ctx.Process(target=_client_proc, args=(
            "127.0.0.1", sub_port, list(range(n_subs)), [], secs + 2.0,
            qos, window, 64, False, "s", out_q))
        pp = ctx.Process(target=_client_proc, args=(
            "127.0.0.1", pub_port, [], list(range(n_pubs)), secs,
            qos, window, 64, False, "p", out_q))
        ps.start()
        time.sleep(1.0)  # subscriptions in place (and replicated)
        pp.start()
        try:
            res = [out_q.get(timeout=secs + 120) for _ in range(2)]
        except Exception:
            # a shard crashed before reporting (mesh not up, connect
            # refused): kill the survivor so the tool exits instead of
            # hanging on a non-daemon child
            for p in (ps, pp):
                if p.is_alive():
                    p.terminate()
            raise SystemExit("client shard died before reporting — "
                             "rerun (mesh may not have formed in time)")
        ps.join(30)
        pp.join(30)
        sent = sum(r[0] for r in res)
        failed = sum(r[1] for r in res)
        received = sum(r[2] for r in res)
        # rate over the PUBLISHING window only: the sub shard runs
        # secs+2.0 to drain, and dividing by its padded elapsed would
        # understate every rate by the padding share
        pub_elapsed = next((r[3] for r in res if r[0] > 0),
                           max(r[3] for r in res))
        return {"deliveries_per_s": received / pub_elapsed,
                "acked_pubs_per_s": (sent - failed) / pub_elapsed,
                "received": received, "sent": sent, "failed": failed,
                "elapsed_s": pub_elapsed}
    finally:
        group.stop()


def micro_hop() -> dict:
    """Per-message cost of the cross-worker hop's ingredients."""
    from vernemq_tpu.broker.message import Msg
    from vernemq_tpu.cluster.codec import decode, encode
    from vernemq_tpu.cluster.node import frame, msg_to_term, term_to_msg

    msg = Msg(topic=("lt", "3", "mX0"), payload=b"x" * 64, qos=1,
              retain=False, mountpoint="", msg_ref=b"r" * 16,
              properties={})
    N = 20_000
    t0 = time.perf_counter()
    for _ in range(N):
        frame(b"msg", msg_to_term(msg))
    enc_us = (time.perf_counter() - t0) / N * 1e6
    wire = encode(msg_to_term(msg))
    t0 = time.perf_counter()
    for _ in range(N):
        term_to_msg(decode(wire))
    dec_us = (time.perf_counter() - t0) / N * 1e6

    # loopback TCP round trip (64B echo), amortised over a pipeline of 1
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    import threading

    def echo():
        conn, _ = srv.accept()
        while True:
            b = conn.recv(4096)
            if not b:
                return
            conn.sendall(b)

    threading.Thread(target=echo, daemon=True).start()
    c = socket.create_connection(srv.getsockname())
    c.sendall(b"w" * 64)
    c.recv(4096)  # warm
    N2 = 2_000
    t0 = time.perf_counter()
    for _ in range(N2):
        c.sendall(b"w" * 64)
        c.recv(4096)
    rtt_us = (time.perf_counter() - t0) / N2 * 1e6
    c.close()
    srv.close()
    return {"encode_us": enc_us, "decode_us": dec_us,
            "loopback_rtt_us": rtt_us}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--secs", type=float, default=20.0)
    ap.add_argument("--subs", type=int, default=16)
    ap.add_argument("--pubs", type=int, default=4)
    ap.add_argument("--qos", type=int, default=1)
    ap.add_argument("--window", type=int, default=32)
    ap.add_argument("--trials", type=int, default=1,
                    help="interleaved rounds (each runs all scenarios "
                         "back to back); MEDIANS are reported — "
                         "absolute throughput drifts over minutes, so "
                         "only within-round ratios are comparable")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    print("micro: hop ingredient costs ...", flush=True)
    micro = micro_hop()
    print(f"  cluster-codec encode {micro['encode_us']:.1f}us  "
          f"decode {micro['decode_us']:.1f}us  "
          f"loopback RTT {micro['loopback_rtt_us']:.1f}us", flush=True)

    # INTERLEAVED rounds: this box's absolute throughput drifts ±30%
    # over minutes, so cross-scenario ratios are only meaningful within
    # one round (scenarios back to back). The hop-cost ratio is the
    # median of per-round local/cross ratios; absolute columns report
    # per-scenario medians.
    layout = {"local1": (1, 0, 0), "local2": (2, 0, 0),
              "cross2": (2, 0, 1)}
    runs: dict = {n: [] for n in layout}
    round_c = []
    round_g = []
    for t in range(args.trials):
        for name, (nw, sw, pw) in layout.items():
            r = run_scenario(nw, sw, pw, args.secs, args.subs,
                             args.pubs, args.qos, args.window)
            runs[name].append(r)
            print(f"round {t} {name}: deliveries/s="
                  f"{r['deliveries_per_s']:.0f}", flush=True)
        t1r = runs["local1"][-1]["deliveries_per_s"]
        t2lr = runs["local2"][-1]["deliveries_per_s"]
        t2xr = runs["cross2"][-1]["deliveries_per_s"]
        if min(t1r, t2lr, t2xr) <= 0:
            print(f"round {t}: a scenario delivered nothing — round "
                  f"excluded from the ratio medians", flush=True)
            continue
        round_c.append(t1r / t2xr - 1.0)
        round_g.append(t1r / t2lr - 1.0)
        print(f"round {t}: c={round_c[-1]:.3f} group={round_g[-1]:.3f}",
              flush=True)

    def med(vals):
        s = sorted(vals)
        return s[len(s) // 2]

    scenarios = {}
    for name, rs in runs.items():
        m = med([r["deliveries_per_s"] for r in rs])
        scenarios[name] = {
            "deliveries_per_s_median": round(m),
            "rounds": [round(r["deliveries_per_s"]) for r in rs],
        }
    if not round_c:
        print(json.dumps({"error": "no complete round", "runs": {
            k: [round(r["deliveries_per_s"]) for r in v]
            for k, v in runs.items()}}))
        raise SystemExit(1)
    # 1-core identity: cross2/local1 = L/(L+H)  =>  c = H/L
    c = med(round_c)
    model = {
        "hop_cost_ratio_c": c,
        "hop_cost_ratio_rounds": [round(x, 3) for x in round_c],
        "group_overhead_ratio": med(round_g),
        # k-core uniform placement: e(k) = 1 / (1 + c*(k-1)/k)
        "per_worker_efficiency": {
            str(k): 1.0 / (1.0 + c * (k - 1) / k) for k in (2, 4, 8)
        },
    }
    out = {"micro": micro, "scenarios": scenarios, "model": model,
           "config": vars(args), "nproc": 1}
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
