"""Measure per-launch overhead + pipelining on the axon tunnel.

1. trivial jitted kernel, N chained (dependent) launches
2. trivial jitted kernel, N independent launches, one checksum pull
3. medium matmul (MXU work ~100 GFLOP) same two ways
4. device_put cost for small arrays
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    note(f"platform={dev.platform}")

    @jax.jit
    def tiny(x):
        return x + 1

    x = jax.device_put(jnp.zeros((8, 128), jnp.float32), dev)
    np.asarray(tiny(x))

    N = 100
    # chained
    t0 = time.perf_counter()
    y = x
    for _ in range(N):
        y = tiny(y)
    np.asarray(y)
    note(f"tiny chained: {(time.perf_counter()-t0)/N*1e3:.3f} ms/launch")

    # independent
    t0 = time.perf_counter()
    outs = [tiny(x) for _ in range(N)]
    acc = outs[0]
    for o in outs[1:]:
        acc = acc + o
    np.asarray(acc)
    note(f"tiny indep: {(time.perf_counter()-t0)/(N+N)*1e3:.3f} ms/launch "
         f"(incl the {N} adds)")

    # medium matmul: [4096, 64] @ [64, 393216] bf16 -> ~206 GFLOP? no:
    # 4096*64*393216*2 = 206 GFLOP... make it [1024, 40] @ [40, 1.8M]
    K, S, B = 64, 1_572_864, 1024
    F = jax.device_put(jnp.ones((K, S), jnp.bfloat16), dev)

    @jax.jit
    def mm(g):
        out = jax.lax.dot_general(g, F, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return (out == 0.0).sum(dtype=jnp.int32)

    g = jax.device_put(jnp.ones((B, K), jnp.bfloat16), dev)
    np.asarray(mm(g))
    M = 20
    t0 = time.perf_counter()
    acc = jnp.zeros((), jnp.int32)
    for _ in range(M):
        acc = acc + mm(g)
    np.asarray(acc)
    per = (time.perf_counter() - t0) / M
    gf = 2 * B * K * S / per / 1e12
    bw = (K * S * 2) / per / 1e9
    note(f"matmul+reduce [B={B},K={K}]x[K,S={S}]: {per*1e3:.2f} ms/launch "
         f"({gf:.1f} TFLOP/s, F-read {bw:.0f} GB/s)")

    # B=8192 same
    g8 = jax.device_put(jnp.ones((8192, K), jnp.bfloat16), dev)
    np.asarray(mm(g8))
    t0 = time.perf_counter()
    acc = jnp.zeros((), jnp.int32)
    for _ in range(M):
        acc = acc + mm(g8)
    np.asarray(acc)
    per8 = (time.perf_counter() - t0) / M
    gf8 = 2 * 8192 * K * S / per8 / 1e12
    note(f"matmul+reduce [B=8192]: {per8*1e3:.2f} ms/launch ({gf8:.1f} TFLOP/s)")

    # device_put cost
    a = np.zeros((1024, 8), np.int32)
    t0 = time.perf_counter()
    for _ in range(50):
        jax.device_put(a, dev)
    note(f"device_put 32KB: {(time.perf_counter()-t0)/50*1e3:.3f} ms")
    t0 = time.perf_counter()
    ds = [jax.device_put(a, dev) for _ in range(50)]
    note(f"device_put 32KB nosync: {(time.perf_counter()-t0)/50*1e3:.3f} ms")


if __name__ == "__main__":
    main()
