"""Probe 2: memory-safe kernel candidates at 1M subs.

  W1: chunked full-scan — unrolled static S-chunks, matmul+pack per chunk,
      cheap extraction on the assembled packed mask.
  W2: batched-tile einsum — pubs grouped by bucket into [T, TP] tiles,
      each tile matmuls its bucket's R-row window: [T,TP,K]x[T,K,R],
      count-only and with per-tile extraction.
"""
import functools
import random
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bench import build_corpus, zipf_topics
    from vernemq_tpu.models.tpu_table import SubscriptionTable
    from vernemq_tpu.ops import match_kernel as K

    subs = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    rng = random.Random(42)
    table = SubscriptionTable(max_levels=8,
                              initial_capacity=1 << (subs - 1).bit_length())
    t0 = time.perf_counter()
    pools = build_corpus(rng, subs, table)
    note(f"corpus {time.perf_counter()-t0:.1f}s")
    dev = jax.devices()[0]
    put = lambda a: jax.device_put(a, dev)
    note(f"platform={dev.platform}")
    arrays = (put(table.words), put(table.eff_len), put(table.has_hash),
              put(table.first_wild), put(table.active))
    bits = table.id_bits
    F_t, t1 = K.build_operands(arrays[0], arrays[1], bits)
    F_t = jax.block_until_ready(F_t)
    S = int(arrays[0].shape[0])
    caps = table.reg_cap
    note(f"S={S} NB={table.NB} bits={bits} glob={caps[0]} "
         f"bucket caps: min={caps[1:].min()} p50={int(np.percentile(caps[1:],50))} "
         f"max={caps[1:].max()} nonzero={(caps[1:]>256).sum()}")
    eff, hh, fw, act = arrays[1], arrays[2], arrays[3], arrays[4]

    def enc(B):
        topics = zipf_topics(rng, pools, B)
        pw = np.full((B, table.L), K.PAD_ID, dtype=np.int32)
        pl = np.zeros(B, dtype=np.int32)
        pd = np.zeros(B, dtype=bool)
        pb = np.zeros(B, dtype=np.int32)
        for i, t in enumerate(topics):
            row, n, dollar, b = table.encode_topic_ex(t)
            pw[i], pl[i], pd[i], pb[i] = row, n, dollar, b
        return pw, pl, pd, pb

    def bench(fn, args, iters=20, label=""):
        np.asarray(jax.tree_util.tree_leaves(fn(*args))[0])
        t0 = time.perf_counter()
        acc = jnp.zeros((), jnp.int32)
        for _ in range(iters):
            out = fn(*args)
            acc = acc + jax.tree_util.tree_leaves(out)[0].sum()
        np.asarray(acc)
        per = (time.perf_counter() - t0) / iters
        B = args[0].shape[0] if args[0].ndim <= 2 else args[0].shape[0] * args[0].shape[1]
        note(f"{label}: {per*1e3:.2f} ms/batch")
        return per

    # ---------------- W1: chunked full-scan, pack per chunk -------------
    def mk_w1(CH, count_only):
        nch = S // CH
        assert S % CH == 0

        @jax.jit
        def w1(pw, pl, pd):
            G = K.build_pub_operand(pw, bits)
            packs = []
            for c in range(nch):
                sl = slice(c * CH, (c + 1) * CH)
                mm = lax.dot_general(G, F_t[:, sl], (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
                m = (mm + t1[None, sl] == 0.0) & K._epilogue(
                    pl, pd, eff[sl], hh[sl], fw[sl], act[sl])
                packs.append(K._pack_mask(m))
            packed = jnp.concatenate(packs, axis=1)
            if count_only:
                return lax.population_count(packed).sum(dtype=jnp.int32)
            return K.extract_indices_packed(packed, 256, 2048)[2].sum()
        return w1

    # ---------------- W2: batched-tile einsum ---------------------------
    # tiles: host groups pubs by bucket, cuts into TP-sized tiles, each
    # covering chunk c of its bucket's region (R-wide windows).
    def tiles_for(pb, n, R, TP):
        order = np.argsort(pb[:n], kind="stable")
        tiles = []  # (pub_sel, col_start, row_lo, row_ln)
        i = 0
        while i < n:
            b = pb[order[i]]
            j = i
            while j < n and pb[order[j]] == b:
                j += 1
            start = int(table.reg_start[b])
            cap = int(table.reg_cap[b])
            for plo in range(i, j, TP):
                sel = order[plo:plo + TP]
                for c0 in range(0, cap, R):
                    cs = min(start + c0, S - R)
                    lo = start + c0 - cs
                    ln = min(R - lo, cap - c0)
                    tiles.append((sel, cs, lo, ln))
            i = j
        return tiles

    def pack_tiles(enc_out, R, TP, Tpad):
        pw, pl, pd, pb = enc_out
        n = pw.shape[0]
        tl = tiles_for(pb, n, R, TP)
        T = len(tl)
        if T > Tpad:
            raise RuntimeError(f"T={T} > Tpad={Tpad}")
        t_pw = np.full((Tpad, TP, table.L), np.int32(K.PAD_ID), np.int32)
        t_pl = np.zeros((Tpad, TP), np.int32)
        t_pd = np.zeros((Tpad, TP), bool)
        t_cs = np.zeros(Tpad, np.int32)
        t_lo = np.zeros(Tpad, np.int32)
        t_ln = np.zeros(Tpad, np.int32)
        for ti, (sel, cs, lo, ln) in enumerate(tl):
            m = len(sel)
            t_pw[ti, :m] = pw[sel]
            t_pl[ti, :m] = pl[sel]
            t_pd[ti, :m] = pd[sel]
            t_cs[ti], t_lo[ti], t_ln[ti] = cs, lo, ln
        return T, t_pw, t_pl, t_pd, t_cs, t_lo, t_ln

    Kdim = int(F_t.shape[0])

    def mk_w2(R, TP, count_only, extract=False):
        @jax.jit
        def w2(t_pw, t_pl, t_pd, t_cs, t_lo, t_ln, gpw, gpl, gpd):
            # global phase (region 0)
            glob = int(caps[0])
            G = K.build_pub_operand(gpw, bits)
            mmg = lax.dot_general(G, F_t[:, :glob], (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
            mg = (mmg + t1[None, :glob] == 0.0) & K._epilogue(
                gpl, gpd, eff[:glob], hh[:glob], fw[:glob], act[:glob])
            gout = (lax.population_count(K._pack_mask(mg)).sum(dtype=jnp.int32)
                    if count_only else
                    K.extract_indices_packed(K._pack_mask(mg), 256, 2048)[2].sum())
            # tile phase: gather F windows [T, K, R]
            cols = t_cs[:, None] + jnp.arange(R)[None, :]      # [T, R]
            Fw = F_t[:, cols]                                   # [K, T, R]
            Fw = jnp.swapaxes(Fw, 0, 1)                         # [T, K, R]
            t1w = t1[cols]                                      # [T, R]
            effw, hhw, fww, actw = eff[cols], hh[cols], fw[cols], act[cols]
            Gt = K.build_pub_operand(
                t_pw.reshape(-1, t_pw.shape[-1]), bits).reshape(
                t_pw.shape[0], TP, -1)                          # [T, TP, Kd]
            mm = lax.dot_general(
                Gt, Fw, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)             # [T, TP, R]
            r = jnp.arange(R, dtype=jnp.int32)
            rowok = (r[None, :] >= t_lo[:, None]) & (r[None, :] < (t_lo + t_ln)[:, None])
            m = (mm + t1w[:, None, :] == 0.0)
            m = m & rowok[:, None, :]
            # epilogue per tile-window
            len_ok = jnp.where(hhw[:, None, :],
                               t_pl[:, :, None] >= effw[:, None, :],
                               t_pl[:, :, None] == effw[:, None, :])
            m = m & len_ok & ~(t_pd[:, :, None] & fww[:, None, :]) & actw[:, None, :]
            if count_only:
                return gout + m.sum(dtype=jnp.int32)
            # per-tile extraction: flatten [T*TP, R]
            Tn = m.shape[0]
            mf = m.reshape(Tn * TP, R)
            pk = K._pack_mask(mf)
            i2, v2, c2 = K.extract_indices_packed(pk, 256, 2048)
            return gout + c2.sum() + i2.sum()
        return w2

    for B in (2048, 8192):
        e = enc(B)
        a = (put(e[0]), put(e[1]), put(e[2]))
        for CH in (131072,):
            try:
                bench(mk_w1(CH, True), a, label=f"W1 count CH={CH} B={B}")
                bench(mk_w1(CH, False), a, label=f"W1 extr  CH={CH} B={B}")
            except Exception as ex:
                note(f"W1 CH={CH} B={B} failed: {type(ex).__name__} {str(ex)[:120]}")
        for R, TP in ((8192, 128), (8192, 256), (32768, 256)):
            try:
                Tpad = 512 if B == 8192 else 256
                T, *tarrs = pack_tiles(e, R, TP, Tpad)
                targs = tuple(put(x) for x in tarrs) + a
                note(f"  tiles T={T} (pad {Tpad}) R={R} TP={TP}")
                bench(mk_w2(R, TP, True), targs,
                      label=f"W2 count R={R} TP={TP} B={B}")
                bench(mk_w2(R, TP, False), targs,
                      label=f"W2 extr  R={R} TP={TP} B={B}")
            except Exception as ex:
                note(f"W2 R={R} TP={TP} B={B} failed: {type(ex).__name__} {str(ex)[:120]}")


if __name__ == "__main__":
    main()
