"""Interleaved repeats of the same kernels to expose tunnel/device noise,
plus per-op overhead inside one executable."""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]
    note(f"platform={dev.platform}")
    K, S = 64, 1_572_864
    F = jax.device_put(jnp.ones((K, S), jnp.bfloat16), dev)

    @jax.jit
    def mm(g):
        out = lax.dot_general(g, F, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
        return (out == 0.0).sum(dtype=jnp.int32)

    gs = {B: jax.device_put(jnp.ones((B, K), jnp.bfloat16), dev)
          for B in (1024, 8192)}
    for B, g in gs.items():
        np.asarray(mm(g))

    def run(B, iters=10):
        g = gs[B]
        t0 = time.perf_counter()
        acc = jnp.zeros((), jnp.int32)
        for _ in range(iters):
            acc = acc + mm(g)
        np.asarray(acc)
        return (time.perf_counter() - t0) / iters * 1e3

    for r in range(4):
        a = run(1024)
        b = run(8192)
        note(f"round {r}: B=1024 {a:.1f} ms  B=8192 {b:.1f} ms")

    # per-op overhead inside one executable: 256 chained scalar-ish ops
    x0 = jax.device_put(jnp.ones((8, 128), jnp.float32), dev)

    def chain(n):
        @jax.jit
        def f(x):
            for i in range(n):
                x = x * 1.0000001 + 0.0000001
            return x.sum()
        return f

    for n in (16, 256, 1024):
        f = chain(n)
        np.asarray(f(x0))
        t0 = time.perf_counter()
        acc = jnp.zeros((), jnp.float32)
        for _ in range(20):
            acc = acc + f(x0)
        np.asarray(acc)
        per = (time.perf_counter() - t0) / 20 * 1e3
        note(f"chain n={n}: {per:.2f} ms/exec ({per/n*1e3:.1f} us/op)")


if __name__ == "__main__":
    main()
