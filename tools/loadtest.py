"""Broker-level load test: real MQTT clients over TCP on localhost.

Measures end-to-end publish->deliver throughput through the full broker
path (parser -> session FSM -> reg view -> queue -> writer), the layer
above bench.py's kernel-level numbers. Two modes:

- single process (default): broker in-process, clients inline.
- ``--workers N``: spawns an N-process :class:`WorkerGroup` sharing one
  SO_REUSEPORT MQTT port (broker/workers.py), and shards the client
  load across ``--client-procs`` OS processes so the harness itself
  isn't the GIL bottleneck it is measuring around.

``--latency`` samples end-to-end publish->deliver latency (monotonic
clock is system-wide on Linux, so cross-process samples are
comparable) and reports p50/p99.

Usage:

  python tools/loadtest.py [--subs 50] [--pubs 8] [--secs 5]
      [--view trie|tpu] [--qos 0] [--window 32]
      [--workers 4] [--client-procs 4] [--latency]
"""
import argparse
import asyncio
import multiprocessing as mp
import socket
import struct
import sys
import time

sys.path.insert(0, "/root/repo")

_LAT_MAGIC = b"LT1"
_SAMPLE_EVERY = 16


def _now_ns() -> int:
    return time.monotonic_ns()


async def _run_clients(host: str, port: int, sub_ids, pub_ids, secs: float,
                       qos: int, window: int, payload_len: int,
                       latency: bool, tag: str, rate: float = 0.0,
                       lat_skip_secs: float = 0.0):
    """Drive one shard of subscribers+publishers; returns
    (sent, failed, received, elapsed, lat_samples_ns)."""
    from vernemq_tpu.client import MQTTClient

    received = 0
    lat_ns = []
    done = asyncio.Event()
    # samples before this cutoff are warmup (first-compile windows on a
    # cold backend) and excluded from the latency report
    lat_from = time.perf_counter() + lat_skip_secs

    async def subscriber(i: int) -> None:
        nonlocal received
        c = MQTTClient(host, port, f"lt-sub{tag}{i}")
        await c.connect()
        await c.subscribe(f"lt/{i % 16}/+", qos=qos)
        while not done.is_set():
            try:
                f = await c.recv(0.5)
            except Exception:
                continue
            if f is not None:
                received += 1
                if latency and f.payload[:3] == _LAT_MAGIC \
                        and time.perf_counter() >= lat_from:
                    t0 = struct.unpack(">Q", f.payload[3:11])[0]
                    lat_ns.append(_now_ns() - t0)
        await c.disconnect()

    sent = 0
    failed = 0

    async def publisher(i: int) -> None:
        nonlocal sent, failed
        c = MQTTClient(host, port, f"lt-pub{tag}{i}")
        await c.connect()
        base_payload = b"x" * payload_len
        j = 0
        inflight: set = set()

        def reap(f):
            inflight.discard(f)
            if not f.cancelled() and f.exception() is not None:
                nonlocal failed
                failed += 1  # acked count excludes this one

        interval = (1.0 / rate) if rate > 0 else 0.0
        next_at = time.perf_counter()
        while not done.is_set():
            if interval:
                # paced publishing: measures broker-ADDED latency, not
                # self-inflicted queueing from an uncapped firehose
                now = time.perf_counter()
                if now < next_at:
                    await asyncio.sleep(next_at - now)
                next_at += interval
            payload = base_payload
            if latency and j % _SAMPLE_EVERY == 0:
                stamp = _LAT_MAGIC + struct.pack(">Q", _now_ns())
                payload = stamp + base_payload[len(stamp):] \
                    if payload_len > len(stamp) else stamp
            if qos and window > 1:
                # pipelined QoS1: keep up to `window` unacked publishes
                # in flight (awaiting each PUBACK serialises the
                # publisher on broker RTT and measures the client, not
                # the broker — the reference's inflight-window behavior)
                fut = asyncio.ensure_future(
                    c.publish(f"lt/{j % 16}/m{tag}{i}", payload, qos=qos))
                inflight.add(fut)
                fut.add_done_callback(reap)
                if len(inflight) >= window:
                    await asyncio.wait(
                        inflight, return_when=asyncio.FIRST_COMPLETED)
            else:
                await c.publish(f"lt/{j % 16}/m{tag}{i}", payload, qos=qos)
            sent += 1
            j += 1
            if j % 64 == 0:
                await asyncio.sleep(0)  # let the loop breathe
        if inflight:
            await asyncio.gather(*inflight, return_exceptions=True)
        await c.disconnect()

    subs = [asyncio.create_task(subscriber(i)) for i in sub_ids]
    await asyncio.sleep(0.5)
    t0 = time.perf_counter()
    pubs = [asyncio.create_task(publisher(i)) for i in pub_ids]
    await asyncio.sleep(secs)
    done.set()
    elapsed = time.perf_counter() - t0
    await asyncio.gather(*pubs, *subs, return_exceptions=True)
    return sent, failed, received, elapsed, lat_ns


def _client_proc(host, port, sub_ids, pub_ids, secs, qos, window,
                 payload_len, latency, tag, out_q, rate=0.0,
                 lat_skip_secs=0.0):
    """Spawn-safe client-shard entry point."""
    res = asyncio.run(_run_clients(host, port, sub_ids, pub_ids, secs,
                                   qos, window, payload_len, latency, tag,
                                   rate, lat_skip_secs))
    out_q.put(res)


def _pctile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _report(view, qos, sent, failed, received, elapsed, lat_ns, subs,
            pubs, workers):
    acked = sent - failed
    line = (f"view={view} qos={qos} workers={workers} "
            f"pubs/s={acked/elapsed:.0f} "
            f"deliveries/s={received/elapsed:.0f} "
            f"(subscribers={subs}, publishers={pubs}"
            + (f", failed={failed}" if failed else "") + ")")
    if lat_ns:
        lat = sorted(lat_ns)
        line += (f" latency_ms p50={_pctile(lat, 0.50)/1e6:.2f}"
                 f" p99={_pctile(lat, 0.99)/1e6:.2f}"
                 f" (n={len(lat)})")
    print(line, flush=True)


async def _main_inproc(args) -> None:
    if args.view == "tpu":
        import jax  # noqa: F401  (matcher path needs a backend)

        if args.jax_platform:
            # this image's jax IGNORES the JAX_PLATFORMS env var; only
            # the config API works. Forcing cpu keeps --view tpu usable
            # when the accelerator tunnel is down.
            jax.config.update("jax_platforms", args.jax_platform)

    from vernemq_tpu.broker.config import Config
    from vernemq_tpu.broker.server import start_broker

    b, server = await start_broker(
        Config(systree_enabled=False, allow_anonymous=True,
               default_reg_view=args.view, sysmon_enabled=False),
        port=0)
    sent, failed, received, elapsed, lat = await _run_clients(
        server.host, server.port, range(args.subs), range(args.pubs),
        args.secs, args.qos, args.window, args.payload, args.latency, "",
        args.rate, args.lat_skip_secs)
    if args.view == "tpu" and getattr(b, "_collector", None) is not None:
        col = b._collector
        mb = sum(m.match_batches
                 for m in getattr(col.view, "_matchers", {}).values())
        mp_ = sum(m.match_publishes
                  for m in getattr(col.view, "_matchers", {}).values())
        print(f"collector: host_hybrid_pubs={col.host_hybrid_pubs} "
              f"device_batches={mb} device_pubs={mp_} "
              f"merges={col.saturated_merges} "
              f"shed={col.overload_host_pubs} "
              f"busy_shed={col.busy_host_pubs} "
              f"rebuild_shed={col.rebuild_host_pubs}", flush=True)
    await b.stop()
    await server.stop()
    _report(args.view, args.qos, sent, failed, received, elapsed, lat,
            args.subs, args.pubs, 0)


def _main_workers(args) -> None:
    import os

    from vernemq_tpu.broker.workers import WorkerGroup

    if args.jax_platform:
        # worker processes and their probe subprocesses read this env
        # var (workers translate it via jax.config at boot)
        os.environ["JAX_PLATFORMS"] = args.jax_platform

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    group = WorkerGroup(args.workers, "127.0.0.1", port,
                        cluster_base=args.cluster_base,
                        allow_anonymous=True, systree_enabled=False,
                        sysmon_enabled=False,
                        default_reg_view=args.view)
    group.start()
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), 0.5).close()
                break
            except OSError:
                time.sleep(0.3)
        else:
            raise RuntimeError("workers never became reachable")
        # give the worker mesh a moment to form before subscribing
        time.sleep(1.5)
        nproc = args.client_procs or args.workers
        ctx = mp.get_context("spawn")
        out_q = ctx.Queue()
        procs = []
        for p in range(nproc):
            sub_ids = [i for i in range(args.subs) if i % nproc == p]
            pub_ids = [i for i in range(args.pubs) if i % nproc == p]
            procs.append(ctx.Process(
                target=_client_proc,
                args=("127.0.0.1", port, sub_ids, pub_ids, args.secs,
                      args.qos, args.window, args.payload, args.latency,
                      f"p{p}-", out_q, args.rate,
                      args.lat_skip_secs)))
        for p in procs:
            p.start()
        totals = [0, 0, 0, 0.0]
        lat_all = []
        import queue as _queue

        shards_ok = 0
        try:
            for _ in procs:
                sent, failed, received, elapsed, lat = out_q.get(
                    timeout=args.secs + 120)
                totals[0] += sent
                totals[1] += failed
                totals[2] += received
                totals[3] = max(totals[3], elapsed)
                lat_all.extend(lat)
                shards_ok += 1
        except _queue.Empty:
            print(f"WARNING: only {shards_ok}/{len(procs)} client shards "
                  "reported (crashed shard?); partial numbers below",
                  file=sys.stderr, flush=True)
        finally:
            for p in procs:
                p.join(5)
                if p.is_alive():
                    p.terminate()
                    p.join(5)
        if totals[3] > 0:
            _report(args.view, args.qos, totals[0], totals[1], totals[2],
                    totals[3], lat_all, args.subs, args.pubs, args.workers)
    finally:
        group.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--subs", type=int, default=50)
    ap.add_argument("--pubs", type=int, default=8)
    ap.add_argument("--secs", type=float, default=5.0)
    ap.add_argument("--qos", type=int, default=0)
    ap.add_argument("--view", default="trie")
    ap.add_argument("--payload", type=int, default=64)
    ap.add_argument("--window", type=int, default=1,
                    help="pipelined unacked publishes per publisher "
                         "(QoS>0; 1 = await each ack)")
    ap.add_argument("--workers", type=int, default=0,
                    help="run the broker as N SO_REUSEPORT worker "
                         "processes (0 = in-process single broker)")
    ap.add_argument("--client-procs", type=int, default=0,
                    help="client shard processes (default: = workers)")
    ap.add_argument("--cluster-base", type=int, default=25600)
    ap.add_argument("--latency", action="store_true",
                    help="sample end-to-end delivery latency")
    ap.add_argument("--jax-platform", default=None,
                    help="force the JAX backend for --view tpu (e.g. "
                         "cpu); jax.config only — env vars are ignored "
                         "by this image's jax")
    ap.add_argument("--lat-skip-secs", type=float, default=0.0,
                    help="exclude latency samples from the first N "
                         "seconds (cold-backend compile warmup)")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="paced publishes/sec per publisher (0 = "
                         "uncapped firehose)")
    args = ap.parse_args()
    if args.workers:
        _main_workers(args)
    else:
        asyncio.run(_main_inproc(args))


if __name__ == "__main__":
    main()
