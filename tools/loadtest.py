"""Broker-level load test: real MQTT clients over TCP on localhost.

Measures end-to-end publish->deliver throughput through the full broker
path (parser -> session FSM -> reg view -> queue -> writer), the layer
above bench.py's kernel-level numbers. Usage:

  python tools/loadtest.py [--subs 50] [--pubs 8] [--secs 5]
      [--view trie|tpu] [--qos 0]
"""
import argparse
import asyncio
import sys
import time

sys.path.insert(0, "/root/repo")


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--subs", type=int, default=50)
    ap.add_argument("--pubs", type=int, default=8)
    ap.add_argument("--secs", type=float, default=5.0)
    ap.add_argument("--qos", type=int, default=0)
    ap.add_argument("--view", default="trie")
    ap.add_argument("--payload", type=int, default=64)
    ap.add_argument("--window", type=int, default=1,
                    help="pipelined unacked publishes per publisher "
                         "(QoS>0; 1 = await each ack)")
    args = ap.parse_args()

    if args.view == "tpu":
        import jax  # noqa: F401  (matcher path needs a backend)

    from vernemq_tpu.broker.config import Config
    from vernemq_tpu.broker.server import start_broker
    from vernemq_tpu.client import MQTTClient

    b, server = await start_broker(
        Config(systree_enabled=False, allow_anonymous=True,
               default_reg_view=args.view, sysmon_enabled=False),
        port=0)
    received = 0
    done = asyncio.Event()

    async def subscriber(i: int) -> None:
        nonlocal received
        c = MQTTClient(server.host, server.port, f"lt-sub{i}")
        await c.connect()
        await c.subscribe(f"lt/{i % 16}/+", qos=args.qos)
        while not done.is_set():
            try:
                f = await c.recv(0.5)
            except Exception:
                continue
            if f is not None:
                received += 1
        await c.disconnect()

    sent = 0
    failed = 0

    async def publisher(i: int) -> None:
        nonlocal sent, failed
        c = MQTTClient(server.host, server.port, f"lt-pub{i}")
        await c.connect()
        payload = b"x" * args.payload
        j = 0
        inflight: set = set()

        def reap(f):
            inflight.discard(f)
            if not f.cancelled() and f.exception() is not None:
                nonlocal failed
                failed += 1  # acked count excludes this one

        while not done.is_set():
            if args.qos and args.window > 1:
                # pipelined QoS1: keep up to `window` unacked publishes
                # in flight (awaiting each PUBACK serialises the
                # publisher on broker RTT and measures the client, not
                # the broker — the reference's inflight-window behavior)
                fut = asyncio.ensure_future(
                    c.publish(f"lt/{j % 16}/m{i}", payload, qos=args.qos))
                inflight.add(fut)
                fut.add_done_callback(reap)
                if len(inflight) >= args.window:
                    await asyncio.wait(
                        inflight, return_when=asyncio.FIRST_COMPLETED)
            else:
                await c.publish(f"lt/{j % 16}/m{i}", payload, qos=args.qos)
            sent += 1
            j += 1
            if j % 64 == 0:
                await asyncio.sleep(0)  # let the loop breathe
        if inflight:
            await asyncio.gather(*inflight, return_exceptions=True)
        await c.disconnect()

    subs = [asyncio.create_task(subscriber(i)) for i in range(args.subs)]
    await asyncio.sleep(0.5)
    t0 = time.perf_counter()
    pubs = [asyncio.create_task(publisher(i)) for i in range(args.pubs)]
    await asyncio.sleep(args.secs)
    done.set()
    elapsed = time.perf_counter() - t0
    await asyncio.gather(*pubs, *subs, return_exceptions=True)
    await b.stop()
    await server.stop()
    # each publish matches subs/16 subscribers on its topic bucket
    acked = sent - failed
    print(f"view={args.view} qos={args.qos} pubs/s={acked/elapsed:.0f} "
          f"deliveries/s={received/elapsed:.0f} "
          f"(subscribers={args.subs}, publishers={args.pubs}"
          + (f", failed={failed}" if failed else "") + ")")


if __name__ == "__main__":
    asyncio.run(main())
