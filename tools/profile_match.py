"""Phase-by-phase profile of the production match path on the real chip.

Answers VERDICT r2 weak-1: where do the ~39ms/batch go at B=1024, 1M subs?
Phases measured independently (each amortized over many iters, one
checksum pull at the end — the axon tunnel's ~65ms RTT stays out of the
steady-state numbers):

  A. pure device: bucketed kernel on device-resident inputs
  B. device + per-batch transfers (the 9 device_puts submit() does today)
  C. host encode (encode_topic_ex loop)
  D. host tile prep (prepare_tiles)
  E. full-scan MXU kernel on device-resident inputs (for comparison)
  F. resolve: host mapping of idx/valid arrays back to entries
"""

from __future__ import annotations

import random
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from bench import build_corpus, zipf_topics
    from vernemq_tpu.models.tpu_matcher import prepare_tiles
    from vernemq_tpu.models.tpu_table import SubscriptionTable
    from vernemq_tpu.ops import match_kernel as K

    subs = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    iters = 30

    rng = random.Random(42)
    table = SubscriptionTable(max_levels=8,
                              initial_capacity=1 << (subs - 1).bit_length())
    t0 = time.perf_counter()
    pools = build_corpus(rng, subs, table)
    note(f"corpus {time.perf_counter()-t0:.1f}s")

    dev = jax.devices()[0]
    note(f"platform={dev.platform}")
    put = lambda a: jax.device_put(a, dev)
    arrays = (put(table.words), put(table.eff_len), put(table.has_hash),
              put(table.first_wild), put(table.active))
    bits = table.id_bits
    operands = K.build_operands(arrays[0], arrays[1], bits)
    S = arrays[0].shape[0]
    reg_start = table.reg_start.copy()
    reg_end = (table.reg_start + table.reg_cap).copy()
    glob_pad = int(table.reg_cap[0])
    note(f"S={S} NB={table.NB} glob_pad={glob_pad} bits={bits}")

    def encode(topics):
        n, L = len(topics), table.L
        pw = np.full((n, L), K.PAD_ID, dtype=np.int32)
        pl = np.zeros(n, dtype=np.int32)
        pd = np.zeros(n, dtype=bool)
        pb = np.zeros(n, dtype=np.int32)
        for i, t in enumerate(topics):
            row, ln, dollar, bucket = table.encode_topic_ex(t)
            pw[i], pl[i], pd[i], pb[i] = row, ln, dollar, bucket
        return pw, pl, pd, pb

    topic_batches = [zipf_topics(rng, pools, B) for _ in range(8)]

    # C. host encode
    t0 = time.perf_counter()
    enc = [encode(tb) for tb in topic_batches]
    enc_ms = (time.perf_counter() - t0) / len(topic_batches) * 1e3
    note(f"C host encode: {enc_ms:.2f} ms/batch")

    # D. host tile prep
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        tiles = [prepare_tiles(pw, pl, pd, pb, pw.shape[0], reg_start,
                               reg_end, glob_pad, S)
                 for (pw, pl, pd, pb) in enc]
    prep_ms = (time.perf_counter() - t0) / (len(enc) * reps) * 1e3
    tcounts = [t[0].shape[0] for t in tiles]
    segs = sorted({t[8] for t in tiles})
    note(f"D host prepare_tiles: {prep_ms:.2f} ms/batch; tile counts "
         f"{sorted(set(tcounts))}; seg_max {segs}")

    # device-resident input sets (A)
    dev_in = []
    for (pw, pl, pd, pb), t in zip(enc, tiles):
        t_pw, t_pl, t_pd, t_start, t_lo, t_len, _, _, seg_max = t
        dev_in.append((put(pw), put(pl), put(pd), put(t_pw), put(t_pl),
                       put(t_pd), put(t_start), put(t_lo), put(t_len),
                       seg_max))
    F_t, t1 = operands

    def run_dev(di):
        (pw, pl, pd, t_pw, t_pl, t_pd, t_start, t_lo, t_len, seg_max) = di
        g1, g2, gc, x1, x2, tc = K.match_extract_bucketed(
            F_t, t1, arrays[1], arrays[2], arrays[3], arrays[4],
            pw, pl, pd, t_pw, t_pl, t_pd, t_start, t_lo, t_len,
            id_bits=bits, k=256, glob_pad=glob_pad, seg_max=seg_max)
        return gc.sum() + tc.sum()

    # warmup/compile all shapes
    for di in dev_in:
        np.asarray(run_dev(di))
    note("compiled A")

    t0 = time.perf_counter()
    acc = jnp.zeros((), jnp.int32)
    for i in range(iters):
        acc = acc + run_dev(dev_in[i % len(dev_in)])
    np.asarray(acc)
    a_ms = (time.perf_counter() - t0) / iters * 1e3
    note(f"A pure device bucketed: {a_ms:.2f} ms/batch")

    # B. with per-batch transfers (prepared host arrays, as submit() does)
    host_in = [(pw, pl, pd) + t[:6] + (t[8],)
               for (pw, pl, pd, pb), t in zip(enc, tiles)]

    def run_put(hi):
        (pw, pl, pd, t_pw, t_pl, t_pd, t_start, t_lo, t_len, seg_max) = hi
        return run_dev((put(pw), put(pl), put(pd), put(t_pw), put(t_pl),
                        put(t_pd), put(t_start), put(t_lo), put(t_len),
                        seg_max))

    np.asarray(run_put(host_in[0]))
    t0 = time.perf_counter()
    acc = jnp.zeros((), jnp.int32)
    for i in range(iters):
        acc = acc + run_put(host_in[i % len(host_in)])
    np.asarray(acc)
    b_ms = (time.perf_counter() - t0) / iters * 1e3
    note(f"B device + per-batch puts: {b_ms:.2f} ms/batch "
         f"(transfer+dispatch overhead {b_ms - a_ms:.2f})")

    # E. full-scan MXU path
    pw0, pl0, pd0 = (put(enc[0][0]), put(enc[0][1]), put(enc[0][2]))
    def run_mxu(i):
        e = dev_in[i % len(dev_in)]
        out = K.match_extract_mxu(*arrays, e[0], e[1], e[2], k=256, chunk=0)
        return out[2].sum()
    np.asarray(run_mxu(0))
    t0 = time.perf_counter()
    acc = jnp.zeros((), jnp.int32)
    for i in range(iters):
        acc = acc + run_mxu(i)
    np.asarray(acc)
    e_ms = (time.perf_counter() - t0) / iters * 1e3
    note(f"E pure device full-scan MXU: {e_ms:.2f} ms/batch")

    # F. resolve cost: pull idx/valid and map to entries host-side
    di = dev_in[0]
    (pw, pl, pd, t_pw, t_pl, t_pd, t_start, t_lo, t_len, seg_max) = di
    out = K.match_extract_bucketed(
        F_t, t1, arrays[1], arrays[2], arrays[3], arrays[4],
        pw, pl, pd, t_pw, t_pl, t_pd, t_start, t_lo, t_len,
        id_bits=bits, k=256, glob_pad=glob_pad, seg_max=seg_max)
    host_out = [np.asarray(o) for o in out]
    gidx, gvalid, gcount, tidx, tvalid, tcount = host_out
    _, _, _, _, _, _, tile_of, pos_of, _ = tiles[0]
    entries = table.entries
    t0 = time.perf_counter()
    for _ in range(reps):
        res = []
        for i in range(B):
            ti, j = tile_of[i], pos_of[i]
            rows = [entries[s] for s in gidx[i][gvalid[i]]]
            rows += [entries[s] for s in tidx[ti, j][tvalid[ti, j]]]
            res.append(rows)
    f_ms = (time.perf_counter() - t0) / reps * 1e3
    nrows = sum(len(r) for r in res)
    note(f"F host resolve: {f_ms:.2f} ms/batch ({nrows} rows)")

    note(f"SUMMARY enc={enc_ms:.2f} prep={prep_ms:.2f} devA={a_ms:.2f} "
         f"dev+put={b_ms:.2f} mxu={e_ms:.2f} resolve={f_ms:.2f} ms")


if __name__ == "__main__":
    main()
