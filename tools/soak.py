"""Scale & soak: many concurrent connections + sustained QoS1 traffic,
with memory/latency stability sampling (VERDICT r3 item 6).

Opens a ladder of persistent connections (idle keepalive holders), runs
paced QoS1 traffic through a subscriber pool for the soak duration, and
samples broker RSS + delivery latency every ``--sample-every`` seconds.
Prints one JSON line per sample and a final summary line; non-flat RSS
growth or latency drift across samples is the failure signal.

  python tools/soak.py [--conns 2000] [--subs 100] [--pubs 8]
      [--minutes 10] [--rate 50] [--sample-every 10]
"""
import argparse
import asyncio
import json
import os
import struct
import sys
import time

sys.path.insert(0, "/root/repo")

_LAT_MAGIC = b"SK1"


def _rss_mb(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--conns", type=int, default=2000,
                    help="idle persistent connections held open")
    ap.add_argument("--subs", type=int, default=100)
    ap.add_argument("--pubs", type=int, default=8)
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--rate", type=float, default=50.0,
                    help="publishes/sec per publisher (paced)")
    ap.add_argument("--sample-every", type=float, default=10.0)
    ap.add_argument("--qos", type=int, default=1)
    ap.add_argument("--connect", default=None, metavar="HOST:PORT",
                    help="target an external broker (own process = own "
                         "fd budget; pass --broker-pid to sample its "
                         "RSS) instead of booting one in-process")
    ap.add_argument("--broker-pid", type=int, default=0,
                    help="pid whose RSS to sample with --connect")
    args = ap.parse_args()

    from vernemq_tpu.client import MQTTClient

    b = server = None
    if args.connect:
        host, _, port_s = args.connect.rpartition(":")
        port = int(port_s)
        pid = args.broker_pid or os.getpid()
    else:
        from vernemq_tpu.broker.config import Config
        from vernemq_tpu.broker.server import start_broker

        b, server = await start_broker(
            Config(systree_enabled=False, allow_anonymous=True,
                   sysmon_enabled=False),
            port=0)
        host, port = server.host, server.port
        pid = os.getpid()

    # ---- connection ladder -------------------------------------------
    idle = []
    t0 = time.perf_counter()
    failed_conns = 0
    for i in range(args.conns):
        c = MQTTClient(host, port, f"soak-idle{i}", keepalive=120)
        try:
            ack = await c.connect(timeout=10.0)
            if ack.rc == 0:
                idle.append(c)
            else:
                failed_conns += 1
        except Exception:
            failed_conns += 1
        if i and i % 500 == 0:
            print(json.dumps({"event": "ladder", "conns": len(idle),
                              "rss_mb": round(_rss_mb(pid), 1),
                              "t_s": round(time.perf_counter() - t0, 1)}),
                  flush=True)
    print(json.dumps({"event": "ladder_done", "conns": len(idle),
                      "failed": failed_conns,
                      "rss_mb": round(_rss_mb(pid), 1),
                      "t_s": round(time.perf_counter() - t0, 1)}),
          flush=True)

    # ---- sustained traffic -------------------------------------------
    done = asyncio.Event()
    received = 0
    lat_window = []  # ns, cleared each sample

    async def subscriber(i: int) -> None:
        nonlocal received
        c = MQTTClient(host, port, f"soak-sub{i}")
        await c.connect()
        await c.subscribe(f"soak/{i % 16}/+", qos=args.qos)
        while not done.is_set():
            try:
                f = await c.recv(0.5)
            except Exception:
                continue
            if f is not None:
                received += 1
                if f.payload[:3] == _LAT_MAGIC:
                    t_pub = struct.unpack(">Q", f.payload[3:11])[0]
                    lat_window.append(time.monotonic_ns() - t_pub)
        await c.disconnect()

    sent = 0
    failed = 0

    async def publisher(i: int) -> None:
        nonlocal sent, failed
        c = MQTTClient(host, port, f"soak-pub{i}")
        await c.connect()
        interval = 1.0 / args.rate if args.rate > 0 else 0.0
        nxt = time.perf_counter()
        j = 0
        while not done.is_set():
            if interval:
                now = time.perf_counter()
                if now < nxt:
                    await asyncio.sleep(nxt - now)
                nxt += interval
            payload = _LAT_MAGIC + struct.pack(">Q", time.monotonic_ns()) \
                + b"x" * 53
            try:
                await c.publish(f"soak/{j % 16}/m{i}", payload,
                                qos=args.qos)
                sent += 1
            except Exception:
                failed += 1
            j += 1
        await c.disconnect()

    subs = [asyncio.create_task(subscriber(i)) for i in range(args.subs)]
    await asyncio.sleep(1.0)
    pubs = [asyncio.create_task(publisher(i)) for i in range(args.pubs)]

    deadline = time.perf_counter() + args.minutes * 60.0
    samples = []
    while time.perf_counter() < deadline:
        await asyncio.sleep(args.sample_every)
        lat = sorted(lat_window)
        lat_window.clear()
        p50 = lat[len(lat) // 2] / 1e6 if lat else 0.0
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))] / 1e6 \
            if lat else 0.0
        sample = {"event": "sample",
                  "t_s": round(time.perf_counter() - t0, 1),
                  "rss_mb": round(_rss_mb(pid), 1),
                  "sent": sent, "received": received, "failed": failed,
                  "lat_ms_p50": round(p50, 2), "lat_ms_p99": round(p99, 2),
                  "n_lat": len(lat)}
        samples.append(sample)
        print(json.dumps(sample), flush=True)
    done.set()
    await asyncio.gather(*pubs, *subs, return_exceptions=True)
    for c in idle:
        try:
            await c.disconnect()
        except Exception:
            pass
    if b is not None:
        await b.stop()
        await server.stop()

    rss = [s["rss_mb"] for s in samples]
    p99s = [s["lat_ms_p99"] for s in samples if s["n_lat"]]
    half = max(1, len(p99s) // 2)
    summary = {
        "event": "summary",
        "conns": len(idle), "failed_conns": failed_conns,
        "minutes": args.minutes, "sent": sent, "received": received,
        "pub_failures": failed,
        "rss_mb_first": rss[0] if rss else 0,
        "rss_mb_last": rss[-1] if rss else 0,
        "rss_growth_pct": round(100 * (rss[-1] - rss[0]) /
                                max(rss[0], 1), 1) if rss else 0,
        "lat_p99_first_half_ms": round(sum(p99s[:half]) / half, 2)
        if p99s else 0,
        "lat_p99_second_half_ms": round(sum(p99s[half:]) /
                                        max(1, len(p99s) - half), 2)
        if p99s else 0,
    }
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    asyncio.run(main())
