"""Validate the K4 production-kernel shape at 1M subs on the real chip.

K4 = one jitted call per batch:
  - global phase: unrolled pub-chunks of <=1024 x region-0 matmul
    + pack + extract (bounds the [Bc, glob] f32 intermediate)
  - tile phase: static T tiles of TP bucket-sorted pubs, each matching a
    traced-start dynamic_slice window of seg_max rows (unrolled, no
    lax.map, no gathers of F)
Also re-times the EXISTING match_extract_bucketed steady-state for a fair
baseline (10 warm iters, single shape).
"""
import functools
import random
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bench import build_corpus, zipf_topics
    from vernemq_tpu.models.tpu_matcher import prepare_tiles
    from vernemq_tpu.models.tpu_table import SubscriptionTable
    from vernemq_tpu.ops import match_kernel as K

    subs = 1_000_000
    rng = random.Random(42)
    import pickle, os
    cache = f"/tmp/corpus_{subs}.pkl"
    t0 = time.perf_counter()
    if os.path.exists(cache):
        with open(cache, "rb") as fh:
            table, pools = pickle.load(fh)
    else:
        table = SubscriptionTable(max_levels=8,
                                  initial_capacity=1 << (subs - 1).bit_length())
        pools = build_corpus(rng, subs, table)
        with open(cache, "wb") as fh:
            pickle.dump((table, pools), fh)
    note(f"corpus {time.perf_counter()-t0:.1f}s")
    dev = jax.devices()[0]
    put = lambda a: jax.device_put(a, dev)
    arrays = (put(table.words), put(table.eff_len), put(table.has_hash),
              put(table.first_wild), put(table.active))
    bits = table.id_bits
    F_t, t1 = K.build_operands(arrays[0], arrays[1], bits)
    F_t = jax.block_until_ready(F_t)
    S = int(arrays[0].shape[0])
    glob = int(table.reg_cap[0])
    eff, hh, fw, act = arrays[1], arrays[2], arrays[3], arrays[4]
    note(f"platform={dev.platform} S={S} glob={glob} bits={bits}")
    reg_start = table.reg_start.copy()
    reg_end = (table.reg_start + table.reg_cap).copy()
    Kd = int(F_t.shape[0])

    def enc(B):
        topics = zipf_topics(rng, pools, B)
        pw = np.full((B, table.L), K.PAD_ID, dtype=np.int32)
        pl = np.zeros(B, dtype=np.int32)
        pd = np.zeros(B, dtype=bool)
        pb = np.zeros(B, dtype=np.int32)
        for i, t in enumerate(topics):
            row, n, dollar, b = table.encode_topic_ex(t)
            pw[i], pl[i], pd[i], pb[i] = row, n, dollar, b
        return pw, pl, pd, pb

    # ---------------- K4 host prep: static T tiles ----------------------
    def k4_tiles(pw, pl, pd, pb, T, seg_max):
        B = pw.shape[0]
        TP = B // T
        order = np.argsort(pb, kind="stable")
        t_pw = np.full((T, TP, table.L), np.int32(K.PAD_ID), np.int32)
        t_pl = np.zeros((T, TP), np.int32)
        t_pd = np.zeros((T, TP), bool)
        t_start = np.zeros(T, np.int32)
        leftovers = []
        for ti in range(T):
            sel = order[ti * TP:(ti + 1) * TP]
            lo = int(reg_start[pb[sel[0]]])
            start = min(lo, S - seg_max)
            keep = []
            for s in sel:
                if int(reg_end[pb[s]]) - start <= seg_max:
                    keep.append(s)
                else:
                    leftovers.append(s)
            m = len(keep)
            t_pw[ti, :m] = pw[keep]
            t_pl[ti, :m] = pl[keep]
            t_pd[ti, :m] = pd[keep]
            t_start[ti] = start
        return t_pw, t_pl, t_pd, t_start, leftovers

    def mk_k4(B, T, seg_max, GC, k=256, count_only=False):
        TP = B // T

        @jax.jit
        def k4(pw, pl, pd, t_pw, t_pl, t_pd, t_start):
            outs = []
            # global phase in GC-sized pub chunks (unrolled)
            for c in range(B // GC):
                sl = slice(c * GC, (c + 1) * GC)
                G = K.build_pub_operand(pw[sl], bits)
                mm = lax.dot_general(G, F_t[:, :glob], (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
                m = (mm + t1[None, :glob] == 0.0) & K._epilogue(
                    pl[sl], pd[sl], eff[:glob], hh[:glob], fw[:glob],
                    act[:glob])
                pk = K._pack_mask(m)
                if count_only:
                    outs.append(lax.population_count(pk).sum(dtype=jnp.int32))
                else:
                    outs.append(K.extract_indices_packed(pk, k, 2048))
            # tile phase (unrolled static T)
            touts = []
            for ti in range(T):
                Fseg = lax.dynamic_slice(F_t, (0, t_start[ti]), (Kd, seg_max))
                t1s = lax.dynamic_slice(t1, (t_start[ti],), (seg_max,))
                effs = lax.dynamic_slice(eff, (t_start[ti],), (seg_max,))
                hhs = lax.dynamic_slice(hh, (t_start[ti],), (seg_max,))
                fws = lax.dynamic_slice(fw, (t_start[ti],), (seg_max,))
                acts = lax.dynamic_slice(act, (t_start[ti],), (seg_max,))
                Gt = K.build_pub_operand(t_pw[ti], bits)
                mm = lax.dot_general(Gt, Fseg, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
                j = jnp.arange(seg_max, dtype=jnp.int32)
                rowok = j[None, :] >= glob - t_start[ti]  # never match region0 twice
                m = (mm + t1s[None, :] == 0.0) & K._epilogue(
                    t_pl[ti], t_pd[ti], effs, hhs, fws, acts) & rowok
                pk = K._pack_mask(m)
                if count_only:
                    touts.append(lax.population_count(pk).sum(dtype=jnp.int32))
                else:
                    i2, v2, c2 = K.extract_indices_packed(pk, k, 2048)
                    touts.append((i2 + t_start[ti], v2, c2))
            if count_only:
                return sum(outs) + sum(touts)
            return outs, touts
        return k4

    def bench(fn, args, iters=20, warm=8, label=""):
        for _ in range(warm):
            out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        t0 = time.perf_counter()
        accs = []
        for _ in range(iters):
            out = fn(*args)
            accs.append(jax.tree_util.tree_leaves(out)[0])
        acc = accs[0].sum()
        for a in accs[1:]:
            acc = acc + a.sum()
        np.asarray(acc)
        per = (time.perf_counter() - t0) / iters
        note(f"{label}: {per*1e3:.2f} ms/batch")
        return per

    import sys as _sys
    cfgs = {"small": ((1024, 4, 262144, 1024),),
            "big": ((8192, 16, 262144, 1024),),
            "mid": ((4096, 8, 262144, 1024),)}[_sys.argv[1] if len(_sys.argv) > 1 else "big"]
    for B, T, seg_max, GC in cfgs:
        e = enc(B)
        t_pw, t_pl, t_pd, t_start, left = k4_tiles(*e, T, seg_max)
        note(f"B={B} T={T} seg={seg_max}: leftovers={len(left)}")
        args = (put(e[0]), put(e[1]), put(e[2]),
                put(t_pw), put(t_pl), put(t_pd), put(t_start))
        try:
            bench(mk_k4(B, T, seg_max, GC, count_only=True), args,
                  label=f"K4 count B={B} T={T} seg={seg_max}")
            bench(mk_k4(B, T, seg_max, GC, count_only=False), args,
                  label=f"K4 extr  B={B} T={T} seg={seg_max}")
        except Exception as ex:
            note(f"K4 B={B} failed: {type(ex).__name__} {str(ex)[:150]}")

    # existing production kernel, steady-state, one shape
    B = 1024
    pw, pl, pd, pb = enc(B)
    (t_pw, t_pl, t_pd, t_s, t_lo, t_len, tile_of, pos_of,
     seg2) = prepare_tiles(pw, pl, pd, pb, B, reg_start, reg_end, glob, S)
    args2 = (F_t, t1, eff, hh, fw, act, put(pw), put(pl), put(pd),
             put(t_pw), put(t_pl), put(t_pd), put(t_s), put(t_lo), put(t_len))
    fn2 = functools.partial(K.match_extract_bucketed, id_bits=bits, k=256,
                            glob_pad=glob, seg_max=seg2)
    bench(lambda *a: fn2(*a)[2], args2, label=f"EXISTING bucketed B={B}")


if __name__ == "__main__":
    main()
