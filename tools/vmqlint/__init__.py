"""vmqlint — the broker's unified static-analysis suite.

One shared AST walk (per-file parse cache), a plugin-pass registry, and
one suppression idiom (``# vmqlint: allow(<pass>): <reason>``; the
legacy ``lint: allow-blocking`` / ``lint: observe-passthrough`` markers
keep working) over six passes:

==================  ====================================================
``blocking``         loop-blocking calls / unbounded waits in async
                     bodies (the old ``tools/lint_blocking.py``)
``metrics``          metric-registry HELP text + ``observe()`` family
                     names (the old ``tools/lint_metrics.py``)
``lock-discipline``  device transfers / compiles / sync IO lexically
                     under a ``threading`` lock, and ``await`` under
                     one — the PR 2/9/10 recurring defect class
``thread-lifecycle`` ``threading.Thread``/``Timer`` started by a class
                     with no join/cancel reachable from ``close()`` /
                     ``stop()``
``knob-registry``    every config read resolves to a ``DEFAULTS`` knob,
                     every schema alias targets one, and no knob is
                     declared but never read
``fault-registry``   every ``faults.inject*`` site and ``breaker
                     path=`` spelling matches the registered set
==================  ====================================================

Run ``python -m tools.vmqlint`` (the tier-1 pre-test gate), or
``--changed`` for a git-diff-scoped fast pass, ``--json`` for machine
output.  Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from .core import Finding, main, run  # noqa: F401
