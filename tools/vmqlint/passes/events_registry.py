"""``events-registry`` pass: journal emit sites and the code registry
agree.

The control-plane event journal (``observability/events.py``) has the
same drift hazard the fault-injection surface had before the
``fault-registry`` pass: a typo'd ``events.emit("braeker_open")`` site
raises at runtime only when the transition actually fires — i.e. during
the outage the journal exists to explain — and a ``KNOWN_EVENTS`` entry
with no emit site is a documented black-box signal that can never
appear (operators grep the timeline for it and conclude "this never
happened" when in truth it was never wired).

Checks (mirroring the fault-registry pass):

1. every ``events.emit(<code>, ...)`` call's first argument is a string
   literal naming a ``KNOWN_EVENTS`` entry;
2. every ``KNOWN_EVENTS`` entry has at least one emit site somewhere in
   the scan roots (sites inside ``observability/events.py`` itself —
   the module's own machinery — don't count, same as the faults file).

Only attribute calls whose receiver is spelled ``events`` / ``_events``
are treated as emit sites: ``emit`` is too common a bare name (the
filter engine has an ``emit`` hook) to match unqualified.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, Pass, const_str

_EVENTS_FILE = "vernemq_tpu/observability/events.py"


def _parse_registry(tree: ast.AST, errors: List[Finding]
                    ) -> Dict[str, int]:
    """``KNOWN_EVENTS`` as a dict literal of string keys -> line."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(isinstance(t, ast.Name) and t.id == "KNOWN_EVENTS"
                   for t in targets):
            continue
        val = node.value
        if not isinstance(val, ast.Dict):
            errors.append(Finding(
                PASS.name, _EVENTS_FILE, node.lineno,
                "KNOWN_EVENTS is not a dict literal — cannot verify"))
            continue
        for k in val.keys:
            s = const_str(k) if k is not None else None
            if s is None:
                errors.append(Finding(
                    PASS.name, _EVENTS_FILE,
                    getattr(k, "lineno", node.lineno),
                    "KNOWN_EVENTS key is not a string literal"))
            else:
                out[s] = k.lineno
    return out


def _emit_code(node: ast.Call) -> Optional[Tuple[Optional[str], int]]:
    """Is this an ``events.emit(...)`` site? -> (code literal or None,
    line)."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "emit"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("events", "_events")):
        return None
    if not node.args:
        return (None, node.lineno)
    return (const_str(node.args[0]), node.lineno)


class EventsRegistryPass(Pass):
    name = "events-registry"
    describe = ("events.emit sites match events.KNOWN_EVENTS and every "
                "registered code has an emit site")
    defect = ("a typo'd event code raises mid-outage (exactly when the "
              "journal must work); a site-less registry entry is a "
              "black-box signal that can never appear")
    tree_scoped = True
    roots = ("vernemq_tpu", "tools", "bench.py")

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        ef = ctx.get(_EVENTS_FILE)
        if ef is None or ef.tree is None:
            return [Finding(PASS.name, _EVENTS_FILE, 0,
                            "events module missing/unparseable")]
        codes = _parse_registry(ef.tree, findings)
        if not codes:
            findings.append(Finding(
                PASS.name, _EVENTS_FILE, 0,
                "KNOWN_EVENTS registry not found — every journal event "
                "code must be registered"))
        sites: Set[str] = set()
        for f in ctx.iter_files(self.roots, respect_changed=False):
            if f.tree is None or f.rel == _EVENTS_FILE:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                hit = _emit_code(node)
                if hit is None:
                    continue
                code, line = hit
                if code is None:
                    findings.append(Finding(
                        PASS.name, f.rel, line,
                        "events.emit code is not a string literal — "
                        "the site cannot be checked against "
                        "KNOWN_EVENTS"))
                    continue
                sites.add(code)
                if codes and code not in codes:
                    findings.append(Finding(
                        PASS.name, f.rel, line,
                        f"event code '{code}' is not in "
                        f"events.KNOWN_EVENTS — register it or fix "
                        f"the spelling"))
        for code, line in sorted(codes.items()):
            if code not in sites:
                findings.append(Finding(
                    PASS.name, _EVENTS_FILE, line,
                    f"KNOWN_EVENTS entry '{code}' has no events.emit "
                    f"site — a documented journal signal that can "
                    f"never appear"))
        return findings


PASS = EventsRegistryPass()
