"""``knob-registry`` pass: config reads, aliases and DEFAULTS agree.

Three drift modes this catches at lint time instead of at boot (or
never):

1. **Phantom reads** — ``cfg.get("tpu_breker_enabled", True)`` on the
   broker :class:`Config` silently serves the default forever (the
   two-arg form never raises), so a typo'd knob read is invisible until
   someone wonders why the conf file has no effect.  Every string-
   literal ``.get``/``.set`` on a *config-shaped* receiver must name a
   ``DEFAULTS`` entry.
2. **Dangling aliases** — every ``schema.py`` dotted-alias target
   (``FLAT_ALIASES``, including the dict-comprehension families), and
   every ``MS_TO_SECONDS``/``DURATION_KEYS`` entry, must resolve to a
   ``DEFAULTS`` knob or an alias key; a rename that misses schema.py
   breaks conf files at parse time.
3. **Dead knobs** — a ``DEFAULTS`` entry nothing in the package ever
   reads is documentation lying about a switch that does nothing.
   ``COMPAT_NOOPS`` entries are exempt by design (accepted-for-
   compatibility, explicitly no effect); anything else is a finding on
   its declaration line — fix it or annotate it there with
   ``# vmqlint: allow(knob-registry): <reason>``.

Config-shaped receivers are resolved by a per-scope taint walk: the
seeds are ``<anything>.config`` attributes, ``Config(...)`` /
``Config.from_file(...)`` / ``load_conf_file(...)`` calls,
``getattr(x, "config")``, and ``.snapshot()`` of a shaped value; plain
names become shaped by assignment from a seed (``cfg = self.config``)
or by a ``Config``-annotated parameter.  Unannotated dict parameters
named ``cfg`` are NOT shaped — the bridge/connector per-entry dicts
share the spelling.  Reads the taint walk cannot see (dynamic keys in
the conf loader) are simply not checked.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, Pass, const_str

_CONFIG_FILE = "vernemq_tpu/broker/config.py"
_SCHEMA_FILE = "vernemq_tpu/broker/schema.py"

#: Config's own attribute surface — not knob reads
_CONFIG_API = {"get", "set", "on_change", "snapshot", "from_file",
               "_values", "_listeners"}


_const_str = const_str  # shared literal probe (core.py)


# ------------------------------------------------------------- registries

def _parse_defaults(tree: ast.AST, rel: str,
                    errors: List[Finding]) -> Dict[str, int]:
    """DEFAULTS knob -> declaration line."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(isinstance(t, ast.Name) and t.id == "DEFAULTS"
                   for t in targets):
            continue
        if not isinstance(node.value, ast.Dict):
            errors.append(Finding(PASS.name, rel, node.lineno,
                                  "DEFAULTS is not a dict literal — "
                                  "cannot verify knob reads"))
            return out
        for k in node.value.keys:
            key = _const_str(k) if k is not None else None
            if key is None:
                errors.append(Finding(
                    PASS.name, rel, getattr(k, "lineno", node.lineno),
                    "DEFAULTS key is not a string literal"))
                continue
            if key in out:
                errors.append(Finding(PASS.name, rel, k.lineno,
                                      f"duplicate DEFAULTS knob "
                                      f"'{key}'"))
            out[key] = k.lineno
    return out


def _dict_pairs(node: ast.Dict) -> List[Tuple[Optional[str],
                                              Optional[str], int]]:
    out = []
    for k, v in zip(node.keys, node.values):
        out.append((_const_str(k) if k is not None else None,
                    _const_str(v), v.lineno))
    return out


def _comp_targets(node: ast.DictComp) -> List[Tuple[str, int]]:
    """The alias-family dict comprehensions map a derived dotted
    spelling to the knob name itself::

        {f"overload.{k[len('overload_'):]}": k for k in ("overload_mode",
         ...)}

    — the *values* iterated are the targets; anything fancier is
    reported as unverifiable by the caller."""
    if not (isinstance(node.value, ast.Name) and len(node.generators) == 1):
        return []
    gen = node.generators[0]
    if not (isinstance(gen.target, ast.Name)
            and gen.target.id == node.value.id
            and isinstance(gen.iter, (ast.Tuple, ast.List, ast.Set))):
        return []
    out = []
    for elt in gen.iter.elts:
        s = _const_str(elt)
        if s is not None:
            out.append((s, elt.lineno))
    return out


def _parse_schema(tree: ast.AST, rel: str, errors: List[Finding]
                  ) -> Tuple[List[Tuple[str, int]], Set[str],
                             List[Tuple[str, int]], Set[str]]:
    """-> (alias targets, alias keys, MS/DURATION entries, compat-noop
    schema names)."""
    targets: List[Tuple[str, int]] = []
    alias_keys: Set[str] = set()
    unit_keys: List[Tuple[str, int]] = []
    noops: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tlist = (node.targets if isinstance(node, ast.Assign)
                     else [node.target])
            names = {t.id for t in tlist if isinstance(t, ast.Name)}
            val = node.value
            if "FLAT_ALIASES" in names and isinstance(val, ast.Dict):
                for k, v, line in _dict_pairs(val):
                    if k is not None:
                        alias_keys.add(k)
                    if v is not None:
                        targets.append((v, line))
            elif names & {"MS_TO_SECONDS", "DURATION_KEYS"} \
                    and isinstance(val, (ast.Set, ast.Tuple, ast.List)):
                for elt in val.elts:
                    s = _const_str(elt)
                    if s is not None:
                        unit_keys.append((s, elt.lineno))
            elif "COMPAT_NOOPS" in names and isinstance(val, ast.Dict):
                for k, _v, _line in _dict_pairs(val):
                    if k is not None:
                        noops.add(k)
            # FLAT_ALIASES["x"] = "y"
            for t in tlist:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "FLAT_ALIASES"):
                    k = _const_str(t.slice)
                    v = _const_str(node.value)
                    if k is not None:
                        alias_keys.add(k)
                    if v is not None:
                        targets.append((v, node.lineno))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "update"
              and isinstance(node.func.value, ast.Name)
              and node.func.value.id == "FLAT_ALIASES" and node.args):
            arg = node.args[0]
            if isinstance(arg, ast.Dict):
                for k, v, line in _dict_pairs(arg):
                    if k is not None:
                        alias_keys.add(k)
                    if v is not None:
                        targets.append((v, line))
            elif isinstance(arg, ast.DictComp):
                found = _comp_targets(arg)
                if not found:
                    errors.append(Finding(
                        PASS.name, rel, arg.lineno,
                        "FLAT_ALIASES.update() with a comprehension "
                        "vmqlint cannot evaluate — use the "
                        "{f'tree.{k[...]}': k for k in (literals)} "
                        "shape"))
                targets.extend(found)
            else:
                errors.append(Finding(
                    PASS.name, rel, arg.lineno,
                    "FLAT_ALIASES.update() argument is not a literal "
                    "dict — alias targets cannot be verified"))
    return targets, alias_keys, unit_keys, noops


# ------------------------------------------------------------- taint walk

def _is_shaped(expr: ast.AST, shaped: Set[str]) -> bool:
    """Is this expression the broker Config (or its snapshot dict)?"""
    if isinstance(expr, ast.Attribute):
        if expr.attr == "config":
            return True
        return False
    if isinstance(expr, ast.Name):
        return expr.id in shaped
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name) and f.id in ("Config",
                                                "load_conf_file"):
            return True
        if isinstance(f, ast.Attribute):
            if f.attr == "from_file" and isinstance(f.value, ast.Name) \
                    and f.value.id == "Config":
                return True
            if f.attr == "snapshot" and _is_shaped(f.value, shaped):
                return True
        if (isinstance(f, ast.Name) and f.id == "getattr"
                and len(expr.args) >= 2
                and _const_str(expr.args[1]) == "config"):
            return True
    return False


class _ScopeWalker(ast.NodeVisitor):
    """Per-function taint of config-shaped names + knob-read harvest."""

    def __init__(self, rel: str, defaults: Dict[str, int],
                 findings: List[Finding], reads: Set[str],
                 shaped: Optional[Set[str]] = None):
        self.rel = rel
        self.defaults = defaults
        self.findings = findings
        self.reads = reads
        self.shaped: Set[str] = set(shaped or ())

    def _enter_function(self, node):
        inner = _ScopeWalker(self.rel, self.defaults, self.findings,
                             self.reads, self.shaped)
        for a in list(node.args.args) + list(node.args.kwonlyargs):
            ann = a.annotation
            ann_name = None
            if isinstance(ann, ast.Name):
                ann_name = ann.id
            elif isinstance(ann, ast.Constant):
                ann_name = str(ann.value)
            if ann_name == "Config":
                inner.shaped.add(a.arg)
        for child in node.body:
            inner.visit(child)

    def visit_FunctionDef(self, node):  # noqa: N802
        self._enter_function(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815

    def visit_Assign(self, node):  # noqa: N802
        shaped_val = _is_shaped(node.value, self.shaped)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if shaped_val:
                    self.shaped.add(tgt.id)
                else:
                    self.shaped.discard(tgt.id)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):  # noqa: N802
        # `cfg: Config = self.config` — trust the annotation like a
        # Config-annotated parameter, or the value like a plain assign
        if isinstance(node.target, ast.Name):
            ann = node.annotation
            ann_name = (ann.id if isinstance(ann, ast.Name)
                        else str(ann.value)
                        if isinstance(ann, ast.Constant) else None)
            if ann_name == "Config" or (
                    node.value is not None
                    and _is_shaped(node.value, self.shaped)):
                self.shaped.add(node.target.id)
            else:
                self.shaped.discard(node.target.id)
        self.generic_visit(node)

    def visit_Attribute(self, node):  # noqa: N802
        # knob read via attribute access (cfg.workers) counts as a read
        if (_is_shaped(node.value, self.shaped)
                and node.attr not in _CONFIG_API
                and node.attr in self.defaults):
            self.reads.add(node.attr)
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in ("get", "set")
                and node.args):
            key = _const_str(node.args[0])
            if key is not None and _is_shaped(f.value, self.shaped):
                if key not in self.defaults:
                    self.findings.append(Finding(
                        PASS.name, self.rel, node.lineno,
                        f"config.{f.attr}(\"{key}\") does not resolve "
                        f"to a DEFAULTS knob — a typo'd read silently "
                        f"serves its fallback forever"))
                elif f.attr == "get":
                    # only a GET on a config-shaped receiver is a read:
                    # .set is a write (a write-only knob is exactly the
                    # plumbed-never-consumed defect), and an unshaped
                    # receiver's .get("k") is some other dict that
                    # happens to share the spelling
                    self.reads.add(key)
        if (isinstance(f, ast.Name) and f.id == "getattr"
                and len(node.args) >= 2
                and _is_shaped(node.args[0], self.shaped)):
            key = _const_str(node.args[1])
            if key is not None and key in self.defaults:
                self.reads.add(key)
        self.generic_visit(node)


class KnobRegistryPass(Pass):
    name = "knob-registry"
    describe = ("config reads resolve to DEFAULTS; schema aliases "
                "target real knobs; no declared-but-never-read knobs")
    defect = ("a typo'd cfg.get silently serves its default; a dead "
              "DEFAULTS entry documents a switch that does nothing")
    tree_scoped = True

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        cfg = ctx.get(_CONFIG_FILE)
        if cfg is None or cfg.tree is None:
            return [Finding(PASS.name, _CONFIG_FILE, 0,
                            "DEFAULTS file missing/unparseable")]
        defaults = _parse_defaults(cfg.tree, _CONFIG_FILE, findings)
        schema = ctx.get(_SCHEMA_FILE)
        if schema is None or schema.tree is None:
            return [Finding(PASS.name, _SCHEMA_FILE, 0,
                            "schema file missing/unparseable")]
        targets, alias_keys, unit_keys, noops = _parse_schema(
            schema.tree, _SCHEMA_FILE, findings)
        for target, line in targets:
            if target not in defaults:
                findings.append(Finding(
                    PASS.name, _SCHEMA_FILE, line,
                    f"schema alias targets unknown knob '{target}' "
                    f"(not in DEFAULTS)"))
        for key, line in unit_keys:
            if key not in defaults and key not in alias_keys:
                findings.append(Finding(
                    PASS.name, _SCHEMA_FILE, line,
                    f"unit-conversion entry '{key}' is neither a "
                    f"DEFAULTS knob nor a schema alias"))
        reads: Set[str] = set()
        for f in ctx.iter_files(self.roots, respect_changed=False):
            if f.tree is None or f.rel == _CONFIG_FILE:
                continue
            w = _ScopeWalker(f.rel, defaults, findings, reads)
            w.visit(f.tree)
        for knob, line in sorted(defaults.items(),
                                 key=lambda kv: kv[1]):
            if knob in reads or knob in noops:
                continue
            findings.append(Finding(
                PASS.name, _CONFIG_FILE, line,
                f"knob '{knob}' is declared in DEFAULTS but never "
                f"read anywhere in the package — wire it up, delete "
                f"it, or annotate the declaration"))
        return findings


PASS = KnobRegistryPass()
