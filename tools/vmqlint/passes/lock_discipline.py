"""``lock-discipline`` pass: no device/compile/IO work under a lock.

The single most-recurring defect class in this repo's review-hardening
tails, shipped (and re-fixed) at least three times:

- PR 2: ``warm_delta_ladder`` compiled the delta-scatter ladder while
  holding the matcher lock — every publish parked behind a jit compile;
- PR 9: ``adopt_slices`` ran device work under the matcher lock from a
  gossip callback — a long device flush parked every session;
- PR 10: ``device_put`` uploads ran inside the filter-engine lock — a
  wedged transfer parked the event loop's ``_tick``/replay/status
  takers.

The cure is always the same shape: **snapshot under the lock, transfer/
compile outside it**.  This pass flags, lexically inside a ``with
<lock>:`` block (any context expression whose final name component ends
in ``lock``/``mutex``):

- device transfers/waits: ``device_put``, ``block_until_ready``,
  ``make_array_from_callback``, ``make_array_from_single_device_arrays``;
- compiles: ``jax.jit`` / ``pjit`` / ``warm_delta_ladder`` /
  ``ensure_warm*`` (each compiles on a cold shape);
- synchronous IO: bare ``open``, ``os.fsync``, ``time.sleep``, and
  journal writes (``append``/``write``/``delete``/``trim``/``flush``/
  ``sync``/``put`` on a receiver spelled ``*journal*``);
- ``await`` while holding a *threading* lock (a plain ``with`` in an
  ``async def``): the loop suspends the coroutine mid-critical-section
  and every thread blocking on that lock — and every session behind
  those threads — waits for the loop to resume it.

Nested function bodies are NOT flagged (they run later, elsewhere —
the background-rebuild closure pattern).  Deliberate sites (a
host-backed fake device in a test helper, a bounded metadata write)
opt out with ``# vmqlint: allow(lock-discipline): <reason>``.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from ..core import Context, Finding, Pass, SourceFile

#: a with-item guards a lock when its context expression's final name
#: component looks lock-shaped (self._lock, plan._lock, self.lock,
#: table_lock ...)
_LOCK_COMPONENT = re.compile(r"(?:^|_)(?:lock|mutex|rlock)$",
                             re.IGNORECASE)

#: final call-name components that are device transfers / waits
_DEVICE_CALLS = {"device_put", "block_until_ready",
                 "make_array_from_callback",
                 "make_array_from_single_device_arrays"}
#: final call-name components that compile (directly or on cold shapes)
_COMPILE_CALLS = {"jit", "pjit", "warm_delta_ladder"}
#: bare-name calls that are synchronous IO
_IO_NAMES = {"open", "input"}
#: (receiver, method) IO pairs
_IO_ATTRS = {("os", "fsync"), ("time", "sleep")}
#: journal-write method names (receiver must be spelled *journal*)
_JOURNAL_METHODS = {"append", "write", "delete", "trim", "flush",
                    "sync", "put"}


def _final_component(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _is_lock_item(item: ast.withitem) -> bool:
    comp = _final_component(item.context_expr)
    return comp is not None and bool(_LOCK_COMPONENT.search(comp))


def _call_parts(node: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(receiver final component or None, callee name)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return _final_component(f.value), f.attr
    if isinstance(f, ast.Name):
        return None, f.id
    return None, None


def _classify(node: ast.Call) -> Optional[str]:
    """Why this call must not run under a lock, or None."""
    recv, callee = _call_parts(node)
    if callee is None:
        return None
    if callee in _DEVICE_CALLS:
        return (f"device transfer/wait `{callee}(...)` under a lock — "
                f"snapshot under the lock, transfer outside it (the "
                f"PR 9 adopt_slices / PR 10 device_put defect class)")
    if callee in _COMPILE_CALLS or callee.startswith("ensure_warm"):
        return (f"compile `{callee}(...)` under a lock — every waiter "
                f"parks behind XLA (the PR 2 warm_delta_ladder defect "
                f"class); compile against throwaway arrays outside it")
    if recv is None and callee in _IO_NAMES:
        return (f"synchronous IO `{callee}(...)` under a lock")
    if (recv, callee) in _IO_ATTRS:
        return (f"synchronous `{recv}.{callee}(...)` under a lock — "
                f"every waiter stalls for its full duration")
    if (callee in _JOURNAL_METHODS and recv is not None
            and "journal" in recv.lower()):
        return (f"journal write `{recv}.{callee}(...)` under a lock — "
                f"journal IO belongs outside the critical section")
    return None


class _FunctionVisitor(ast.NodeVisitor):
    """Walk one function body tracking how many lock-shaped ``with``
    blocks enclose the current node.  Nested function definitions are
    skipped — their bodies execute later, not under the lock."""

    def __init__(self, findings: List[Finding], rel: str):
        self.findings = findings
        self.rel = rel
        self.lock_depth = 0

    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        pass

    def visit_Lambda(self, node):  # noqa: N802
        pass

    def visit_With(self, node):  # noqa: N802
        # items evaluate left-to-right, each under whatever locks the
        # earlier items acquired — so `with self._lock, open(p) as fh:`
        # opens the file WITH the lock held, and a nested
        # `with open(p):` body-statement is just as visible as the
        # assignment spelling
        entered = 0
        for item in node.items:
            self.visit(item.context_expr)
            if _is_lock_item(item):
                self.lock_depth += 1
                entered += 1
        for child in node.body:
            self.visit(child)
        self.lock_depth -= entered

    def visit_Await(self, node):  # noqa: N802
        if self.lock_depth:
            self.findings.append(Finding(
                PASS.name, self.rel, node.lineno,
                "await while holding a threading lock — the coroutine "
                "suspends mid-critical-section and every thread (and "
                "session) behind the lock waits for the loop to resume "
                "it; release first or use asyncio.Lock"))
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        if self.lock_depth:
            why = _classify(node)
            if why:
                self.findings.append(
                    Finding(PASS.name, self.rel, node.lineno, why))
        self.generic_visit(node)


class LockDisciplinePass(Pass):
    name = "lock-discipline"
    describe = ("device transfers, compiles, sync IO and awaits inside "
                "`with <lock>` blocks")
    defect = ("work that can wedge or take seconds runs inside a "
              "threading critical section — every waiter (often the "
              "event loop) parks behind it")

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for f in ctx.iter_files(self.roots):
            self._scan(f, findings)
        return findings

    @staticmethod
    def _scan(f: SourceFile, findings: List[Finding]) -> None:
        if f.tree is None:
            return
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                v = _FunctionVisitor(findings, f.rel)
                for child in node.body:
                    v.visit(child)


PASS = LockDisciplinePass()
