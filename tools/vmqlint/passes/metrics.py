"""``metrics`` pass: metric-registry HELP + observe() family names.

Port of the original ``tools/lint_metrics.py`` (PR 8) onto the vmqlint
framework.  Two invariants, both cheap to break silently and annoying
to debug at scrape time:

1. Every registered metric has non-empty HELP text: the ``COUNTERS``
   table (broker/metrics.py), the ``STAGE_FAMILIES`` histogram table
   (observability/histogram.py), and every literal descriptions dict
   passed to ``Metrics.register_gauges``.
2. Every ``observe("name", ...)`` call site names a REGISTERED
   histogram family — a typo'd family raises KeyError on the hot path,
   in production, at the first sampled publish, instead of here.

Suppress a delegation seam (Metrics.observe -> histogram.observe
forwards a dynamic name by design) with the vmqlint allow marker
naming this pass and its reason, or the legacy
``# lint: observe-passthrough``.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import Context, Finding, Pass, const_str

_COUNTERS_FILE = "vernemq_tpu/broker/metrics.py"
_HIST_FILE = "vernemq_tpu/observability/histogram.py"

_const_str = const_str  # shared literal probe (core.py)


def _tuple_table(tree: ast.AST, name: str, rel: str,
                 errors: List[Finding], what: str) -> Set[str]:
    """Collect (name, help) 2-tuple tables like COUNTERS /
    STAGE_FAMILIES; flag entries with empty or non-literal HELP."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in targets):
            continue
        value = node.value
        if not isinstance(value, (ast.List, ast.Tuple)):
            continue
        for elt in value.elts:
            if not isinstance(elt, ast.Tuple) or len(elt.elts) < 2:
                errors.append(Finding(
                    PASS.name, rel, elt.lineno,
                    f"{what} entry is not a (name, help) tuple"))
                continue
            metric = _const_str(elt.elts[0])
            # help may be an implicit concat of string constants — the
            # parser folds adjacent literals into one Constant, so a
            # plain _const_str covers the multi-line style used here
            help_text = _const_str(elt.elts[1])
            if metric is None:
                errors.append(Finding(
                    PASS.name, rel, elt.lineno,
                    f"{what} name is not a string literal"))
                continue
            names.add(metric)
            if not help_text or not help_text.strip():
                errors.append(Finding(
                    PASS.name, rel, elt.lineno,
                    f"{what} '{metric}' has empty HELP text"))
    return names


def _check_gauge_dicts(tree: ast.AST, rel: str,
                       errors: List[Finding]) -> None:
    """Every literal dict passed to register_gauges(...) must have
    non-empty string values (the HELP text of each gauge)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr == "register_gauges"):
            continue
        cands = list(node.args[1:2]) + [
            kw.value for kw in node.keywords
            if kw.arg == "descriptions"]
        for d in cands:
            if not isinstance(d, ast.Dict):
                continue  # dynamic dict: parity tests cover those names
            for k, v in zip(d.keys, d.values):
                key = _const_str(k) if k is not None else None
                val = _const_str(v)
                if key is None:
                    continue
                if not val or not val.strip():
                    errors.append(Finding(
                        PASS.name, rel, v.lineno,
                        f"gauge '{key}' registered with empty HELP "
                        f"text"))


def _check_observe_sites(tree: ast.AST, rel: str, families: Set[str],
                         errors: List[Finding]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # exact-name match: observe_lag and other observe-ish
            # methods fall out here without needing an exempt list
            if fn.attr != "observe":
                continue
        elif isinstance(fn, ast.Name):
            if fn.id != "observe":
                continue
        else:
            continue
        fam = _const_str(node.args[0])
        if fam is None:
            errors.append(Finding(
                PASS.name, rel, node.lineno,
                "observe() family is not a string literal (cannot "
                "verify registration statically)"))
        elif fam not in families:
            errors.append(Finding(
                PASS.name, rel, node.lineno,
                f"observe() names unregistered histogram family "
                f"'{fam}'"))


class MetricsPass(Pass):
    name = "metrics"
    describe = ("every counter/gauge/histogram has HELP text; every "
                "observe() names a registered family")
    defect = ("an empty HELP ships a broken exposition line; a typo'd "
              "family KeyErrors on the hot path under load")
    tree_scoped = True  # the family registry lives in two fixed files

    def run(self, ctx: Context) -> List[Finding]:
        errors: List[Finding] = []
        counters = ctx.get(_COUNTERS_FILE)
        if counters is None or counters.tree is None:
            return [Finding(PASS.name, _COUNTERS_FILE, 0,
                            "COUNTERS table file missing/unparseable")]
        _tuple_table(counters.tree, "COUNTERS", _COUNTERS_FILE, errors,
                     "counter")
        hist = ctx.get(_HIST_FILE)
        if hist is None or hist.tree is None:
            return [Finding(PASS.name, _HIST_FILE, 0,
                            "STAGE_FAMILIES file missing/unparseable")]
        families = _tuple_table(hist.tree, "STAGE_FAMILIES", _HIST_FILE,
                                errors, "histogram")
        if not families:
            errors.append(Finding(PASS.name, _HIST_FILE, 0,
                                  "STAGE_FAMILIES table not found"))
        for f in ctx.iter_files(self.roots, respect_changed=False):
            if f.tree is None:
                continue
            _check_gauge_dicts(f.tree, f.rel, errors)
            _check_observe_sites(f.tree, f.rel, families, errors)
        return errors


PASS = MetricsPass()
