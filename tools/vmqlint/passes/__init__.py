"""Pass registry: one entry per defect class the suite encodes.

Each pass module exports a ``PASS`` instance; adding a pass = adding a
module here.  Keep the list ordered cheapest-first so a syntax-level
failure surfaces before the registry diffs."""

from __future__ import annotations

from typing import List

from ..core import Pass


def all_passes() -> List[Pass]:
    from . import (blocking, events_registry, fault_registry,
                   knob_registry, lock_discipline, metrics,
                   thread_lifecycle)

    return [blocking.PASS, metrics.PASS, lock_discipline.PASS,
            thread_lifecycle.PASS, knob_registry.PASS,
            fault_registry.PASS, events_registry.PASS]
