"""``blocking`` pass: no loop-blocking calls inside ``async def``.

Port of the original ``tools/lint_blocking.py`` (PR 5/6/7/9) onto the
vmqlint framework.  The defect class is the old binary load shedder: a
synchronous stall (``time.sleep``, sync file IO, an unbounded
cross-thread wait, a sleep-poll ring helper, a process-wide mesh
barrier) sitting on the event loop inside an async path, freezing every
session's IO for its duration.  See the original module docstring —
the rules are unchanged; what changed is the scan scope (now also
``tools/`` and ``bench.py``: the loadtest/soak/bench harnesses run the
same event-loop rules) and the suppression idiom
(``# vmqlint: allow(blocking): <reason>``; the legacy
``# lint: allow-blocking`` marker still works).
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Context, Finding, Pass, SourceFile

#: call spellings that block the event loop. Attribute calls match on
#: the LAST TWO components, so ``jax.distributed.initialize`` and a
#: bare ``distributed.initialize`` both hit.
_BAD_ATTR = {("time", "sleep"), ("os", "fsync"),
             ("shared_memory", "SharedMemory"),
             # mesh seams: process-wide barriers / device waits
             ("distributed", "initialize"),
             ("multihost_utils", "sync_global_devices"),
             ("multihost_utils", "process_allgather")}
_BAD_NAME = {"open", "input", "SharedMemory"}

#: method names that are ALWAYS blocking regardless of arguments: the
#: shm-ring sleep-poll helpers (parallel/shm_ring.py) and jax's
#: device-completion wait — device waits belong on executor threads
_BLOCKING_METHODS = {"pop_wait", "push_wait", "block_until_ready"}


def _call_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return (f.value.id, f.attr)
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Attribute):
        # dotted chain (jax.distributed.initialize): match on the last
        # two components — the prefix module alias is spelling-dependent
        return (f.value.attr, f.attr)
    if isinstance(f, ast.Name):
        return f.id
    return None


def _unbounded_wait(node: ast.Call):
    """Detect unbounded cross-thread waits by METHOD SHAPE (the receiver
    may be any expression, so typing is out of reach for an AST pass):
    ``x.acquire()`` with neither a positional ``blocking`` arg nor a
    ``timeout=``/``blocking=`` kwarg, ``x.result()`` with no arguments,
    and ``x.get()`` with no arguments at all (``dict.get(key)`` always
    has a positional arg, so it never matches).  Returns the pretty
    spelling to report, or None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    kw = {k.arg for k in node.keywords}
    if f.attr == "acquire":
        if not node.args and not ({"timeout", "blocking"} & kw):
            return ".acquire()"
    elif f.attr == "result":
        if not node.args and "timeout" not in kw:
            return ".result()"
    elif f.attr == "get":
        if not node.args and not kw:
            return ".get()"
    return None


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walk ONE async function's body without descending into nested
    function definitions (each async def gets its own visitor from the
    module walk; nested sync defs are not loop-bound)."""

    def __init__(self, findings: List[Finding], rel: str):
        self.findings = findings
        self.rel = rel
        # directly-awaited calls are loop-FRIENDLY versions of the same
        # spellings (asyncio.Queue.get, asyncio.Lock.acquire): exempt
        self._awaited = set()

    def visit_Await(self, node):  # noqa: N802
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # noqa: N802 — ast API
        pass  # nested sync def: not necessarily on the loop

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        pass  # visited by the module-level walk

    def visit_Call(self, node):  # noqa: N802
        name = _call_name(node)
        if name == ("asyncio", "wait_for") or name == "wait_for":
            # the wrapped awaitable is bounded by wait_for's timeout
            for a in node.args:
                if isinstance(a, ast.Call):
                    self._awaited.add(id(a))
        bad = (name in _BAD_NAME if isinstance(name, str)
               else name in _BAD_ATTR)
        if (not bad and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS):
            # blocking helpers: any receiver spelling counts (the
            # method shape is the contract, like _unbounded_wait)
            bad, name = True, f".{node.func.attr}"
        if bad:
            pretty = name if isinstance(name, str) else ".".join(name)
            self.findings.append(Finding(
                PASS.name, self.rel, node.lineno,
                f"blocking call `{pretty}(...)` inside async def"))
        if id(node) not in self._awaited:
            unbounded = _unbounded_wait(node)
            if unbounded:
                self.findings.append(Finding(
                    PASS.name, self.rel, node.lineno,
                    f"unbounded `{unbounded}` inside async def (no "
                    f"timeout= — a wedged holder parks the loop "
                    f"forever; bound it or mark `# vmqlint: "
                    f"allow(blocking): <reason>`)"))
        self.generic_visit(node)


class BlockingPass(Pass):
    name = "blocking"
    describe = ("loop-blocking calls / unbounded waits inside async "
                "bodies")
    defect = ("a synchronous stall on the event loop freezes every "
              "session's IO (the old fixed-sleep load shedder)")
    roots = ("vernemq_tpu", "tools", "bench.py")

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for f in ctx.iter_files(self.roots):
            self._scan(f, findings)
        return findings

    @staticmethod
    def _scan(f: SourceFile, findings: List[Finding]) -> None:
        if f.tree is None:
            return  # parse errors are reported once by the core
        for node in ast.walk(f.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                v = _AsyncBodyVisitor(findings, f.rel)
                for child in node.body:
                    v.visit(child)


PASS = BlockingPass()
