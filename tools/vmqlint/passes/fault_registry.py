"""``fault-registry`` pass: injection points and breaker paths agree.

The fault-injection surface has three places that must spell the same
names or drills silently no-op:

- the injection **sites** (``faults.inject("device.dispatch")`` hooks
  threaded through the tree),
- the **registry** (``robustness/faults.py`` ``KNOWN_POINTS`` — what
  ``vmq-admin fault inject`` validates against and the docs list),
- the admin/drill surface (``vmq-admin fault inject point=...``,
  ``breaker trip|reset path=...``).

A typo'd ``faults.inject("device.dipatch")`` site creates a point no
plan ever targets — the seam is dead and chaos drills pass vacuously.
A registry entry with no site means an operator can "inject" a fault
that can never fire.  Same story for breaker paths: the
``breaker show`` rows and the ``trip|reset`` path filter must both
match ``robustness/breaker.py`` ``BREAKER_PATHS`` exactly, or a new
device path ships un-drillable.

Checks:

1. every ``faults.inject(...)``/``inject_async(...)`` first argument is
   a string literal naming a ``KNOWN_POINTS`` entry;
2. every ``KNOWN_POINTS`` entry has at least one injection site;
3. every breaker admin row — a dict literal carrying BOTH ``"path"``
   and ``"mountpoint"`` keys, the ``breaker show`` row shape (plain
   ``"path"`` dicts are file paths/HTTP routes, not this surface) —
   names a ``BREAKER_PATHS`` entry (the ``"-"`` placeholder row is
   exempt), and every ``path in (None, "<lit>", ...)`` selector branch
   (the trip/reset per-path filter idiom — recognized by the ``None``
   member meaning "all paths") uses only registered spellings;
4. every ``BREAKER_PATHS`` entry appears in at least one ``"path"``
   row (a registered path with no admin surface is un-drillable).

(The trip/reset *validation* no longer carries its own literal tuple —
``admin/commands.py`` imports ``BREAKER_PATHS`` — so the remaining
drift surface is exactly the per-path selector branches checked here.)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, Pass, const_str

_FAULTS_FILE = "vernemq_tpu/robustness/faults.py"
_BREAKER_FILE = "vernemq_tpu/robustness/breaker.py"

#: `breaker show` placeholder row when no matcher exists yet
_PATH_PLACEHOLDERS = {"-"}


_const_str = const_str  # shared literal probe (core.py)


def _parse_const_table(tree: ast.AST, var: str, rel: str,
                       errors: List[Finding],
                       ) -> Dict[str, int]:
    """``var`` as a dict literal (keys) or tuple/list/set literal
    (elements) of string constants -> name -> line."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(isinstance(t, ast.Name) and t.id == var
                   for t in targets):
            continue
        val = node.value
        if isinstance(val, ast.Dict):
            for k in val.keys:
                s = _const_str(k) if k is not None else None
                if s is None:
                    errors.append(Finding(
                        PASS.name, rel,
                        getattr(k, "lineno", node.lineno),
                        f"{var} key is not a string literal"))
                else:
                    out[s] = k.lineno
        elif isinstance(val, (ast.Tuple, ast.List, ast.Set)):
            for elt in val.elts:
                s = _const_str(elt)
                if s is None:
                    errors.append(Finding(
                        PASS.name, rel, elt.lineno,
                        f"{var} entry is not a string literal"))
                else:
                    out[s] = elt.lineno
        else:
            errors.append(Finding(
                PASS.name, rel, node.lineno,
                f"{var} is not a literal table — cannot verify"))
    return out


def _inject_point(node: ast.Call) -> Optional[Tuple[Optional[str], int]]:
    """Is this an injection site?  -> (point literal or None, line)."""
    f = node.func
    callee = None
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name) and f.value.id == "faults":
            callee = f.attr
    elif isinstance(f, ast.Name):
        callee = f.id if f.id in ("inject", "inject_async") else None
    if callee not in ("inject", "inject_async"):
        return None
    if not node.args:
        return (None, node.lineno)
    return (_const_str(node.args[0]), node.lineno)


class FaultRegistryPass(Pass):
    name = "fault-registry"
    describe = ("faults.inject* sites match KNOWN_POINTS; breaker "
                "path= spellings match BREAKER_PATHS")
    defect = ("a typo'd injection point or breaker path makes drills "
              "and admin trip/reset silently no-op")
    tree_scoped = True

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        ff = ctx.get(_FAULTS_FILE)
        if ff is None or ff.tree is None:
            return [Finding(PASS.name, _FAULTS_FILE, 0,
                            "faults module missing/unparseable")]
        points = _parse_const_table(ff.tree, "KNOWN_POINTS",
                                    _FAULTS_FILE, findings)
        if not points:
            findings.append(Finding(
                PASS.name, _FAULTS_FILE, 0,
                "KNOWN_POINTS registry not found — every injection "
                "point must be registered"))
        bf = ctx.get(_BREAKER_FILE)
        if bf is None or bf.tree is None:
            return findings + [Finding(PASS.name, _BREAKER_FILE, 0,
                               "breaker module missing/unparseable")]
        paths = _parse_const_table(bf.tree, "BREAKER_PATHS",
                                   _BREAKER_FILE, findings)
        if not paths:
            findings.append(Finding(
                PASS.name, _BREAKER_FILE, 0,
                "BREAKER_PATHS registry not found"))

        sites: Set[str] = set()
        path_rows: Set[str] = set()
        for f in ctx.iter_files(self.roots, respect_changed=False):
            if f.tree is None:
                continue
            in_faults = f.rel == _FAULTS_FILE
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Call) and not in_faults:
                    hit = _inject_point(node)
                    if hit is not None:
                        point, line = hit
                        if point is None:
                            findings.append(Finding(
                                PASS.name, f.rel, line,
                                "faults.inject* point is not a string "
                                "literal — the site cannot be checked "
                                "against KNOWN_POINTS"))
                        else:
                            sites.add(point)
                            if points and point not in points:
                                findings.append(Finding(
                                    PASS.name, f.rel, line,
                                    f"injection point '{point}' is not "
                                    f"in faults.KNOWN_POINTS — "
                                    f"register it or fix the "
                                    f"spelling"))
                elif isinstance(node, ast.Dict):
                    # a breaker admin ROW, not any dict with a "path"
                    # key (file paths, HTTP routes): the show rows all
                    # carry BOTH "path" and "mountpoint" — that pair is
                    # the disambiguator
                    keys = {_const_str(k) for k in node.keys
                            if k is not None}
                    if "path" not in keys or "mountpoint" not in keys:
                        continue
                    for k, v in zip(node.keys, node.values):
                        if (k is not None and _const_str(k) == "path"):
                            val = _const_str(v)
                            if val is None \
                                    or val in _PATH_PLACEHOLDERS:
                                continue
                            path_rows.add(val)
                            if paths and val not in paths:
                                findings.append(Finding(
                                    PASS.name, f.rel, v.lineno,
                                    f"breaker path '{val}' is not in "
                                    f"breaker.BREAKER_PATHS"))
                elif isinstance(node, ast.Compare):
                    findings.extend(self._check_membership(
                        node, f.rel, paths))
        for point, line in sorted(points.items()):
            if point not in sites:
                findings.append(Finding(
                    PASS.name, _FAULTS_FILE, line,
                    f"KNOWN_POINTS entry '{point}' has no "
                    f"faults.inject* site — an operator-injectable "
                    f"fault that can never fire"))
        for path, line in sorted(paths.items()):
            if path not in path_rows:
                findings.append(Finding(
                    PASS.name, _BREAKER_FILE, line,
                    f"BREAKER_PATHS entry '{path}' never appears as a "
                    f"breaker-show/trip row — the path is "
                    f"un-drillable from the admin surface"))
        return findings

    @staticmethod
    def _check_membership(node: ast.Compare, rel: str,
                          paths: Dict[str, int]) -> List[Finding]:
        """``path in (None, "match")`` selector branches (the trip/
        reset per-path filter idiom — the ``None`` member means "no
        filter, take all paths" and distinguishes this shape from URL/
        filesystem path tests) must use registered spellings only."""
        if not (isinstance(node.left, ast.Name)
                and node.left.id == "path" and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and isinstance(node.comparators[0],
                               (ast.Tuple, ast.List, ast.Set))):
            return []
        elts = node.comparators[0].elts
        if not any(isinstance(e, ast.Constant) and e.value is None
                   for e in elts):
            return []  # no None member: not the breaker selector idiom
        out = []
        for elt in elts:
            s = _const_str(elt)
            if s is not None and paths and s not in paths:
                out.append(Finding(
                    PASS.name, rel, elt.lineno,
                    f"breaker path selector names '{s}' which is not "
                    f"in BREAKER_PATHS — the branch can never match a "
                    f"registered path"))
        return out


PASS = FaultRegistryPass()
