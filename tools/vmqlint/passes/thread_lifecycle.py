"""``thread-lifecycle`` pass: every started thread has an owner.

Defect class (PR 2/4 review-hardening tails, shipped twice): a class
starts a ``threading.Thread``/``threading.Timer`` and its ``close()``/
``stop()`` never joins or cancels it — background rebuild threads
outlived ``close()``, installed stale tables after teardown, and kept
test processes alive.  The repo's rule since PR 4: a thread handle is
state; whoever stores it winds it down (join/cancel) or explicitly
documents the cooperative-stop design.

For every class that starts a Thread/Timer, this pass requires:

- the constructed thread is **stored** (``self.x = threading.Thread``,
  possibly via a local, or appended to a ``self.<collection>``) — an
  inline ``threading.Thread(...).start()`` leaves ``close()`` nothing
  to join;
- a ``.join(...)`` or ``.cancel(...)`` of that attribute (directly,
  through a local alias, or on the loop variable of a
  ``for ... in self.<collection>``) is **reachable from a lifecycle
  method**: the class-local ``self.<m>()`` call graph is walked to a
  fixpoint from ``close``/``stop``/``shutdown``/``__exit__`` — a join
  parked in a helper nothing on the teardown path calls does not count;
- the class has a lifecycle method at all.

Module-level fire-and-forget threads (one-shot dump writers) are out of
scope — the defect class is *instances that claim a lifecycle and leak
threads past it*.  Deliberate designs (sacrificial executors whose
wedged workers are abandoned by contract; cooperative-stop rebuild
threads that observe a closed flag and discard their install) opt out
with ``# vmqlint: allow(thread-lifecycle): <reason>`` on the
``Thread(...)`` construction line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, Pass, SourceFile

_LIFECYCLE_METHODS = {"close", "stop", "shutdown", "__exit__"}


def _is_thread_ctor(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return (f.value.id in ("threading", "_threading")
                and f.attr in ("Thread", "Timer"))
    if isinstance(f, ast.Name):
        return f.id in ("Thread", "Timer")
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> ``"x"``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """One pass over a method body collecting thread construction,
    storage, start and join/cancel facts (with one level of local
    aliasing: ``t = threading.Thread(...)`` / ``m = self._monitor``)."""

    def __init__(self):
        # local name -> "ctor" (holds a fresh thread) or ("attr", name)
        self.alias: Dict[str, object] = {}
        #: self attrs assigned a fresh thread: attr -> ctor line
        self.stored: Dict[str, int] = {}
        #: self attrs a fresh thread was append/add-ed to: attr -> line
        self.collected: Dict[str, int] = {}
        #: attrs .start()ed (directly or via alias)
        self.started_attrs: Set[str] = set()
        #: ctor lines started without any storage (inline/local-only);
        #: line -> True once .start() observed
        self.naked_ctors: Dict[int, bool] = {}
        #: ctor line -> ("attr"|"coll", name) once stored/collected —
        #: start order independent
        self.ctor_home: Dict[int, tuple] = {}
        #: attrs joined/cancelled in this method (incl. via alias or
        #: for-loop over a self collection)
        self.joined: Set[str] = set()

    def _expr_thread(self, node: ast.AST) -> Optional[Tuple[str, int]]:
        """Is this expression a fresh thread? -> ("ctor", line)."""
        if isinstance(node, ast.Call) and _is_thread_ctor(node):
            return ("ctor", node.lineno)
        if isinstance(node, ast.Name):
            a = self.alias.get(node.id)
            if isinstance(a, tuple) and a[0] == "ctor":
                return ("ctor", a[1])
        return None

    def visit_Assign(self, node):  # noqa: N802
        val_thread = self._expr_thread(node.value)
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is not None:
                if val_thread:
                    self.stored[attr] = val_thread[1]
                    # a stored ctor is no longer naked, even if the
                    # local alias is .start()ed after this assignment
                    self.ctor_home[val_thread[1]] = ("attr", attr)
                    self.naked_ctors.pop(val_thread[1], None)
            elif isinstance(tgt, ast.Name):
                if isinstance(node.value, ast.Call) \
                        and _is_thread_ctor(node.value):
                    self.alias[tgt.id] = ("ctor", node.value.lineno)
                    self.naked_ctors.setdefault(node.value.lineno, False)
                elif _self_attr(node.value) is not None:
                    self.alias[tgt.id] = ("attr",
                                          _self_attr(node.value))
                else:
                    self.alias.pop(tgt.id, None)
        self.generic_visit(node)

    def _receiver_attr(self, recv: ast.AST) -> Optional[str]:
        """Resolve a call receiver to a self attr (direct or alias)."""
        attr = _self_attr(recv)
        if attr is not None:
            return attr
        if isinstance(recv, ast.Name):
            a = self.alias.get(recv.id)
            if isinstance(a, tuple) and a[0] == "attr":
                return a[1]
        return None

    def visit_Call(self, node):  # noqa: N802
        f = node.func
        if isinstance(f, ast.Attribute):
            recv = f.value
            if f.attr == "start" and not node.args:
                th = self._expr_thread(recv)
                if th:  # threading.Thread(...).start() / t.start()
                    home = self.ctor_home.get(th[1])
                    if home is not None:  # stored/collected earlier
                        self.started_attrs.add(home[1])
                    else:                 # truly unstored so far
                        self.naked_ctors[th[1]] = True
                else:
                    attr = self._receiver_attr(recv)
                    if attr is not None:
                        self.started_attrs.add(attr)
            elif f.attr in ("join", "cancel"):
                attr = self._receiver_attr(recv)
                if attr is not None:
                    self.joined.add(attr)
            elif f.attr in ("append", "add"):
                attr = self._receiver_attr(recv)
                if attr is not None and node.args:
                    th = self._expr_thread(node.args[0])
                    if th:
                        self.collected[attr] = th[1]
                        self.ctor_home[th[1]] = ("coll", attr)
                        self.naked_ctors.pop(th[1], None)
        if isinstance(node, ast.Call) and _is_thread_ctor(node):
            # ctor seen in any other position: candidate naked start
            self.naked_ctors.setdefault(node.lineno, False)
        self.generic_visit(node)

    def visit_For(self, node):  # noqa: N802
        # `for t in self._threads: t.join()` — credit the collection
        it = node.iter
        if isinstance(it, ast.Call) and it.args:  # list(self._threads)
            it = it.args[0]
        attr = _self_attr(it)
        if attr is not None and isinstance(node.target, ast.Name):
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in ("join", "cancel")
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == node.target.id):
                    self.joined.add(attr)
        self.generic_visit(node)


class ThreadLifecyclePass(Pass):
    name = "thread-lifecycle"
    describe = ("Thread/Timer started by a class with no join/cancel "
                "reachable from close()/stop()")
    defect = ("background threads outlive close(), install stale state "
              "after teardown and keep processes alive")

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for f in ctx.iter_files(self.roots):
            self._scan(f, findings)
        return findings

    @staticmethod
    def _scan(f: SourceFile, findings: List[Finding]) -> None:
        if f.tree is None:
            return
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                _audit_class(node, f, findings)


def _self_calls(item: ast.AST) -> Set[str]:
    """Method names this method invokes as ``self.<m>(...)``."""
    out: Set[str] = set()
    for node in ast.walk(item):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and _self_attr(node.func) is not None):
            out.add(node.func.attr)
    return out


def _audit_class(cls: ast.ClassDef, f: SourceFile,
                 findings: List[Finding]) -> None:
    stored: Dict[str, int] = {}
    collected: Dict[str, int] = {}
    started: Set[str] = set()
    #: attrs joined/cancelled, per method name (reachability matters)
    joined_by_method: Dict[str, Set[str]] = {}
    calls_by_method: Dict[str, Set[str]] = {}
    naked: Dict[int, bool] = {}
    method_names: Set[str] = set()
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        method_names.add(item.name)
        scan = _MethodScan()
        for child in item.body:
            scan.visit(child)
        for attr, line in scan.stored.items():
            stored.setdefault(attr, line)
        for attr, line in scan.collected.items():
            collected.setdefault(attr, line)
        started |= scan.started_attrs
        joined_by_method.setdefault(item.name, set()).update(scan.joined)
        calls_by_method[item.name] = _self_calls(item)
        for line, was_started in scan.naked_ctors.items():
            naked[line] = naked.get(line, False) or was_started
    thread_lines = (list(stored.values()) + list(collected.values())
                    + [ln for ln, st in naked.items() if st])
    if not thread_lines:
        return
    # joins count only when REACHABLE from a lifecycle method: the
    # class-local call graph (self.<m>() edges) walked to a fixpoint
    # from close/stop/shutdown/__exit__ — a join parked in a helper
    # nothing on the teardown path calls is the PR 4 defect with extra
    # steps, not a fix for it
    has_lifecycle = bool(method_names & _LIFECYCLE_METHODS)
    reachable = set(method_names & _LIFECYCLE_METHODS)
    frontier = set(reachable)
    while frontier:
        nxt = set()
        for m in frontier:
            for callee in calls_by_method.get(m, ()):
                if callee in method_names and callee not in reachable:
                    reachable.add(callee)
                    nxt.add(callee)
        frontier = nxt
    joined: Set[str] = set()
    for m in reachable:
        joined |= joined_by_method.get(m, set())
    for line, was_started in sorted(naked.items()):
        if was_started:
            findings.append(Finding(
                PASS.name, f.rel, line,
                f"class {cls.name} starts a Thread/Timer without "
                f"storing its handle — close()/stop() has nothing to "
                f"join; keep the handle or mark `# vmqlint: "
                f"allow(thread-lifecycle): <reason>`"))
    for attr, line in sorted({**stored, **collected}.items(),
                             key=lambda kv: kv[1]):
        if attr not in started:
            # constructed but never .start()ed anywhere in the class:
            # nothing to wind down (joining an unstarted Thread raises)
            continue
        if not has_lifecycle:
            findings.append(Finding(
                PASS.name, f.rel, line,
                f"class {cls.name} starts threads but has no "
                f"close()/stop() lifecycle method to wind them down"))
        elif attr not in joined:
            findings.append(Finding(
                PASS.name, f.rel, line,
                f"class {cls.name} stores a Thread/Timer in "
                f"self.{attr} but no join/cancel of it is reachable "
                f"from close()/stop() — a background thread outliving "
                f"close() is the PR 4 stale-install defect class"))


PASS = ThreadLifecyclePass()
