"""vmqlint framework: shared parse cache, pass registry, suppression.

Design contract (stable — the tier-1 gate and the shims rely on it):

- **One walk.** Every pass consumes the same :class:`SourceFile`
  objects; a file is read and ``ast.parse``\\ d at most once per run no
  matter how many passes look at it.
- **Suppression.** A finding on line N is suppressed when line N (or a
  comment-only line directly above it) carries
  ``# vmqlint: allow(<pass>[, <pass>...]): <reason>`` naming the pass
  (or ``*``).  The reason is mandatory — an allow marker with no reason
  is itself a finding, as is one naming an unknown pass.  The legacy
  markers ``# lint: allow-blocking`` and ``# lint: observe-passthrough``
  are honored as ``allow(blocking)`` / ``allow(metrics)``.
- **Scopes.** File-scoped passes are restricted by ``--changed`` (and
  by explicit path arguments) to the files in play; tree-scoped passes
  (registry diffs need the whole tree to be meaningful) always run in
  full — they are one dict lookup per call site and cost nothing.
- **Exit codes.** 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

#: scan roots, repo-relative. ``vernemq_tpu`` is the product tree;
#: ``tools`` and ``bench.py`` carry the loadtest/soak/bench harnesses
#: whose async bodies run the same event-loop rules (the old
#: lint_blocking hardcoded the package dir and missed them).
SCAN_ROOTS: Tuple[str, ...] = ("vernemq_tpu", "tools", "bench.py")

ALLOW_RE = re.compile(
    r"#\s*vmqlint:\s*allow\(\s*([a-z0-9*][a-z0-9*,\- ]*)\)"
    r"\s*(?::\s*(\S.*))?")
#: legacy marker substring -> pass it suppresses (no reason required —
#: pre-vmqlint sites carry their reason in prose after the marker)
LEGACY_MARKS = {"lint: allow-blocking": "blocking",
                "lint: observe-passthrough": "metrics"}


def const_str(node) -> Optional[str]:
    """The string value of an ``ast.Constant`` str node, else None —
    the shared literal probe every registry pass keys on."""
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


@dataclass(frozen=True)
class Finding:
    pass_name: str
    rel: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.rel}:{self.line}: [{self.pass_name}] {self.message}"

    def as_dict(self) -> Dict[str, object]:
        return {"pass": self.pass_name, "file": self.rel,
                "line": self.line, "message": self.message}


class SourceFile:
    """One scanned file: text + cached AST + suppression map."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self._tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        self._parsed = False
        # line -> pass names allowed there ('*' = every pass); a marker
        # on a comment-only line also covers the next line, so long
        # statements can carry their annotation above instead of
        # stretching past the line-length limit
        self.allow: Dict[int, Set[str]] = {}
        #: (line, passes, reason) of every vmqlint allow marker, for
        #: marker-hygiene checks
        self.markers: List[Tuple[int, Tuple[str, ...], str]] = []
        self._scan_markers()

    @property
    def tree(self) -> Optional[ast.Module]:
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.text, filename=self.rel)
            except SyntaxError as e:
                self.syntax_error = e
        return self._tree

    def _scan_markers(self) -> None:
        lines = self.text.splitlines()
        for i, line in enumerate(lines, 1):
            names: Set[str] = set()
            m = ALLOW_RE.search(line)
            if m:
                passes = tuple(p.strip() for p in m.group(1).split(",")
                               if p.strip())
                self.markers.append((i, passes, (m.group(2) or "").strip()))
                names.update(passes)
            for mark, pass_name in LEGACY_MARKS.items():
                if mark in line:
                    names.add(pass_name)
            if not names:
                continue
            self.allow.setdefault(i, set()).update(names)
            # a marker inside a comment block annotates the first code
            # line after it (long reasons wrap; the statement itself
            # may be black-formatted past the marker line) — walk over
            # the remaining comment-only and blank lines to the code
            # line below
            if line.lstrip().startswith("#"):
                j = i  # 0-based index of the line after the marker
                while j < len(lines) and (
                        not lines[j].strip()
                        or lines[j].lstrip().startswith("#")):
                    j += 1
                self.allow.setdefault(j + 1, set()).update(names)

    def allows(self, pass_name: str, line: int) -> bool:
        names = self.allow.get(line)
        return bool(names) and (pass_name in names or "*" in names)


class Context:
    """What a pass sees: the file set plus the changed-file filter."""

    def __init__(self, files: Dict[str, SourceFile],
                 changed: Optional[Set[str]] = None):
        self.files = files
        self.changed = changed  # None = everything is in play

    def get(self, rel: str) -> Optional[SourceFile]:
        return self.files.get(rel)

    def iter_files(self, roots: Sequence[str],
                   respect_changed: bool = True) -> Iterable[SourceFile]:
        for rel in sorted(self.files):
            if not any(rel == r or rel.startswith(r.rstrip("/") + "/")
                       for r in roots):
                continue
            if (respect_changed and self.changed is not None
                    and rel not in self.changed):
                continue
            yield self.files[rel]


class Pass:
    """Base pass. Subclasses set ``name``/``describe``/``defect`` and
    implement :meth:`run`; ``tree_scoped`` passes ignore ``--changed``
    (their registry diffs are only meaningful over the whole tree)."""

    name: str = ""
    describe: str = ""
    #: the defect class this pass encodes (README table; --list output)
    defect: str = ""
    tree_scoped: bool = False
    roots: Tuple[str, ...] = ("vernemq_tpu",)

    def run(self, ctx: Context) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


# --------------------------------------------------------- file discovery

def _rel_ok(rel: str) -> bool:
    return rel.endswith(".py") and "__pycache__" not in rel


def collect_files(root: str = REPO_ROOT,
                  overrides: Optional[Dict[str, str]] = None,
                  ) -> Dict[str, SourceFile]:
    """Read every scannable file under :data:`SCAN_ROOTS` once.
    ``overrides`` maps repo-relative paths to replacement text (tests
    seed defects without touching the tree; an override may also add a
    file that does not exist on disk)."""
    files: Dict[str, SourceFile] = {}
    for entry in SCAN_ROOTS:
        top = os.path.join(root, entry)
        if os.path.isfile(top):
            if _rel_ok(entry):
                files[entry] = None  # type: ignore[assignment]
            continue
        for dirpath, dirs, names in os.walk(top):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for fn in names:
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rel = rel.replace(os.sep, "/")
                if _rel_ok(rel):
                    files[rel] = None  # type: ignore[assignment]
    for rel in list(files):
        if overrides and rel in overrides:
            continue
        with open(os.path.join(root, rel), "r", encoding="utf-8") as fh:
            files[rel] = SourceFile(rel, fh.read())
    for rel, text in (overrides or {}).items():
        files[rel] = SourceFile(rel, text)
    return files


def changed_files(root: str = REPO_ROOT) -> Optional[Set[str]]:
    """Repo-relative paths changed vs HEAD (staged, unstaged, and
    untracked) — the ``--changed`` fast-iteration scope.  Returns
    ``None`` when git is unavailable/failing: that must widen the scan
    to everything, not narrow it to nothing (an empty set is the
    legitimate "working tree clean" answer; a FAILED probe producing
    the same value would make the gate vacuously green)."""
    out: Set[str] = set()
    for args in (["git", "diff", "--name-only", "HEAD", "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(args, cwd=root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None  # no git: scan everything
        if res.returncode != 0:
            return None
        out.update(line.strip() for line in res.stdout.splitlines()
                   if line.strip())
    return out


# ----------------------------------------------------------------- runner

def _registry() -> Dict[str, Pass]:
    from .passes import all_passes

    return {p.name: p for p in all_passes()}


def _hygiene(files: Iterable[SourceFile],
             known: Set[str]) -> List[Finding]:
    """The suppression idiom polices itself: a marker with a typo'd
    pass name silently suppresses nothing, and one with no reason
    defeats the annotate-deliberate-sites discipline."""
    out: List[Finding] = []
    for f in files:
        for line, passes, reason in f.markers:
            unknown = [p for p in passes if p != "*" and p not in known]
            if unknown:
                out.append(Finding(
                    "allow-marker", f.rel, line,
                    f"allow() names unknown pass(es) "
                    f"{', '.join(sorted(unknown))} (known: "
                    f"{', '.join(sorted(known))})"))
            if not reason:
                out.append(Finding(
                    "allow-marker", f.rel, line,
                    "allow() marker with no reason — write `# vmqlint: "
                    "allow(<pass>): <why this site is deliberate>`"))
    return out


def run(passes: Optional[Sequence[str]] = None,
        changed: bool = False,
        paths: Optional[Sequence[str]] = None,
        overrides: Optional[Dict[str, str]] = None,
        files: Optional[Dict[str, SourceFile]] = None,
        root: str = REPO_ROOT,
        ) -> Tuple[List[Finding], Dict[str, object]]:
    """Run the selected passes; returns (findings, stats).

    ``paths`` restricts file-scoped passes to those repo-relative files
    (the shim/test surface); ``changed`` restricts them to the git
    working-set.  Tree-scoped passes always see everything."""
    registry = _registry()
    if passes is None:
        selected = list(registry.values())
    else:
        missing = [p for p in passes if p not in registry]
        if missing:
            raise KeyError(f"unknown pass(es): {', '.join(missing)} "
                           f"(known: {', '.join(sorted(registry))})")
        selected = [registry[p] for p in passes]
    if files is None:
        files = collect_files(root, overrides)
    elif overrides:
        files = dict(files)
        for rel, text in overrides.items():
            files[rel] = SourceFile(rel, text)

    restrict: Optional[Set[str]] = None
    if paths is not None:
        restrict = {p.replace(os.sep, "/") for p in paths}
        unknown = {p for p in restrict if p not in files}
        if unknown:
            # a typo'd path silently scanning zero files would read as
            # "clean" — the same vacuous-green mode the --changed git
            # probe guards against
            raise KeyError(f"path(s) not in the scan set: "
                           f"{', '.join(sorted(unknown))}")
    elif changed:
        delta = changed_files(root)
        if delta is not None:  # git failure -> full scan, never "none"
            restrict = {rel for rel in delta if rel in files}
    ctx = Context(files, restrict)

    findings: List[Finding] = []
    # a file that does not parse defeats every pass — surface it once
    scanned = list(ctx.iter_files(SCAN_ROOTS, respect_changed=False))
    for f in scanned:
        if f.tree is None and f.syntax_error is not None:
            findings.append(Finding(
                "parse", f.rel, f.syntax_error.lineno or 0,
                f"syntax error: {f.syntax_error.msg}"))
    findings.extend(_hygiene(scanned, set(registry)))
    for p in selected:
        findings.extend(p.run(ctx))

    # parse and marker-hygiene findings are about the marker/file
    # itself and must not be suppressible by the very marker they
    # police (a reasonless star marker would otherwise self-suppress
    # the mandatory-reason finding along with everything on its line)
    unsuppressible = {"parse", "allow-marker"}
    kept = [f for f in findings
            if f.pass_name in unsuppressible
            or not (f.rel in files and files[f.rel].allows(f.pass_name,
                                                           f.line))]
    kept.sort(key=lambda f: (f.rel, f.line, f.pass_name))
    stats: Dict[str, object] = {
        "passes": [p.name for p in selected],
        "files_scanned": len(scanned),
        "restricted_to": sorted(restrict) if restrict is not None else None,
        "finding_count": len(kept),
        "suppressed": len(findings) - len(kept),
    }
    return kept, stats


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tools.vmqlint",
        description="unified static-analysis suite (tier-1 pre-test "
                    "gate); exit 0 clean, 1 findings, 2 error")
    ap.add_argument("paths", nargs="*",
                    help="restrict file-scoped passes to these "
                         "repo-relative files")
    ap.add_argument("--pass", dest="passes", action="append",
                    metavar="NAME", help="run only this pass "
                    "(repeatable)")
    ap.add_argument("--changed", action="store_true",
                    help="file-scoped passes only look at the git "
                         "working-set (fast local iteration; "
                         "tree-scoped registry passes still run full)")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--list", dest="list_passes", action="store_true",
                    help="list registered passes and exit")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2
    try:
        registry = _registry()
        if args.list_passes:
            for name in sorted(registry):
                p = registry[name]
                scope = "tree" if p.tree_scoped else "file"
                print(f"{name:18s} [{scope}] {p.describe}")
            return 0
        findings, stats = run(passes=args.passes,
                              changed=args.changed,
                              paths=args.paths or None)
    except KeyError as e:
        print(f"vmqlint: {e.args[0]}", file=sys.stderr)
        return 2
    except Exception as e:  # internal error must not read as "clean"
        print(f"vmqlint: internal error: {e!r}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps({"findings": [f.as_dict() for f in findings],
                          **stats}, indent=2, sort_keys=True))
        return 1 if findings else 0
    if findings:
        print(f"vmqlint: {len(findings)} finding(s):", file=sys.stderr)
        for f in findings:
            print(f"  {f.render()}", file=sys.stderr)
        return 1
    print(f"vmqlint: clean ({len(stats['passes'])} passes, "
          f"{stats['files_scanned']} files"
          + (", changed-scope" if args.changed else "") + ")")
    return 0
