"""``python -m tools.vmqlint`` — the tier-1 pre-test static gate."""

import sys

from .core import main

if __name__ == "__main__":
    sys.exit(main())
