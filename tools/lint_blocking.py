#!/usr/bin/env python3
"""Static check: no loop-blocking calls inside ``async def`` bodies.

The class of bug this catches is exactly what the old binary load
shedder was: a synchronous stall (``time.sleep(1.0)``) sitting on the
event loop inside an async path, freezing every session's IO for its
duration. Flags, inside any ``async def`` in ``vernemq_tpu/``:

- ``time.sleep(...)`` (use ``await asyncio.sleep`` — or run the sync
  work in an executor);
- synchronous file IO via a direct ``open(...)`` / ``os.fsync(...)``
  call (push it behind ``run_in_executor`` or a sync helper that the
  loop calls knowingly — a *named* helper documents the stall, a bare
  ``open`` in an async body is almost always an accident);
- ``input(...)`` (never legal on the loop).

Nested synchronous ``def``s inside an async function are NOT flagged
(they may run anywhere — an executor, a thread); nested async defs are
visited in their own right. A line may opt out with a trailing
``# lint: allow-blocking`` comment naming its reason — the opt-out is
for deliberate, capped stalls (e.g. a fault-injection seam that models
a slow disk ON the loop on purpose).

Exits 1 with ``file:line`` findings; wired into ``tools/run_tier1.sh``
as a pre-test step so a regression fails tier-1 before a single test
runs.
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
TARGET = os.path.join(ROOT, "vernemq_tpu")

ALLOW_MARK = "lint: allow-blocking"

#: call spellings that block the event loop
_BAD_ATTR = {("time", "sleep"), ("os", "fsync")}
_BAD_NAME = {"open", "input"}


def _call_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return (f.value.id, f.attr)
    if isinstance(f, ast.Name):
        return f.id
    return None


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walk ONE async function's body without descending into nested
    function definitions (each async def gets its own visitor from the
    module walk; nested sync defs are not loop-bound)."""

    def __init__(self, findings, rel, allowed_lines):
        self.findings = findings
        self.rel = rel
        self.allowed = allowed_lines

    def visit_FunctionDef(self, node):  # noqa: N802 — ast API
        pass  # nested sync def: not necessarily on the loop

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        pass  # visited by the module-level walk

    def visit_Call(self, node):  # noqa: N802
        name = _call_name(node)
        bad = (name in _BAD_NAME if isinstance(name, str)
               else name in _BAD_ATTR)
        if bad and node.lineno not in self.allowed:
            pretty = name if isinstance(name, str) else ".".join(name)
            self.findings.append(
                f"{self.rel}:{node.lineno}: blocking call "
                f"`{pretty}(...)` inside async def")
        self.generic_visit(node)


def scan_file(path: str, rel: str, findings) -> None:
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    allowed = {i for i, line in enumerate(src.splitlines(), 1)
               if ALLOW_MARK in line}
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        findings.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            v = _AsyncBodyVisitor(findings, rel, allowed)
            for child in node.body:
                v.visit(child)


def main() -> int:
    findings = []
    for dirpath, _dirs, files in os.walk(TARGET):
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            scan_file(path, os.path.relpath(path, ROOT), findings)
    if findings:
        print("lint_blocking: loop-blocking calls in async bodies:",
              file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("lint_blocking: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
