#!/usr/bin/env python3
"""Thin compatibility shim: the blocking lint moved into the unified
static-analysis suite (``tools/vmqlint``, the ``blocking`` pass).

Kept so existing invocations (docs, muscle memory, CI snippets that
predate the suite) keep working; new callers should run
``python -m tools.vmqlint`` (every pass) or
``python -m tools.vmqlint --pass blocking``.  Same exit-code contract:
0 clean, 1 findings.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from tools.vmqlint.core import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--pass", "blocking"]))
