#!/usr/bin/env python3
"""Static check: no loop-blocking calls inside ``async def`` bodies.

The class of bug this catches is exactly what the old binary load
shedder was: a synchronous stall (``time.sleep(1.0)``) sitting on the
event loop inside an async path, freezing every session's IO for its
duration. Flags, inside any ``async def`` in ``vernemq_tpu/``:

- ``time.sleep(...)`` (use ``await asyncio.sleep`` — or run the sync
  work in an executor);
- synchronous file IO via a direct ``open(...)`` / ``os.fsync(...)``
  call (push it behind ``run_in_executor`` or a sync helper that the
  loop calls knowingly — a *named* helper documents the stall, a bare
  ``open`` in an async body is almost always an accident);
- ``input(...)`` (never legal on the loop);
- unbounded waits that the stall watchdog cannot release: a bare
  ``<lock>.acquire()`` with no ``timeout=``/``blocking=False``, a
  ``<future>.result()`` with no timeout, and a no-argument
  ``<queue>.get()`` — each parks the LOOP behind another thread's
  progress forever if that thread wedges (``dict.get(key)`` and
  bounded variants are not flagged);
- the cross-process seam (parallel/shm_ring.py): the blocking ring
  helpers ``.pop_wait(...)``/``.push_wait(...)`` (sleep-poll loops for
  plain-thread ring ends — on the loop they freeze every session for
  the full timeout while the peer process lags), and a direct
  ``SharedMemory(...)`` construction (segment create/attach is
  synchronous filesystem+mmap work; do it at boot or in an executor,
  never per-request on the loop);
- the mesh seam (parallel/mesh_match.py): ``jax.distributed.
  initialize(...)`` (blocks until every process of the runtime has
  dialed the coordinator — boot-time work, never on the loop),
  ``.block_until_ready()`` (parks the loop behind device completion —
  dispatch from an executor like every other device call), and the
  blocking multihost collectives ``multihost_utils.
  sync_global_devices`` / ``process_allgather`` (barriers over every
  process of the mesh: one slow peer stalls every session this loop
  serves).

Nested synchronous ``def``s inside an async function are NOT flagged
(they may run anywhere — an executor, a thread); nested async defs are
visited in their own right. A line may opt out with a trailing
``# lint: allow-blocking`` comment naming its reason — the opt-out is
for deliberate, capped stalls (e.g. a fault-injection seam that models
a slow disk ON the loop on purpose).

Exits 1 with ``file:line`` findings; wired into ``tools/run_tier1.sh``
as a pre-test step so a regression fails tier-1 before a single test
runs.
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
TARGET = os.path.join(ROOT, "vernemq_tpu")

ALLOW_MARK = "lint: allow-blocking"

#: call spellings that block the event loop. Attribute calls match on
#: the LAST TWO components, so ``jax.distributed.initialize`` and a
#: bare ``distributed.initialize`` both hit ("distributed",
#: "initialize").
_BAD_ATTR = {("time", "sleep"), ("os", "fsync"),
             ("shared_memory", "SharedMemory"),
             # mesh seams: process-wide barriers / device waits
             ("distributed", "initialize"),
             ("multihost_utils", "sync_global_devices"),
             ("multihost_utils", "process_allgather")}
_BAD_NAME = {"open", "input", "SharedMemory"}

#: method names that are ALWAYS blocking regardless of arguments: the
#: shm-ring sleep-poll helpers for plain-thread producers/consumers
#: (parallel/shm_ring.py) — the timeout bounds the wait but still parks
#: the loop for up to its full length while the peer process lags —
#: and jax's device-completion wait (a wedged mesh collective would
#: park the loop forever; device waits belong on executor threads)
_BLOCKING_METHODS = {"pop_wait", "push_wait", "block_until_ready"}


def _call_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return (f.value.id, f.attr)
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Attribute):
        # dotted chain (jax.distributed.initialize): match on the last
        # two components — the prefix module alias is spelling-dependent
        return (f.value.attr, f.attr)
    if isinstance(f, ast.Name):
        return f.id
    return None


def _unbounded_wait(node: ast.Call):
    """Detect unbounded cross-thread waits by METHOD SHAPE (the receiver
    may be any expression, so typing is out of reach for an AST pass):

    - ``x.acquire()`` with neither a positional ``blocking`` arg nor a
      ``timeout=``/``blocking=`` kwarg — ``threading.Lock.acquire``'s
      forever-blocking form (``acquire(False)`` and
      ``acquire(timeout=...)`` are bounded);
    - ``x.result()`` with no arguments — ``Future.result`` waiting
      forever on another thread;
    - ``x.get()`` with NO positional arguments and no
      ``timeout=``/``block=`` kwarg — ``queue.Queue.get``'s blocking
      form. ``dict.get(key[, default])`` always has a positional arg,
      so it never matches.

    Returns the pretty spelling to report, or None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    kw = {k.arg for k in node.keywords}
    if f.attr == "acquire":
        if not node.args and not ({"timeout", "blocking"} & kw):
            return ".acquire()"
    elif f.attr == "result":
        if not node.args and "timeout" not in kw:
            return ".result()"
    elif f.attr == "get":
        if not node.args and not kw:
            return ".get()"
    return None


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walk ONE async function's body without descending into nested
    function definitions (each async def gets its own visitor from the
    module walk; nested sync defs are not loop-bound)."""

    def __init__(self, findings, rel, allowed_lines):
        self.findings = findings
        self.rel = rel
        self.allowed = allowed_lines
        # directly-awaited calls are loop-FRIENDLY versions of the same
        # spellings (asyncio.Queue.get, asyncio.Lock.acquire): exempt
        self._awaited = set()

    def visit_Await(self, node):  # noqa: N802
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # noqa: N802 — ast API
        pass  # nested sync def: not necessarily on the loop

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        pass  # visited by the module-level walk

    def visit_Call(self, node):  # noqa: N802
        name = _call_name(node)
        if name == ("asyncio", "wait_for") or name == "wait_for":
            # the wrapped awaitable is bounded by wait_for's timeout
            for a in node.args:
                if isinstance(a, ast.Call):
                    self._awaited.add(id(a))
        bad = (name in _BAD_NAME if isinstance(name, str)
               else name in _BAD_ATTR)
        if (not bad and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS):
            # shm-ring blocking helpers: any receiver spelling counts
            # (the method shape is the contract, like _unbounded_wait)
            bad, name = True, f".{node.func.attr}"
        if bad and node.lineno not in self.allowed:
            pretty = name if isinstance(name, str) else ".".join(name)
            self.findings.append(
                f"{self.rel}:{node.lineno}: blocking call "
                f"`{pretty}(...)` inside async def")
        unbounded = (None if id(node) in self._awaited
                     else _unbounded_wait(node))
        if unbounded and node.lineno not in self.allowed:
            self.findings.append(
                f"{self.rel}:{node.lineno}: unbounded `{unbounded}` "
                f"inside async def (no timeout= — a wedged holder "
                f"parks the loop forever; bound it or mark "
                f"`# {ALLOW_MARK}: <reason>`)")
        self.generic_visit(node)


def scan_file(path: str, rel: str, findings) -> None:
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    allowed = {i for i, line in enumerate(src.splitlines(), 1)
               if ALLOW_MARK in line}
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        findings.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            v = _AsyncBodyVisitor(findings, rel, allowed)
            for child in node.body:
                v.visit(child)


def main() -> int:
    findings = []
    for dirpath, _dirs, files in os.walk(TARGET):
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            scan_file(path, os.path.relpath(path, ROOT), findings)
    if findings:
        print("lint_blocking: loop-blocking calls in async bodies:",
              file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("lint_blocking: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
