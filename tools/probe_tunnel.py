"""Isolate the axon-tunnel I/O cost model at the windowed kernel's real
shapes: upload bandwidth, download bandwidth, whether outputs transfer
eagerly, and per-call cost with numpy vs device-resident args.

Run on the chip: python tools/probe_tunnel.py
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def timeit(fn, n=8, warm=3):
    for _ in range(warm):
        fn()
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    note(f"platform={dev.platform}")

    # 1. upload: device_put of numpy, synced by a 1-element pull
    @jax.jit
    def first(x):
        return x.reshape(-1)[:8]

    for mb in (0.125, 0.5, 2.0, 8.0):
        a = np.zeros((int(mb * 1e6) // 4,), np.int32)
        per = timeit(lambda: np.asarray(first(jax.device_put(a, dev))))
        note(f"upload {mb:6.3f}MB: {per*1e3:7.2f} ms  "
             f"({mb/per:.1f} MB/s)")

    # 2. download: np.asarray of a device array
    for mb in (0.125, 0.5, 2.0, 8.0):
        d = jax.device_put(np.zeros((int(mb * 1e6) // 4,), np.int32), dev)
        np.asarray(d[:1])
        per = timeit(lambda: np.asarray(d))
        note(f"download {mb:6.3f}MB: {per*1e3:7.2f} ms  "
             f"({mb/per:.1f} MB/s)")

    # 3. per-call: numpy args at config-3 shapes, tiny output, pipelined
    B, L, T, TP, k = 4096, 8, 26, 256, 256
    pw = np.zeros((B, L), np.int32)
    pl = np.zeros(B, np.int32)
    pd = np.zeros(B, bool)
    t_pw = np.zeros((T, TP, L), np.int32)
    t_pl = np.zeros((T, TP), np.int32)
    t_pd = np.zeros((T, TP), bool)
    t_start = np.zeros(T, np.int32)
    args_np = (pw, pl, pd, t_pw, t_pl, t_pd, t_start,
               t_pw.copy(), t_pl.copy(), t_pd.copy(), t_start.copy())
    nbytes = sum(a.nbytes for a in args_np)

    @jax.jit
    def f_small(*a):
        return sum(x.sum(dtype=jnp.int32) for x in a)

    def pipelined(args_fn, n=12):
        acc = None
        f_small(*args_fn())  # warm
        np.asarray(f_small(*args_fn()))
        t0 = time.perf_counter()
        outs = [f_small(*args_fn()) for _ in range(n)]
        acc = outs[0]
        for o in outs[1:]:
            acc = acc + o
        np.asarray(acc)
        return (time.perf_counter() - t0) / n

    note(f"jit call, {len(args_np)} numpy args {nbytes/1e6:.2f}MB, tiny out: "
         f"{pipelined(lambda: args_np)*1e3:7.2f} ms/call")

    args_dev = tuple(jax.device_put(a, dev) for a in args_np)
    jax.block_until_ready(args_dev)
    note(f"jit call, same args device-resident, tiny out: "
         f"{pipelined(lambda: args_dev)*1e3:7.2f} ms/call")

    # one concatenated buffer vs many: is per-buffer overhead the cost?
    flat = np.zeros((nbytes // 4,), np.int32)

    @jax.jit
    def f_flat(x):
        return x.sum(dtype=jnp.int32)

    def one_call():
        return f_flat(jax.device_put(flat, dev))

    np.asarray(one_call())
    t0 = time.perf_counter()
    outs = [one_call() for _ in range(12)]
    acc = outs[0]
    for o in outs[1:]:
        acc = acc + o
    np.asarray(acc)
    note(f"jit call, ONE {nbytes/1e6:.2f}MB numpy arg, tiny out: "
         f"{(time.perf_counter()-t0)/12*1e3:7.2f} ms/call")

    # 4. big outputs (config-3 result shapes), device arg, refs kept,
    # one checksum pull at the end — does output transfer eagerly?
    x = jax.device_put(np.int32(1), dev)

    @jax.jit
    def f_bigout(x):
        gidx = jnp.zeros((B, k), jnp.int32) + x
        gval = jnp.zeros((B, k), bool)
        gcnt = jnp.zeros((B,), jnp.int32) + x
        tidx = jnp.zeros((T, TP, k), jnp.int32) + x
        tval = jnp.zeros((T, TP, k), bool)
        tcnt = jnp.zeros((T, TP), jnp.int32) + x
        return gidx, gval, gcnt, tidx, tval, tcnt, gidx + 1, gval, tcnt

    out_bytes = sum(np.prod(o.shape) * o.dtype.itemsize
                    for o in jax.eval_shape(f_bigout, x))
    f_bigout(x)
    np.asarray(f_bigout(x)[2])
    t0 = time.perf_counter()
    n = 12
    keep = []
    acc = jnp.zeros((), jnp.int32)
    for _ in range(n):
        o = f_bigout(x)
        keep.append(o)
        acc = acc + o[2].sum()
    np.asarray(acc)
    per = (time.perf_counter() - t0) / n
    note(f"jit call, device arg, {out_bytes/1e6:.1f}MB outputs kept as refs, "
         f"checksum pull: {per*1e3:7.2f} ms/call")

    # same but pull ALL outputs each call
    t0 = time.perf_counter()
    for _ in range(6):
        o = f_bigout(x)
        for a in o:
            np.asarray(a)
    per = (time.perf_counter() - t0) / 6
    note(f"jit call, device arg, pull ALL {out_bytes/1e6:.1f}MB outputs: "
         f"{per*1e3:7.2f} ms/call")


if __name__ == "__main__":
    main()
