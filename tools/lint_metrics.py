#!/usr/bin/env python3
"""Static metrics-registry lint (tier-1 pre-test pass, like
lint_blocking.py).

Two invariants, both cheap to break silently and annoying to debug at
scrape time:

1. Every registered metric has non-empty HELP text: the
   ``COUNTERS`` table (broker/metrics.py), the ``STAGE_FAMILIES``
   histogram table (observability/histogram.py), and every literal
   descriptions dict passed to ``Metrics.register_gauges`` — an empty
   description ships a ``# HELP name`` line Prometheus tooling chokes
   on, and the parity tests only cover families they explicitly name.

2. Every ``*.observe("name", ...)`` / ``observe("name", ...)`` call
   site in the tree names a REGISTERED histogram family: a typo'd
   family name raises KeyError on the hot path — in production, under
   load, at the first sampled publish — instead of here.

Exit 0 = clean. Any finding prints file:line and exits 1.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PKG = ROOT / "vernemq_tpu"

#: methods named `observe` that are NOT histogram observations
_OBSERVE_EXEMPT_ATTRS = {"observe_lag"}

#: same opt-out idiom as lint_blocking's allow marker: a delegation
#: seam (Metrics.observe -> histogram.observe, the registry's own
#: dispatch) forwards a dynamic name by design
ALLOW_MARK = "lint: observe-passthrough"


def _const_str(node) -> str | None:
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def _tuple_table(tree: ast.AST, name: str, path: Path, errors: list,
                 what: str) -> set:
    """Collect (name, help) 2-tuple tables like COUNTERS /
    STAGE_FAMILIES; flag entries with empty or non-literal HELP."""
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in targets):
            continue
        value = node.value
        if not isinstance(value, (ast.List, ast.Tuple)):
            continue
        for elt in value.elts:
            if not isinstance(elt, ast.Tuple) or len(elt.elts) < 2:
                errors.append(f"{path}:{elt.lineno}: {what} entry is "
                              "not a (name, help) tuple")
                continue
            metric = _const_str(elt.elts[0])
            # help may be an implicit concat of string constants — the
            # parser folds adjacent literals into one Constant, so a
            # plain _const_str covers the multi-line style used here
            help_text = _const_str(elt.elts[1])
            if metric is None:
                errors.append(f"{path}:{elt.lineno}: {what} name is "
                              "not a string literal")
                continue
            names.add(metric)
            if not help_text or not help_text.strip():
                errors.append(f"{path}:{elt.lineno}: {what} "
                              f"'{metric}' has empty HELP text")
    return names


def _check_gauge_dicts(tree: ast.AST, path: Path, errors: list) -> None:
    """Every literal dict passed to register_gauges(...) must have
    non-empty string values (the HELP text of each gauge)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr == "register_gauges"):
            continue
        cands = list(node.args[1:2]) + [
            kw.value for kw in node.keywords
            if kw.arg == "descriptions"]
        for d in cands:
            if not isinstance(d, ast.Dict):
                continue  # dynamic dict: parity tests cover those names
            for k, v in zip(d.keys, d.values):
                key = _const_str(k) if k is not None else None
                val = _const_str(v)
                if key is None:
                    continue
                if not val or not val.strip():
                    errors.append(f"{path}:{v.lineno}: gauge '{key}' "
                                  "registered with empty HELP text")


def _check_observe_sites(tree: ast.AST, path: Path, families: set,
                         errors: list, allowed_lines: set) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr != "observe" or fn.attr in _OBSERVE_EXEMPT_ATTRS:
                continue
        elif isinstance(fn, ast.Name):
            if fn.id != "observe":
                continue
        else:
            continue
        if node.lineno in allowed_lines:
            continue
        fam = _const_str(node.args[0])
        if fam is None:
            errors.append(f"{path}:{node.lineno}: observe() family is "
                          "not a string literal (cannot verify "
                          "registration statically)")
        elif fam not in families:
            errors.append(f"{path}:{node.lineno}: observe() names "
                          f"unregistered histogram family '{fam}'")


def main() -> int:
    errors: list = []
    metrics_tree = ast.parse(
        (PKG / "broker" / "metrics.py").read_text())
    _tuple_table(metrics_tree, "COUNTERS", PKG / "broker" / "metrics.py",
                 errors, "counter")
    hist_path = PKG / "observability" / "histogram.py"
    families = _tuple_table(ast.parse(hist_path.read_text()),
                            "STAGE_FAMILIES", hist_path, errors,
                            "histogram")
    if not families:
        errors.append(f"{hist_path}: STAGE_FAMILIES table not found")
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            errors.append(f"{path}: unparseable: {e}")
            continue
        allowed = {i for i, line in enumerate(text.splitlines(), 1)
                   if ALLOW_MARK in line}
        _check_gauge_dicts(tree, path, errors)
        _check_observe_sites(tree, path, families, errors, allowed)
    if errors:
        print(f"lint_metrics: {len(errors)} finding(s)")
        for e in errors:
            print(f"  {e}")
        return 1
    print("lint_metrics: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
