#!/usr/bin/env python3
"""Thin compatibility shim: the metrics-registry lint moved into the
unified static-analysis suite (``tools/vmqlint``, the ``metrics``
pass).

Kept so existing invocations keep working; new callers should run
``python -m tools.vmqlint`` (every pass) or
``python -m tools.vmqlint --pass metrics``.  Same exit-code contract:
0 clean, 1 findings.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from tools.vmqlint.core import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--pass", "metrics"]))
