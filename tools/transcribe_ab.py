"""Turn /tmp/tpu_watch outputs (bench.json + tune_*.txt sweeps) into the
README's on-chip A/B markdown table.

The recovery watch (`tools/tpu_watch.sh`) runs the packed-transport
sweeps (B=8192/16384, fa=96, packed_rows) and `bench.py` the moment
the accelerator tunnel answers. This script parses those artifacts and
prints the markdown block to paste into README "Benchmarks", including
the device-resident KERNEL-ONLY rate per geometry and the headline
comparison against the best verified prior number.

  python tools/transcribe_ab.py [--dir /tmp/tpu_watch]
"""
import argparse
import json
import os
import re
import sys

ROW = re.compile(
    r"TP=(?P<tp>\d+) FM=(?P<fm>\d+) B=(?P<b>\d+) FA=(?P<fa>\d+) "
    r"V=(?P<v>\S+): (?P<mps>[\d.]+)M matches/s "
    r"(?P<pps>[\d.]+)k pubs/s batch=(?P<batch>[\d.]+)ms")
KROW = re.compile(
    r"V=\S+ KERNEL-ONLY: (?P<kmps>[\d.]+)M matches/s "
    r"batch=(?P<kbatch>[\d.]+)ms")
BEST = re.compile(r"BEST: (?P<tag>.+?) (?P<mps>[\d.]+)M matches/s")


def load_last_json(path):
    """Last JSON line of an artifact file (bench prints one JSON line;
    stderr noise may precede it)."""
    if not os.path.exists(path):
        return None
    try:
        for line in reversed(open(path).read().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
    except (ValueError, OSError):
        pass
    return None


def parse_sweep(path):
    if not os.path.exists(path):
        return None
    rows, best = [], None
    for line in open(path, errors="replace"):
        m = ROW.search(line)
        if m:
            rows.append(m.groupdict())
            continue
        k = KROW.search(line)
        if k and rows:
            rows[-1].update(k.groupdict())  # attach to its geometry row
        b = BEST.search(line)
        if b:
            best = b.groupdict()
    return {"rows": rows, "best": best}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="/tmp/tpu_watch")
    ap.add_argument("--prior", type=float, default=1.66,
                    help="best verified prior M matches/s (r2)")
    args = ap.parse_args()

    sweeps = {
        "packed B=8192": parse_sweep(
            os.path.join(args.dir, "tune_packed_b8192.txt")),
        "packed B=16384": parse_sweep(
            os.path.join(args.dir, "tune_packed_b16384.txt")),
        "packed B=8192 fa=96": parse_sweep(
            os.path.join(args.dir, "tune_packed_fa96.txt")),
        "packed_rows B=4096": parse_sweep(
            os.path.join(args.dir, "tune_packed_rows.txt")),
    }
    bench = load_last_json(os.path.join(args.dir, "bench.json"))

    print("### On-chip kernel A/B (1M subs, tools/tune_windowed.py)\n")
    print("| variant | best config | matches/s | batch ms "
          "| kernel-only matches/s | kernel-only batch ms |")
    print("|---|---|---|---|---|---|")
    any_rows = False
    for name, sweep in sweeps.items():
        if not sweep or not sweep["rows"]:
            print(f"| {name} | (sweep missing/failed) | — | — | — | — |")
            continue
        any_rows = True
        top = max(sweep["rows"], key=lambda r: float(r["mps"]))
        km = (f"{float(top['kmps']):.2f}M" if "kmps" in top else "—")
        kb = (f"{float(top['kbatch']):.1f}" if "kbatch" in top else "—")
        print(f"| {name} | TP={top['tp']} B={top['b']} FM={top['fm']} "
              f"FA={top['fa']} | {float(top['mps']):.2f}M | "
              f"{float(top['batch']):.1f} | {km} | {kb} |")
    print()
    if bench is not None:
        v = bench.get("value", 0)
        print(f"bench.py headline: **{v:,} matches/s** "
              f"({bench.get('metric', '?')}; platform="
              f"{bench.get('platform')}, fallback="
              f"{bench.get('platform_fallback')}) — "
              f"{v / (args.prior * 1e6):.2f}x the best verified prior "
              f"({args.prior}M, r2).")
        if "kernel_matches_per_sec" in bench:
            k = bench["kernel_matches_per_sec"]
            print(f"device-resident kernel rate: **{k:,} matches/s** "
                  f"(vs_baseline_kernel="
                  f"{bench.get('vs_baseline_kernel')}) — the chip's own "
                  f"ceiling with zero per-batch transport.")
    # stacked-transport point (r5: N batches/executable, ONE result pull)
    stacked = load_last_json(os.path.join(args.dir, "bench_stacked.json"))
    if stacked is not None:
        c3 = stacked.get("configs", {}).get("3_mixed_1m_zipf", {})
        if "n_stack" in c3:
            print(f"stacked transport (--variant packed_stack, "
                  f"N={c3['n_stack']}): "
                  f"**{round(c3.get('matches_per_sec', 0)):,} matches/s** "
                  f"({round(c3.get('publishes_per_sec', 0)):,} pubs/s, "
                  f"batch {c3.get('batch_ms')}ms, group "
                  f"{c3.get('group_ms')}ms) — per-dispatch RTTs "
                  f"amortised over the group.")
    if not any_rows and bench is None and stacked is None:
        print("No artifacts found — has the recovery watch fired? "
              f"(dir: {args.dir})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
