"""Device-regime added-latency measurement (VERDICT r4 item 3).

Drives the production collector + device path at a PACED arrival rate —
in-process, no sockets: the object under test is the BatchCollector →
TpuMatcher pipeline (window close, host prep, device dispatch, result
scatter), i.e. everything between `reg.publish`'s fold call and its
match rows. The host-trie column runs the SAME arrival process against
the synchronous trie fold (the reference's inline fold,
``vmq_reg.erl:257-319``) so "added latency" is a like-for-like delta on
one corpus and one probe distribution.

At arrival rates below the hybrid threshold the collector serves
flushes host-side by design (hybrid dispatch) — the interesting regime
starts where device batches actually form. Use ``--rates`` to ladder
through arrival rates and read where the device engages
(``served_device_pubs`` vs ``host_hybrid_pubs``).

Usage:
  python tools/collector_latency.py [--subs 1000000] [--secs 10]
      [--rates 2000,10000,40000,80000] [--window-us 200]
      [--max-batch 4096] [--seed 42] [--json out.json]

On the CPU backend this is a correctness stand-in (the device is ~100x
slower than the chip); the judge-facing numbers come from a TPU run.
"""
import argparse
import asyncio
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def pctl(vals, q):
    return float(np.percentile(np.asarray(vals), q)) if vals else 0.0


class _FakeRegistry:
    """The two seams BatchCollector/TpuRegView touch: the host trie (shed
    + hybrid target) and the warm-load iterator."""

    def __init__(self, trie):
        self._trie = trie

    def trie(self, mountpoint=""):
        return self._trie

    def fold_subscriptions(self, mountpoint=""):
        return iter(())  # matcher is injected pre-built; nothing to load


async def drive(submit, topics_iter, rate: float, secs: float):
    """Paced arrival process: ``rate`` submissions/s for ``secs``;
    returns (latencies_s, submitted, completed). Pacing measures
    broker-ADDED latency, not self-inflicted queueing."""
    lat = []
    inflight = set()
    interval = 1.0 / rate
    t_end = time.perf_counter() + secs
    next_at = time.perf_counter()
    submitted = 0

    while time.perf_counter() < t_end:
        now = time.perf_counter()
        if now < next_at:
            await asyncio.sleep(next_at - now)
        else:
            # behind schedule: STILL yield — holding the loop starves
            # the collector's window timer and the executor completion
            # callbacks, charging driver-induced delay to the device
            # column (the synchronous trie column has no such timers)
            await asyncio.sleep(0)
        next_at += interval
        topic = next(topics_iter)
        t0 = time.perf_counter()
        res = submit(topic)
        if asyncio.isfuture(res):
            inflight.add(res)
            res.add_done_callback(
                lambda f, t0=t0: (inflight.discard(f),
                                  lat.append(time.perf_counter() - t0)))
        else:
            lat.append(time.perf_counter() - t0)
        submitted += 1
    if inflight:
        await asyncio.gather(*inflight, return_exceptions=True)
    return lat, submitted


async def main_async(args) -> None:
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from bench import build_corpus, zipf_topics
    from vernemq_tpu.models.tpu_matcher import (BatchCollector, TpuMatcher,
                                                TpuRegView)
    from vernemq_tpu.models.tpu_table import SubscriptionTable
    from vernemq_tpu.models.trie import SubscriptionTrie

    platform = jax.devices()[0].platform
    rng = random.Random(args.seed)
    n = args.subs if platform != "cpu" else min(args.subs, 50_000)
    table = SubscriptionTable(max_levels=8,
                              initial_capacity=1 << (n - 1).bit_length())
    t0 = time.perf_counter()
    pools = build_corpus(rng, n, table)
    trie = SubscriptionTrie()
    for e in table.entries:
        if e is not None:
            trie.add(list(e[0]), e[1], e[2])
    print(f"# corpus {n} subs built in {time.perf_counter()-t0:.1f}s "
          f"(platform={platform})", file=sys.stderr, flush=True)

    m = TpuMatcher(max_levels=table.L, initial_capacity=16,
                   max_fanout=args.max_fanout, flat_avg=args.flat_avg)
    m.table = table
    table.resized = True
    with m.lock:
        m.sync()
    m.async_rebuild = True  # production posture from here on
    view = TpuRegView(_FakeRegistry(trie))
    view._matchers[""] = m  # inject the pre-built matcher (no warm-load)
    t0 = time.perf_counter()
    shapes = m.warm_ladder(args.max_batch)
    print(f"# warm ladder: {shapes} shapes in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr, flush=True)

    results = []
    for rate in args.rates:
        # fresh collector per rate (clean stats)
        col = BatchCollector(view, window_us=args.window_us,
                             max_batch=args.max_batch,
                             host_threshold=args.host_threshold,
                             lock_busy_shed_ms=args.lock_busy_shed_ms)
        topics = iter(lambda: zipf_topics(rng, pools, 1)[0], None)
        # trie column first (same arrival process, synchronous fold)
        tr_lat, tr_n = await drive(
            lambda t: trie.match(list(t)), topics, rate, args.secs)
        dv_lat, dv_n = await drive(
            lambda t: col.submit("", t), topics, rate, args.secs)
        dev_pubs = (m.match_publishes
                    - getattr(m, "_lat_prev_pubs", 0))
        m._lat_prev_pubs = m.match_publishes
        row = {
            "rate_pubs_per_sec": rate,
            "achieved_trie_rate": round(tr_n / args.secs),
            "achieved_device_rate": round(dv_n / args.secs),
            "trie_ms_p50": round(1e3 * pctl(tr_lat, 50), 3),
            "trie_ms_p99": round(1e3 * pctl(tr_lat, 99), 3),
            "device_ms_p50": round(1e3 * pctl(dv_lat, 50), 3),
            "device_ms_p99": round(1e3 * pctl(dv_lat, 99), 3),
            "added_ms_p50": round(1e3 * (pctl(dv_lat, 50)
                                         - pctl(tr_lat, 50)), 3),
            "added_ms_p99": round(1e3 * (pctl(dv_lat, 99)
                                         - pctl(tr_lat, 99)), 3),
            "served_device_pubs": dev_pubs,
            "host_hybrid_pubs": col.host_hybrid_pubs,
            "busy_host_pubs": col.busy_host_pubs,
            "rebuild_host_pubs": col.rebuild_host_pubs,
            "overload_host_pubs": col.overload_host_pubs,
        }
        results.append(row)
        print(json.dumps(row), flush=True)

    out = {"platform": platform, "subs": n, "window_us": args.window_us,
           "max_batch": args.max_batch,
           "host_threshold": args.host_threshold, "rows": results}
    if args.json:
        # vmqlint: allow(blocking): one-shot artifact write AFTER the
        # measurement loops; nothing else shares this harness loop
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--subs", type=int, default=1_000_000)
    ap.add_argument("--secs", type=float, default=10.0)
    ap.add_argument("--rates", default="2000,10000,40000,80000",
                    type=lambda s: [int(x) for x in s.split(",")])
    ap.add_argument("--window-us", type=int, default=200)
    ap.add_argument("--max-batch", type=int, default=4096)
    ap.add_argument("--max-fanout", type=int, default=256)
    ap.add_argument("--flat-avg", type=int, default=128)
    ap.add_argument("--host-threshold", type=int, default=8)
    ap.add_argument("--lock-busy-shed-ms", type=int, default=500)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    asyncio.run(main_async(args))


if __name__ == "__main__":
    main()
