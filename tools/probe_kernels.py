"""Candidate kernel formulations for the v3 match path, timed on the real
chip at a realistic 1M-sub table (bench corpus shape).

Variants:
  V1: current full-scan coded matmul + extract_indices_packed(block=2048)
  V2: full-scan coded matmul + CHEAP extraction (matvec block counts +
      small triangular cumsum) at several block sizes
  V3: count-only full-scan (lower bound: matmul + pack + popcount-sum)
  V4: chunked-table batched einsum (single-bucket tiles) count-only
All at B in {2048, 8192}.
"""
import functools
import random
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bench import build_corpus, zipf_topics
    from vernemq_tpu.models.tpu_table import SubscriptionTable
    from vernemq_tpu.ops import match_kernel as K

    subs = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    rng = random.Random(42)
    table = SubscriptionTable(max_levels=8,
                              initial_capacity=1 << (subs - 1).bit_length())
    t0 = time.perf_counter()
    pools = build_corpus(rng, subs, table)
    note(f"corpus {time.perf_counter()-t0:.1f}s")
    dev = jax.devices()[0]
    put = lambda a: jax.device_put(a, dev)
    note(f"platform={dev.platform}")
    arrays = (put(table.words), put(table.eff_len), put(table.has_hash),
              put(table.first_wild), put(table.active))
    bits = table.id_bits
    F_t, t1 = K.build_operands(arrays[0], arrays[1], bits)
    S = int(arrays[0].shape[0])
    note(f"S={S} NB={table.NB} bits={bits}")
    eff, hh, fw, act = arrays[1], arrays[2], arrays[3], arrays[4]

    def enc(B):
        topics = zipf_topics(rng, pools, B)
        pw = np.full((B, table.L), K.PAD_ID, dtype=np.int32)
        pl = np.zeros(B, dtype=np.int32)
        pd = np.zeros(B, dtype=bool)
        pb = np.zeros(B, dtype=np.int32)
        for i, t in enumerate(topics):
            row, n, dollar, b = table.encode_topic_ex(t)
            pw[i], pl[i], pd[i], pb[i] = row, n, dollar, b
        return pw, pl, pd, pb

    def mask_full(pw, pl, pd):
        G = K.build_pub_operand(pw, bits)
        mm = lax.dot_general(G, F_t, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        return (mm + t1[None, :] == 0.0) & K._epilogue(pl, pd, eff, hh, fw, act)

    # -- V1: current extraction
    @functools.partial(jax.jit, static_argnames=("k",))
    def v1(pw, pl, pd, k=256):
        m = mask_full(pw, pl, pd)
        i, v, c = K.extract_indices_packed(K._pack_mask(m), k, 2048)
        return i.sum() + c.sum()

    # -- V2: cheap extraction
    def extract_cheap(packed, k, block):
        B, W = packed.shape
        wpb = block // 32
        nblk = W // wpb
        pc = lax.population_count(packed).astype(jnp.float32)
        # per-block counts: [B*nblk, wpb] @ ones  (matvec, 2BW flops)
        blk_cnt = lax.dot_general(
            pc.reshape(B * nblk, wpb).astype(jnp.bfloat16),
            jnp.ones((wpb, 1), jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).reshape(B, nblk)
        # inclusive cumsum over nblk via small triangular matmul — but counts
        # can exceed bf16 exactness (<=block<=8192 ok: ints to 256 only are
        # exact in bf16! counts up to block=2048 NOT bf16-exact) → f32 matmul
        tri = (jnp.arange(nblk)[:, None] <= jnp.arange(nblk)[None, :])
        blk_cum = lax.dot_general(
            blk_cnt, tri.astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.int32)
        count = blk_cum[:, -1]
        targets = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None, :],
                                   (B, k))
        blk = jnp.sum((blk_cum[:, None, :] <= targets[:, :, None])
                      .astype(jnp.int32), axis=2)
        blk_c = jnp.minimum(blk, nblk - 1)
        prev_cum = jnp.where(
            blk_c > 0,
            jnp.take_along_axis(blk_cum, jnp.maximum(blk_c - 1, 0), axis=1), 0)
        offset = targets - prev_cum
        words = jnp.take_along_axis(
            packed.reshape(B, nblk, wpb), blk_c[:, :, None], axis=1)
        wpc = lax.population_count(words).astype(jnp.int32)
        tri2 = (jnp.arange(wpb)[:, None] <= jnp.arange(wpb)[None, :])
        wcum = lax.dot_general(
            wpc.reshape(B * k, wpb).astype(jnp.bfloat16),
            tri2.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.int32).reshape(B, k, wpb)
        widx = jnp.sum((wcum <= offset[:, :, None]).astype(jnp.int32), axis=2)
        widx_c = jnp.minimum(widx, wpb - 1)
        prior = jnp.where(
            widx_c > 0,
            jnp.squeeze(jnp.take_along_axis(
                wcum, jnp.maximum(widx_c - 1, 0)[:, :, None], axis=2), 2), 0)
        bit_rank = offset - prior
        word = jnp.squeeze(
            jnp.take_along_axis(words, widx_c[:, :, None], axis=2), 2)
        p_range = jnp.arange(32, dtype=jnp.uint32)
        below = (jnp.uint32(1) << p_range) - jnp.uint32(1)
        cnt_below = lax.population_count(
            word[:, :, None] & below[None, None, :]).astype(jnp.int32)
        bit_set = ((word[:, :, None] >> p_range[None, None, :]) & 1).astype(jnp.int32)
        ind = (cnt_below == bit_rank[:, :, None]) & (bit_set == 1)
        pos_bit = jnp.sum(jnp.arange(32, dtype=jnp.int32)[None, None, :]
                          * ind.astype(jnp.int32), axis=2)
        idx = blk_c * block + widx_c * 32 + pos_bit
        valid = targets < count[:, None]
        return idx.astype(jnp.int32), valid, count

    def mk_v2(block):
        @functools.partial(jax.jit, static_argnames=("k",))
        def v2(pw, pl, pd, k=256):
            m = mask_full(pw, pl, pd)
            i, v, c = extract_cheap(K._pack_mask(m), k, block)
            return i.sum() + c.sum()
        return v2

    # -- V3: count-only lower bound
    @jax.jit
    def v3(pw, pl, pd):
        m = mask_full(pw, pl, pd)
        pk = K._pack_mask(m)
        return lax.population_count(pk).sum(dtype=jnp.int32)

    def bench(fn, args, iters=20, label=""):
        np.asarray(fn(*args))
        t0 = time.perf_counter()
        acc = jnp.zeros((), jnp.int32)
        for _ in range(iters):
            acc = acc + fn(*args)
        np.asarray(acc)
        per = (time.perf_counter() - t0) / iters
        B = args[0].shape[0]
        note(f"{label}: {per*1e3:.2f} ms/batch -> {B/per/1e3:.0f}k pubs/s")
        return per

    for B in (2048, 8192):
        pw, pl, pd, pb = enc(B)
        a = (put(pw), put(pl), put(pd))
        bench(v3, a, label=f"V3 count-only      B={B}")
        bench(v1, a, label=f"V1 cur extract     B={B}")
        for blk in (2048, 8192):
            bench(mk_v2(blk), a, label=f"V2 cheap blk={blk:5d} B={B}")


if __name__ == "__main__":
    main()
