#!/usr/bin/env bash
# Canonical tier-1 verification (the exact command ROADMAP.md specifies,
# encapsulated so CI and humans run the same thing).
#
#   tools/run_tier1.sh                 # tier-1: everything but -m slow
#   tools/run_tier1.sh -m chaos        # your -m replaces the marker filter
#   tools/run_tier1.sh -k spool -x     # other args pass through, tier-1
#                                      # marker filter kept
#
# Exits with pytest's status; prints DOTS_PASSED=<n> for the driver.
# Chaos/soak tests are opt-in: they carry BOTH the `chaos` and `slow`
# markers, so tier-1's `-m 'not slow'` excludes them (run them with
# `tools/run_tier1.sh -m chaos`, or set TIER1_CHAOS=1 to append the
# chaos leg after a green tier-1 run).
set -o pipefail
cd "$(dirname "$0")/.."

LOG=${TIER1_LOG:-/tmp/_t1.log}
TIMEOUT=${TIER1_TIMEOUT:-870}
if [ $# -gt 0 ]; then
  case " $* " in
    *" -m "*|*" -m="*|*" --markers "*) EXTRA=("$@") ;;
    *) EXTRA=(-m 'not slow' "$@") ;;
  esac
else
  EXTRA=(-m 'not slow')
fi

# best-effort native build (wire codec + kvstore/counters/fence): the
# loaders build on demand anyway, but warming here keeps the first
# test that touches the codec from paying the compile inside its own
# timeout. Skips cleanly when no toolchain is present — every native
# consumer has a bit-identical pure-Python fallback.
if command -v g++ >/dev/null 2>&1 || command -v c++ >/dev/null 2>&1; then
  make -C native >/dev/null 2>&1 || true
fi

# loaded-codec version assertion: when the warmup produced a wire-codec
# extension, its baked-in FASTPATH_VERSION must match the source header
# — a stale .so served from the build cache would otherwise shadow a
# contract bump and every "native" test result would be a lie. The
# runtime loader enforces min_version too; this catches it BEFORE 700
# tests run against the wrong module. (Skips cleanly when the codec
# didn't build: the pure twin is the contract then.)
python - <<'PYEOF' || exit 1
import re, sys
from vernemq_tpu.protocol import fastpath

mod = fastpath.load_native()
if mod is not None:
    src = open("native/codec.cc", encoding="utf-8").read()
    m = re.search(r"FASTPATH_VERSION\s*=\s*(\d+)", src)
    want = int(m.group(1))
    got = getattr(mod, "FASTPATH_VERSION", None)
    if got != want or want != fastpath.REQUIRED_VERSION:
        sys.exit(f"stale wire codec: loaded FASTPATH_VERSION={got}, "
                 f"source header says {want}, loader requires "
                 f"{fastpath.REQUIRED_VERSION} — rebuild native/")
PYEOF

# pre-test static gate: the unified vmqlint suite (tools/vmqlint) —
# blocking calls in async bodies, metric-registry HELP/observe names,
# lock discipline (no device/compile/IO under a threading lock),
# thread lifecycle (every started thread joined/cancelled from close),
# knob registry (config reads <-> DEFAULTS <-> schema aliases agree),
# fault-point/breaker-path registry (inject sites and admin drills
# can't drift). A regression in any defect class fails tier-1 before a
# single test runs. Fast local iteration: `python -m tools.vmqlint
# --changed` scopes the file-level passes to the git working-set.
python -m tools.vmqlint || exit 1

# hung-test forensics: faulthandler dumps every thread's stack just
# below the outer timeout wall (tests/conftest.py arms it), so a wedged
# test prints WHERE it hung instead of dying silently at the kill.
# Short walls keep a small margin so the dump still beats the SIGTERM;
# non-positive disables (conftest skips arming).
DUMP_S=${TIER1_FAULTHANDLER_S:-$((TIMEOUT > 60 ? TIMEOUT - 30 : TIMEOUT - 5))}

rm -f "$LOG"
timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
  TIER1_FAULTHANDLER_S="$DUMP_S" \
  python -m pytest tests/ -q "${EXTRA[@]}" \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
  -p no:randomly 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

# opt-in chaos leg (TIER1_CHAOS=1): after a green tier-1 run, also run
# the fault-injection soaks (`-m chaos` — partition storms, handoff
# bounce, filter/watchdog chaos). Kept out of the default gate because
# the soaks are long; CI jobs that want the full robustness sweep set
# the env var instead of remembering a second command.
if [ "${TIER1_CHAOS:-0}" = "1" ] && [ "$rc" -eq 0 ]; then
  CLOG=${TIER1_CHAOS_LOG:-/tmp/_t1_chaos.log}
  rm -f "$CLOG"
  timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
    TIER1_FAULTHANDLER_S="$DUMP_S" \
    python -m pytest tests/ -q -m chaos \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee "$CLOG"
  rc=${PIPESTATUS[0]}
  cat "$CLOG" >> "$LOG"
fi

echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
exit "$rc"
