"""Roofline arithmetic for the windowed match kernel (VERDICT r4 item 1:
"write the arithmetic: bytes touched per batch vs HBM bandwidth at the
current geometry, and state whether THIS kernel formulation can reach
10M matches/s").

Builds the bench corpus at the requested scale, derives the EXACT kernel
geometry the production matcher would use for the batch size (same
window_params/_geometry code path), and counts the HBM bytes and MXU
FLOPs each batch touches:

- dense phase: Fg [K, glob] bf16 re-streamed per pub chunk (gc pubs at a
  time), plus t1/epilogue vectors per chunk;
- probe-A/B tiles: each of T (T2) tiles streams a [K, seg_max] (seg2)
  operand window + epilogue vectors;
- intermediates: the [TP, seg] f32 mismatch block per tile and the
  [gc, glob] dense block — XLA fuses the compare+pack, so these are
  compute-layer traffic that mostly stays in VMEM/registers; the model
  counts them at a configurable reuse discount (default 0: fused);
- outputs: the packed flat result vector (Bpad*(fa+3) int32).

Ceilings: matches/s <= avg_fanout * Bpad / max(bytes/BW, flops/FLOPS).
v5e defaults: 819 GB/s HBM, 197 TFLOP/s bf16.

The measured companion is bench.py --kernel-only (match_packed_scan —
zero per-batch transport); this file is the analytical half of
ROOFLINE.md. Runs fine on CPU: it executes no kernel, it only sizes one.
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_probe(args) -> None:
    """The MEASURED half of the amortization story: run the
    kernel-resident multi-batch probe (bench.match_many_probe — K
    batches per scanned executable, donated staging) standalone, at
    smoke scale on CPU or full scale on an accelerator. This is the
    empirical companion to the analytic model below: dispatch cost
    amortizes as dispatch/K + kernel_cost per batch."""
    import random as _random

    from bench import WindowedBench, build_corpus, init_backend, \
        match_many_probe
    from vernemq_tpu.models.tpu_table import SubscriptionTable

    jax_mod, devices, fallback = init_backend()
    platform = devices[0].platform
    smoke = platform == "cpu"
    subs = min(args.subs, 100_000) if smoke else args.subs
    batch = args.probe_batch or (min(args.batch, 256) if smoke
                                 else args.batch)
    rng = _random.Random(args.seed)
    table = SubscriptionTable(
        max_levels=args.levels,
        initial_capacity=1 << (subs - 1).bit_length())
    t0 = time.perf_counter()
    pools = build_corpus(rng, subs, table)
    print(f"# corpus built in {time.perf_counter()-t0:.0f}s",
          file=sys.stderr, flush=True)
    wb = WindowedBench(jax_mod, table, pools, rng, batch,
                       variant="packed")
    ks = tuple(int(x) for x in args.probe_ks.split(",") if x.strip())
    out = match_many_probe(wb, ks=ks, reps=args.probe_reps,
                           probe_batch=batch)
    out.update({"mode": "measured_match_many_probe",
                "platform": platform, "platform_fallback": fallback,
                "subs": subs, "batch": batch})
    print(json.dumps(out, indent=1))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--subs", type=int, default=1_000_000)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--levels", type=int, default=8)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--hbm-gbps", type=float, default=819.0)
    ap.add_argument("--bf16-tflops", type=float, default=197.0)
    ap.add_argument("--fanout", type=float, default=None,
                    help="avg matches/pub (default: measured on a "
                         "5k-topic host-trie probe of the corpus)")
    ap.add_argument("--flat-avg", type=int, default=128)
    ap.add_argument("--intermediate-factor", type=float, default=0.0,
                    help="fraction of the [pubs, seg] f32 mismatch "
                         "blocks charged to HBM (0 = fully fused)")
    ap.add_argument("--probe", action="store_true",
                    help="RUN the kernel-resident match_many dispatch-"
                         "amortization probe (K-batch ladder, measured) "
                         "instead of the analytic model; smoke-scales "
                         "on CPU")
    ap.add_argument("--probe-ks", default="1,2,4,8,16")
    ap.add_argument("--probe-reps", type=int, default=2)
    ap.add_argument("--probe-batch", type=int, default=None)
    args = ap.parse_args()

    if args.probe:
        run_probe(args)
        return

    import jax

    jax.config.update("jax_platforms", "cpu")
    from bench import build_corpus, host_trie_like_for_like
    from vernemq_tpu.models.tpu_matcher import TILE_PUBS, window_params
    from vernemq_tpu.models.tpu_table import SubscriptionTable

    rng = random.Random(args.seed)
    table = SubscriptionTable(
        max_levels=args.levels,
        initial_capacity=1 << (args.subs - 1).bit_length())
    t0 = time.perf_counter()
    pools = build_corpus(rng, args.subs, table)
    print(f"# corpus built in {time.perf_counter()-t0:.0f}s",
          file=sys.stderr, flush=True)

    S = table.cap
    L = table.L
    bits = table.id_bits
    K = (5 if bits == 16 else 6) * L  # build_operands planes
    glob = table.reg_cap[0]
    gb_end = table.gb_end
    ng = table.NG
    reg_start = table.reg_start
    reg_end = table.reg_start + table.reg_cap
    Bpad = args.batch
    TP = TILE_PUBS

    amax = (int((reg_end[1 + ng:] - reg_start[1 + ng:]).max())
            if len(reg_start) > 1 + ng else 0)
    T, seg_max, gc = window_params(S, int(glob), amax, Bpad,
                                   zone=S - gb_end)
    if ng:  # same guard as TpuMatcher._geometry
        gmax = int((reg_end[1:1 + ng] - reg_start[1:1 + ng]).max())
        T2, seg2, _ = window_params(S, int(glob), gmax, Bpad,
                                    zone=gb_end - int(glob))
    else:
        T2, seg2 = 0, 0

    BF, F32 = 2, 4
    epi = 4 + 1 + 1 + 1  # eff i32 + hh/fw/act bool per row
    row_bytes = K * BF + F32 + epi  # one streamed table row

    # dense phase: REGION 0 ONLY ([K, glob_pad] — the both-levels-wild
    # filters; the g-bucket zone [glob, gb_end) is served by the probe-B
    # tiles, charged below), re-streamed once per gc-chunk
    n_chunks = -(-Bpad // gc)
    dense_bytes = n_chunks * int(glob) * row_bytes
    # probe tiles: one operand window per tile
    probeA_bytes = T * seg_max * row_bytes
    probeB_bytes = T2 * seg2 * row_bytes
    out_bytes = Bpad * (args.flat_avg + 3) * F32
    pub_bytes = Bpad * (L * F32 + 16)
    inter_bytes = args.intermediate_factor * F32 * (
        n_chunks * gc * int(glob) + (T * TP * seg_max) + (T2 * TP * seg2))
    total_bytes = (dense_bytes + probeA_bytes + probeB_bytes + out_bytes
                   + pub_bytes + inter_bytes)

    flops = 2 * K * (Bpad * int(glob) + T * TP * seg_max
                     + T2 * TP * seg2)

    t_hbm = total_bytes / (args.hbm_gbps * 1e9)
    t_mxu = flops / (args.bf16_tflops * 1e12)
    t_batch = max(t_hbm, t_mxu)

    if args.fanout is None:
        probe = host_trie_like_for_like(table, pools, args.seed + 103,
                                        n_probe=5000)
        fanout = probe["trie_avg_fanout"]
    else:
        fanout = args.fanout

    pubs_per_sec = Bpad / t_batch
    matches_per_sec = fanout * pubs_per_sec
    out = {
        "subs": args.subs, "S_padded": int(S), "K": K, "id_bits": bits,
        "geometry": {"Bpad": Bpad, "gb_end": int(gb_end),
                     "glob": int(glob), "T": int(T),
                     "seg_max": int(seg_max), "gc": int(gc),
                     "T2": int(T2), "seg2": int(seg2),
                     "dense_chunks": n_chunks},
        "bytes_per_batch": {
            "dense": int(dense_bytes), "probeA": int(probeA_bytes),
            "probeB": int(probeB_bytes), "outputs": int(out_bytes),
            "pubs": int(pub_bytes), "intermediates": int(inter_bytes),
            "total": int(total_bytes)},
        "flops_per_batch": int(flops),
        "batch_ms_hbm_bound": round(t_hbm * 1e3, 3),
        "batch_ms_mxu_bound": round(t_mxu * 1e3, 3),
        "bound": "hbm" if t_hbm >= t_mxu else "mxu",
        "avg_fanout": fanout,
        "ceiling_pubs_per_sec": round(pubs_per_sec),
        "ceiling_matches_per_sec": round(matches_per_sec),
        "reaches_10M_matches": matches_per_sec >= 10e6,
        "hbm_gbps": args.hbm_gbps, "bf16_tflops": args.bf16_tflops,
    }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
