"""Churn soak for the multi-device seat (ShardedTpuMatcher): sustained
subscribe/unsubscribe + batched matching on the virtual CPU mesh, with
continuous host-trie parity checks — the BASELINE config-5 delta-stream
discipline applied to the seat the broker serves through when
``tpu_mesh`` is set.

Usage: python tools/seat_churn.py [--secs 240] [--subs 20000]
           [--mesh 2x4] [--batch 64] [--churn 50]
Prints one JSON line: rounds, publishes matched, parity failures (must
be 0), match latency percentiles (round 0 reported separately as
compile_ms — it is the XLA compile + full device build) and
RebuildInProgress sheds (the seat runs with the production
async_rebuild posture).
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--secs", type=float, default=240.0)
    ap.add_argument("--subs", type=int, default=20_000)
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--churn", type=int, default=50,
                    help="adds+removes per round")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()

    import re

    flags = os.environ.get("XLA_FLAGS", "")
    want = "--xla_force_host_platform_device_count=8"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       want, flags)
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from vernemq_tpu.models.trie import SubscriptionTrie
    from vernemq_tpu.parallel.mesh import make_mesh
    from vernemq_tpu.parallel.sharded_match import ShardedTpuMatcher

    b, s = (int(x) for x in args.mesh.split("x"))
    mesh = make_mesh(jax.devices()[:b * s], batch=b)
    seat = ShardedTpuMatcher(mesh, max_levels=8, max_fanout=128)
    seat.async_rebuild = True  # production posture: growth sheds, not stalls
    trie = SubscriptionTrie()
    rng = random.Random(args.seed)
    l0 = [f"r{i}" for i in range(32)]
    l1 = [f"d{i}" for i in range(64)]
    l2 = [f"m{i}" for i in range(16)]

    def rand_filter():
        r = rng.random()
        w = [rng.choice(l0), rng.choice(l1), rng.choice(l2)]
        if r < 0.6:
            return w
        if r < 0.8:
            return [w[0], "+", w[2]]
        if r < 0.9:
            return ["+", w[1], w[2]]
        return [w[0], w[1], "#"]

    live = {}
    with seat.lock:
        for i in range(args.subs):
            f = rand_filter()
            seat.table.add(list(f), i, None)
            trie.add(list(f), i, None)
            live[i] = f
    next_key = args.subs

    from vernemq_tpu.models.tpu_matcher import RebuildInProgress

    t_end = time.time() + args.secs
    rounds = pubs = fails = sheds = 0
    match_ms = []
    compile_ms = []
    while time.time() < t_end:
        # churn: add + remove args.churn subscriptions
        with seat.lock:
            for _ in range(args.churn):
                f = rand_filter()
                seat.table.add(list(f), next_key, None)
                trie.add(list(f), next_key, None)
                live[next_key] = f
                next_key += 1
            for k in rng.sample(sorted(live), args.churn):
                f = live.pop(k)
                seat.table.remove(list(f), k)
                trie.remove(list(f), k)
        topics = [(rng.choice(l0), rng.choice(l1), rng.choice(l2))
                  for _ in range(args.batch)]
        t0 = time.perf_counter()
        try:
            res = seat.match_batch(topics)  # sync() applies the delta
        except RebuildInProgress:
            # production shed: the trie would serve; here we just wait
            # for the background install and count the shed
            sheds += 1
            time.sleep(0.2)
            continue
        dt = time.perf_counter() - t0
        (compile_ms if rounds == 0 else match_ms).append(dt * 1e3)
        for t, rows in zip(topics, res):
            got = sorted(k for _, k, _ in rows)
            want = sorted(k for _, k, _ in trie.match(list(t)))
            if got != want:
                fails += 1
        pubs += len(topics)
        rounds += 1
    out = {
        "rounds": rounds, "publishes": pubs, "parity_failures": fails,
        "resident_subs": len(live), "churn_per_round": 2 * args.churn,
        "match_ms_p50": round(float(np.percentile(match_ms, 50)), 1)
        if match_ms else None,
        "match_ms_p99": round(float(np.percentile(match_ms, 99)), 1)
        if match_ms else None,
        "compile_ms": round(compile_ms[0], 1) if compile_ms else None,
        "mesh": args.mesh, "host_fallback_pubs": seat.host_fallbacks,
        "rebuild_sheds": sheds, "async_rebuilds": seat.rebuilds_async,
    }
    print(json.dumps(out))
    sys.exit(1 if fails else 0)


if __name__ == "__main__":
    main()
