"""Parameter sweep for the flat windowed kernel on the real chip:
batch size x tile width x window-fairness x flat capacity. Prints one
line per config; run after any kernel change.

Usage:
  python tools/tune_windowed.py [subs] [--cpu] [--rows | --pallas]
      [--tp 128,256] [--b 2048,4096,8192] [--fm 1,2,4] [--fa 128]

Each axis takes a comma list; the grid is their product. Keep the grid
small on a tunnel — every distinct (TP, B, FM) geometry is a fresh
compile (~30-60s).
"""
import random
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def _axis(argv, name, default):
    flag = f"--{name}"
    if flag in argv:
        i = argv.index(flag)
        vals = [int(x) for x in argv[i + 1].split(",")]
        del argv[i:i + 2]
        return vals
    return default


def main():
    argv = sys.argv[1:]
    if "--cpu" in argv:
        argv.remove("--cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")
    variant = "flat"
    if "--rows" in argv:  # gather-merge kernel instead of scatter-flat
        argv.remove("--rows")
        variant = "rows"
    if "--pallas" in argv:  # fused Pallas tile matcher (probe phases)
        argv.remove("--pallas")
        variant = "pallas"
    if "--packed" in argv:  # single-vector I/O transport (production)
        argv.remove("--packed")
        variant = "packed"
    if "--packed-rows" in argv:  # single-vector I/O over the rows kernel
        argv.remove("--packed-rows")
        variant = "packed_rows"
    tps = _axis(argv, "tp", [128, 256])
    bs = _axis(argv, "b", [2048, 4096, 8192])
    fms = _axis(argv, "fm", [2])
    fas = _axis(argv, "fa", [128])
    import jax

    from bench import WindowedBench, build_corpus
    from vernemq_tpu.models import tpu_matcher as TM
    from vernemq_tpu.models.tpu_table import SubscriptionTable

    subs = int(argv[0]) if argv else 1_000_000
    rng = random.Random(42)
    table = SubscriptionTable(max_levels=8,
                              initial_capacity=1 << (subs - 1).bit_length())
    t0 = time.perf_counter()
    pools = build_corpus(rng, subs, table)
    note(f"corpus {time.perf_counter()-t0:.1f}s platform="
         f"{jax.devices()[0].platform} grid: TP={tps} B={bs} FM={fms} "
         f"FA={fas}")

    best = None
    for tile_pubs in tps:
        TM.TILE_PUBS = tile_pubs
        for fm in fms:
            TM.FAIR_MULT = fm
            for B in bs:
                for fa in fas:
                    tag = f"TP={tile_pubs} FM={fm} B={B} FA={fa} V={variant}"
                    try:
                        wb = WindowedBench(jax, table, pools, rng, B, 256,
                                           flat_avg=fa, variant=variant)
                        r = wb.run(20, warmup=8, measure_resolve=False)
                        note(f"{tag}: "
                             f"{r['matches_per_sec']/1e6:.2f}M matches/s "
                             f"{r['publishes_per_sec']/1e3:.0f}k pubs/s "
                             f"batch={r['batch_ms']:.2f}ms "
                             f"enc={r['encode_ms']:.2f} "
                             f"prep={r['prep_ms']:.2f} "
                             f"sync_p50={r['synced_batch_ms_p50']:.1f} "
                             f"left={r['leftover_pubs']} "
                             f"ovf={r['overflow_pubs']}")
                        if variant == "packed":
                            # device-resident rate at this geometry: the
                            # chip's own ceiling, minus the tunnel
                            try:
                                k = wb.run_kernel_only()
                                note(f"{tag} KERNEL-ONLY: "
                                     f"{k['kernel_matches_per_sec']/1e6:.2f}M"
                                     f" matches/s "
                                     f"batch={k['kernel_batch_ms']:.2f}ms "
                                     f"{k['kernel_publishes_per_sec']/1e3:.0f}"
                                     f"k pubs/s")
                            except Exception as e:
                                note(f"{tag} KERNEL-ONLY FAILED: "
                                     f"{type(e).__name__}: {str(e)[:120]}")
                        if best is None or r["matches_per_sec"] > best[0]:
                            best = (r["matches_per_sec"], tag)
                    except Exception as e:
                        note(f"{tag} FAILED: {type(e).__name__}: "
                             f"{str(e)[:120]}")
    if best:
        note(f"BEST: {best[1]} {best[0]/1e6:.2f}M matches/s")


if __name__ == "__main__":
    main()
