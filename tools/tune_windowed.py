"""Parameter sweep for the windowed kernel on the real chip: batch size x
tile width. Prints one line per config; run after any kernel change.

Usage: python tools/tune_windowed.py [subs]
"""
import random
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def note(m):
    print(m, file=sys.stderr, flush=True)


def main():
    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from bench import WindowedBench, build_corpus
    from vernemq_tpu.models import tpu_matcher as TM
    from vernemq_tpu.models.tpu_table import SubscriptionTable

    subs = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    rng = random.Random(42)
    table = SubscriptionTable(max_levels=8,
                              initial_capacity=1 << (subs - 1).bit_length())
    t0 = time.perf_counter()
    pools = build_corpus(rng, subs, table)
    note(f"corpus {time.perf_counter()-t0:.1f}s platform="
         f"{jax.devices()[0].platform}")

    best = None
    for tile_pubs in (128, 256, 512):
        TM.TILE_PUBS = tile_pubs
        for B in (2048, 4096, 8192):
            for fa in (96, 128):  # flat_avg: result-buffer slots per pub
                try:
                    wb = WindowedBench(jax, table, pools, rng, B, 256,
                                       flat_avg=fa)
                    r = wb.run(20, warmup=8, measure_resolve=False)
                    line = (f"TP={tile_pubs} B={B} FA={fa}: "
                            f"{r['matches_per_sec']/1e6:.2f}M matches/s "
                            f"{r['publishes_per_sec']/1e3:.0f}k pubs/s "
                            f"batch={r['batch_ms']:.2f}ms "
                            f"enc={r['encode_ms']:.2f} "
                            f"prep={r['prep_ms']:.2f} "
                            f"sync_p50={r['synced_batch_ms_p50']:.1f} "
                            f"left={r['leftover_pubs']} "
                            f"ovf={r['overflow_pubs']}")
                    note(line)
                    if best is None or r["matches_per_sec"] > best[0]:
                        best = (r["matches_per_sec"], tile_pubs, B, fa)
                except Exception as e:
                    note(f"TP={tile_pubs} B={B} FA={fa} FAILED: "
                         f"{type(e).__name__}: {str(e)[:120]}")
    if best:
        note(f"BEST: TILE_PUBS={best[1]} B={best[2]} flat_avg={best[3]} "
             f"{best[0]/1e6:.2f}M matches/s")


if __name__ == "__main__":
    main()
