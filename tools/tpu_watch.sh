#!/bin/bash
# Probe the axon tunnel every 5 min; when it answers, fire the bench and
# tuning sweeps once, recording everything under /tmp/tpu_watch/.
set -u
OUT=/tmp/tpu_watch
mkdir -p "$OUT"
cd /root/repo || exit 1
while true; do
  if timeout 60 python - <<'EOF' >/dev/null 2>&1
import jax
ds = jax.devices()
assert ds and ds[0].platform != "cpu", ds
EOF
  then
    date > "$OUT/recovered_at"
    echo "tunnel recovered, running bench" >> "$OUT/log"
    timeout 1800 python bench.py > "$OUT/bench.json" 2> "$OUT/bench.err"
    echo "bench rc=$?" >> "$OUT/log"
    timeout 1200 python tools/tune_windowed.py 1000000 --tp 256 --b 4096 --fm 2 --fa 128 \
      > "$OUT/tune_flat.txt" 2>&1
    echo "tune_flat rc=$?" >> "$OUT/log"
    timeout 1200 python tools/tune_windowed.py 1000000 --tp 256 --b 4096 --fm 2 --fa 128 --rows \
      > "$OUT/tune_rows.txt" 2>&1
    echo "tune_rows rc=$?" >> "$OUT/log"
    timeout 1200 python tools/tune_windowed.py 1000000 --tp 256 --b 4096 --fm 2 --fa 128 --pallas \
      > "$OUT/tune_pallas.txt" 2>&1
    echo "tune_pallas rc=$?" >> "$OUT/log"
    touch "$OUT/DONE"
    exit 0
  fi
  date >> "$OUT/probe_failures"
  sleep 300
done
