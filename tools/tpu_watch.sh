#!/bin/bash
# Probe the axon tunnel every 5 min; when it answers, fire the r4 packed
# bench + sweeps once, recording everything under /tmp/tpu_watch/.
#
# Order: quick packed B=8192 point (most valuable single number + a
# compile-server health probe), then the full bench (the headline
# artifact, with the kernel-only probe), then the never-yet-measured
# packed_rows point BEFORE the remaining wedge-prone big-B/fa points —
# a hung compile at one of those must not cost the unmeasured data.
# The Pallas sweep is deliberately ABSENT: its Mosaic remote-compile
# crashed the compile server twice (HTTP 500) and wedged the tunnel for
# the rest of the session; do not auto-fire it.
set -u
OUT=/tmp/tpu_watch
mkdir -p "$OUT"
cd /root/repo || exit 1
while true; do
  if timeout 60 python - <<'EOF' >/dev/null 2>&1
import jax
ds = jax.devices()
assert ds and ds[0].platform != "cpu", ds
EOF
  then
    if ps -eo args | grep -E "^python( .*)? bench\.py" | grep -vq grep; then
      # the round-end driver (or another session) is already benching
      # the chip — two bench processes would contend and pollute both
      echo "bench already running elsewhere; standing down" >> "$OUT/log"
      date >> "$OUT/probe_failures"
      sleep 300
      continue
    fi
    date > "$OUT/recovered_at"
    echo "tunnel recovered" >> "$OUT/log"
    # recovery windows can be SHORT (r3 saw one 25-min window all
    # round): grab the single most valuable quick number first — the
    # packed B=8192 point (one compile + 20 iters, ~3-5 min; its
    # compile wedged last time, so it also probes server health, and
    # it now reports the device-resident kernel-only rate too) —
    # before committing ~25 min to the full bench ladder.
    timeout 900 python tools/tune_windowed.py 1000000 --packed \
      --tp 256 --b 8192 --fm 2 --fa 128 \
      > "$OUT/tune_packed_b8192.txt" 2>&1
    echo "tune_packed_b8192 rc=$?" >> "$OUT/log"
    timeout 2400 python bench.py > "$OUT/bench.json" 2> "$OUT/bench.err"
    echo "bench rc=$?" >> "$OUT/log"
    timeout 900 python tools/tune_windowed.py 1000000 --packed-rows \
      --tp 256 --b 4096 --fm 2 --fa 128 \
      > "$OUT/tune_packed_rows.txt" 2>&1
    echo "tune_packed_rows rc=$?" >> "$OUT/log"
    timeout 900 python tools/tune_windowed.py 1000000 --packed \
      --tp 256 --b 16384 --fm 2 --fa 128 \
      > "$OUT/tune_packed_b16384.txt" 2>&1
    echo "tune_packed_b16384 rc=$?" >> "$OUT/log"
    # result bytes scale with flat_avg (Bpad*(fa+3) words/batch): a
    # tighter fa is the cheapest download cut IF overflow stays ~0
    timeout 900 python tools/tune_windowed.py 1000000 --packed \
      --tp 256 --b 8192 --fm 2 --fa 96 \
      > "$OUT/tune_packed_fa96.txt" 2>&1
    echo "tune_packed_fa96 rc=$?" >> "$OUT/log"
    # stacked transport (r5): N batches per executable + ONE result
    # pull — amortises the 2 per-dispatch RTTs (ROOFLINE.md predicts
    # ~2x end-to-end through this tunnel)
    timeout 1200 python bench.py --configs 3 --variant packed_stack \
      --stack 8 > "$OUT/bench_stacked.json" 2> "$OUT/bench_stacked.err"
    echo "bench_stacked rc=$?" >> "$OUT/log"
    touch "$OUT/DONE"
    exit 0
  fi
  date >> "$OUT/probe_failures"
  sleep 300
done
