"""Host-trie microbench ladder — the reference's trie bench suite shape
(``vmq_reg_trie_bench_SUITE.erl:97-214``: insert / single-lookup /
fanout-lookup / delete wall time at 1k, 2k, ... subscriptions).

Runs the same ladder against ``models/trie.py`` (the host oracle that
backs every broker when the device view is off/degraded) and prints one
JSON line per rung.

  python tools/trie_ladder.py [--max 1048576] [--lookups 20000]
"""
import argparse
import json
import random
import sys
import time

sys.path.insert(0, "/root/repo")


def run_rung(n: int, lookups: int, rng: random.Random) -> dict:
    from vernemq_tpu.models.trie import SubscriptionTrie

    t = SubscriptionTrie()
    # reference shape: 3-level topics, a mix of exact and wildcard
    # filters (the SUITE inserts {client, topic} rows of both kinds)
    filters = []
    for i in range(n):
        a, b = i % 251, (i // 251) % 97
        kind = i % 10
        if kind == 0:
            f = [f"lvl{a}", "+", f"leaf{i % 1009}"]
        elif kind == 1:
            f = [f"lvl{a}", f"mid{b}", "#"]
        else:
            f = [f"lvl{a}", f"mid{b}", f"leaf{i % 1009}"]
        filters.append((f, i))
    t0 = time.perf_counter()
    for f, key in filters:
        t.add(f, key, None)
    insert_s = time.perf_counter() - t0

    topics = [[f"lvl{rng.randrange(251)}", f"mid{rng.randrange(97)}",
               f"leaf{rng.randrange(1009)}"] for _ in range(lookups)]
    t0 = time.perf_counter()
    matched = 0
    for tp in topics:
        matched += len(t.match(tp))
    lookup_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for f, key in filters:
        t.remove(f, key)
    delete_s = time.perf_counter() - t0

    return {
        "subs": n,
        "insert_s": round(insert_s, 3),
        "inserts_per_sec": round(n / insert_s),
        "lookup_us_avg": round(1e6 * lookup_s / lookups, 2),
        "lookups_per_sec": round(lookups / lookup_s),
        "avg_fanout": round(matched / lookups, 2),
        "delete_s": round(delete_s, 3),
        "deletes_per_sec": round(n / delete_s),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--max", type=int, default=1 << 20)
    ap.add_argument("--lookups", type=int, default=20_000)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    rng = random.Random(args.seed)
    n = 1024
    while n <= args.max:
        print(json.dumps(run_rung(n, args.lookups, rng)), flush=True)
        n *= 2


if __name__ == "__main__":
    main()
