"""Asyncio MQTT client (v4 + v5).

Plays the role of the reference's ``gen_mqtt_client`` behaviour
(``apps/vmq_commons/src/gen_mqtt_client.erl``): a programmatic client used
by the bridge for broker-to-broker links and by the test suites as the
"real protocol over TCP" driver (the reference suites build frames with the
parser's gen_* helpers and speak raw TCP — ``packet.erl``; this client is
that, structured).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Tuple

from .protocol import codec_v4, codec_v5
from .protocol.types import (
    PROTO_5,
    Auth,
    Connack,
    Connect,
    Disconnect,
    Frame,
    Pingreq,
    Pingresp,
    Puback,
    Pubcomp,
    Publish,
    Pubrec,
    Pubrel,
    SubOpts,
    Suback,
    Subscribe,
    Unsuback,
    Unsubscribe,
    Will,
)


class MQTTClient:
    def __init__(self, host: str, port: int, client_id: str = "",
                 proto_ver: int = 4, clean_start: bool = True,
                 username: Optional[str] = None, password: Optional[bytes] = None,
                 keepalive: int = 60, will: Optional[Will] = None,
                 properties: Optional[Dict[str, Any]] = None,
                 ssl_context=None):
        self.host, self.port = host, port
        self.client_id = client_id
        self.proto_ver = proto_ver
        self.codec = codec_v5 if proto_ver == PROTO_5 else codec_v4
        self.clean_start = clean_start
        self.username, self.password = username, password
        self.keepalive = keepalive
        self.will = will
        self.connect_properties = properties or {}
        self.ssl_context = ssl_context
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._buf = b""
        self._next_pid = 0
        self.connack: Optional[Connack] = None
        # inbound publishes land here; acks handled inline by recv loop
        self.messages: asyncio.Queue = asyncio.Queue()
        self.disconnect_frame: Optional[Disconnect] = None
        self._acks: Dict[int, asyncio.Future] = {}
        self._recv_task: Optional[asyncio.Task] = None
        self._auto_ack = True
        self.closed = False

    # ------------------------------------------------------------ plumbing

    def _pid(self) -> int:
        self._next_pid = (self._next_pid % 65535) + 1
        return self._next_pid

    def _send(self, frame: Frame) -> None:
        assert self._writer is not None
        self._writer.write(self.codec.serialise(frame))

    async def _read_frame(self) -> Optional[Frame]:
        while True:
            frame, rest = self.codec.parse(self._buf)
            self._buf = bytes(rest)
            if frame is not None:
                return frame
            chunk = await self._reader.read(65536)
            if not chunk:
                return None
            self._buf += chunk

    # ------------------------------------------------------------- connect

    async def connect(self, timeout: float = 5.0) -> Connack:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=self.ssl_context)
        self._send(Connect(
            proto_ver=self.proto_ver, client_id=self.client_id,
            username=self.username, password=self.password,
            clean_start=self.clean_start, keepalive=self.keepalive,
            will=self.will, properties=self.connect_properties,
        ))
        frame = await asyncio.wait_for(self._read_frame(), timeout)
        if isinstance(frame, Auth):
            # enhanced auth continuation is driven by the caller via auth()
            self._pending_auth = frame
            return frame
        if not isinstance(frame, Connack):
            raise ConnectionError(f"expected CONNACK, got {frame!r}")
        self.connack = frame
        self._recv_task = asyncio.get_event_loop().create_task(self._recv_loop())
        return frame

    async def auth(self, reason_code: int, properties: Dict[str, Any],
                   timeout: float = 5.0) -> Frame:
        """Send an AUTH frame during enhanced auth; returns the next
        CONNACK/AUTH frame."""
        self._send(Auth(reason_code=reason_code, properties=properties))
        frame = await asyncio.wait_for(self._read_frame(), timeout)
        if isinstance(frame, Connack):
            self.connack = frame
            self._recv_task = asyncio.get_event_loop().create_task(self._recv_loop())
        return frame

    async def _recv_loop(self) -> None:
        try:
            while True:
                frame = await self._read_frame()
                if frame is None:
                    break
                t = type(frame)
                if t is Publish:
                    if self._auto_ack and frame.qos == 1:
                        self._send(Puback(packet_id=frame.packet_id))
                    elif self._auto_ack and frame.qos == 2:
                        self._send(Pubrec(packet_id=frame.packet_id))
                    await self.messages.put(frame)
                elif t is Pubrel:
                    if self._auto_ack:
                        self._send(Pubcomp(packet_id=frame.packet_id))
                elif t in (Puback, Pubrec, Pubcomp, Suback, Unsuback):
                    if t is Pubrec:
                        self._send(Pubrel(packet_id=frame.packet_id))
                        continue  # wait for PUBCOMP to resolve the future
                    fut = self._acks.pop(frame.packet_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(frame)
                elif t is Pingresp:
                    pass
                elif t is Disconnect:
                    self.disconnect_frame = frame
                    await self.messages.put(frame)
                    break
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self.closed = True
            await self.messages.put(None)  # EOF marker

    # ------------------------------------------------------------- actions

    async def subscribe(self, topics, qos: int = 0,
                        properties: Optional[Dict[str, Any]] = None,
                        opts: Optional[SubOpts] = None,
                        timeout: float = 5.0) -> Suback:
        if isinstance(topics, str):
            topics = [topics]
        pid = self._pid()
        fut = asyncio.get_event_loop().create_future()
        self._acks[pid] = fut
        self._send(Subscribe(
            packet_id=pid,
            topics=[(t, opts or SubOpts(qos=qos)) for t in topics],
            properties=properties or {},
        ))
        return await asyncio.wait_for(fut, timeout)

    async def unsubscribe(self, topics, timeout: float = 5.0) -> Unsuback:
        if isinstance(topics, str):
            topics = [topics]
        pid = self._pid()
        fut = asyncio.get_event_loop().create_future()
        self._acks[pid] = fut
        self._send(Unsubscribe(packet_id=pid, topics=topics))
        return await asyncio.wait_for(fut, timeout)

    async def publish(self, topic: str, payload: bytes = b"", qos: int = 0,
                      retain: bool = False,
                      properties: Optional[Dict[str, Any]] = None,
                      timeout: float = 5.0) -> Optional[Frame]:
        pid = self._pid() if qos else None
        frame = Publish(topic=topic, payload=payload, qos=qos, retain=retain,
                        packet_id=pid, properties=properties or {})
        if qos == 0:
            self._send(frame)
            return None
        fut = asyncio.get_event_loop().create_future()
        self._acks[pid] = fut
        self._send(frame)
        return await asyncio.wait_for(fut, timeout)  # Puback or Pubcomp

    async def ping(self) -> None:
        self._send(Pingreq())

    async def recv(self, timeout: float = 5.0) -> Optional[Frame]:
        """Next inbound PUBLISH (or server DISCONNECT/None-EOF)."""
        return await asyncio.wait_for(self.messages.get(), timeout)

    async def disconnect(self, reason_code: int = 0,
                         properties: Optional[Dict[str, Any]] = None) -> None:
        if self._writer is not None and not self.closed:
            try:
                if self.proto_ver == PROTO_5:
                    self._send(Disconnect(reason_code=reason_code,
                                          properties=properties or {}))
                else:
                    self._send(Disconnect())
                await self._writer.drain()
            except ConnectionError:
                pass
        await self.close()

    async def close(self) -> None:
        self.closed = True
        if self._recv_task is not None:
            self._recv_task.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:
                pass


class _ConnackRejected(ConnectionError):
    """Broker refused the CONNECT (rc != 0) — reported via
    on_connect_error, NOT on_disconnect (one event per attempt)."""

    def __init__(self, rc: int):
        super().__init__(f"CONNACK rc={rc}")
        self.rc = rc


class ReconnectingClient:
    """The behaviour-surface client of the reference (`gen_mqtt_client.erl`):
    a supervised connect/consume loop with reconnect backoff, a bounded
    offline publish queue with drop accounting, resubscribe-on-connect and
    keepalive pings, exposing the callback surface the reference defines —
    ``on_connect`` (gen_mqtt_client.erl:398-416 CONNACK dispatch),
    ``on_connect_error`` (per-rc, same lines), ``on_disconnect``
    (maybe_reconnect, :624-631), ``on_publish`` (:482-520 deliver path),
    ``on_subscribe``/``on_unsubscribe`` (:423-447).

    The reference reconnects on a FIXED ``reconnect_timeout`` (:343);
    ``backoff="exponential"`` optionally doubles up to ``backoff_max``
    (the vmq_bridge restart discipline). The offline queue mirrors
    ``o_queue``/``max_queue_size`` (:337,346): publishes while down are
    queued up to the cap, beyond it dropped WITH accounting (:658-660,
    ``out_queue_dropped`` in info, :538-541), and drained on CONNACK
    (publish_from_queue, :650-656). ``max_queue_size=0`` queues nothing
    (every offline publish drops), matching the reference default.

    Used by :class:`~vernemq_tpu.plugins.bridge.Bridge`; also the public
    client for long-lived integrations (the test-suite driver stays the
    bare :class:`MQTTClient`)."""

    def __init__(self, host: str, port: int,
                 reconnect_timeout: float = 10.0,
                 backoff: str = "fixed", backoff_max: float = 300.0,
                 max_queue_size: int = 0, resubscribe: bool = True,
                 connect_timeout: float = 10.0,
                 on_connect=None, on_connect_error=None,
                 on_disconnect=None, on_publish=None,
                 on_subscribe=None, on_unsubscribe=None,
                 subscriptions: Optional[Dict[str, SubOpts]] = None,
                 **client_kw: Any):
        self.host, self.port = host, port
        self.reconnect_timeout = reconnect_timeout
        self.backoff = backoff
        self.backoff_max = backoff_max
        self.max_queue_size = max_queue_size
        self.resubscribe = resubscribe
        self.connect_timeout = connect_timeout
        self.client_kw = client_kw
        self.on_connect = on_connect
        self.on_connect_error = on_connect_error
        self.on_disconnect = on_disconnect
        self.on_publish = on_publish
        self.on_subscribe = on_subscribe
        self.on_unsubscribe = on_unsubscribe
        self.client: Optional[MQTTClient] = None
        self.connected = asyncio.Event()
        self.connected_since: Optional[float] = None
        #: inbound publishes when no on_publish callback is given
        self.messages: asyncio.Queue = asyncio.Queue()
        self._subs: Dict[str, SubOpts] = dict(subscriptions or {})
        self._queue: List[Tuple[str, bytes, int, bool, Dict[str, Any]]] = []
        self.out_queue_dropped = 0
        self._task: Optional[asyncio.Task] = None
        self._ping_task: Optional[asyncio.Task] = None
        self._cb_tasks: set = set()
        self._stopped = False

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        for t in (self._task, self._ping_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        if self.client is not None:
            try:
                await self.client.disconnect()
            except Exception:
                pass
            self.client = None
        self.connected.clear()

    def _fire(self, cb, *args) -> None:
        if cb is None:
            return
        try:
            res = cb(*args)
            if asyncio.iscoroutine(res):
                # strong-ref the task: the loop only weak-refs tasks, so
                # an unreferenced async callback could be GC'd mid-run
                t = asyncio.get_event_loop().create_task(res)
                self._cb_tasks.add(t)
                t.add_done_callback(self._cb_tasks.discard)
        except Exception:
            import logging

            logging.getLogger("vernemq_tpu.client").exception(
                "reconnecting-client callback failed")

    async def _run(self) -> None:
        delay = self.reconnect_timeout
        loop = asyncio.get_event_loop()
        while not self._stopped:
            client = MQTTClient(self.host, self.port, **self.client_kw)
            try:
                ack = await client.connect(timeout=self.connect_timeout)
                if getattr(ack, "rc", 1) != 0:
                    self._fire(self.on_connect_error, ack.rc)
                    raise _ConnackRejected(ack.rc)
                self.client = client
                self.connected_since = loop.time()
                delay = self.reconnect_timeout  # success resets backoff
                if self.resubscribe:
                    for topic, opts in list(self._subs.items()):
                        await client.subscribe(topic, opts=opts)
                self.connected.set()
                self._fire(self.on_connect, ack.session_present)
                # drain the offline queue (publish_from_queue): pop only
                # AFTER a publish succeeds, so a failure mid-drain keeps
                # the unsent remainder queued for the next connect (a
                # retried head may duplicate — QoS1 at-least-once)
                while self._queue:
                    topic, payload, qos, retain, props = self._queue[0]
                    await client.publish(topic, payload, qos=qos,
                                         retain=retain, properties=props)
                    self._queue.pop(0)
                self._ping_task = loop.create_task(
                    self._keepalive(client))
                while True:
                    frame = await client.messages.get()
                    if frame is None:
                        raise ConnectionError("connection closed")
                    if isinstance(frame, Publish):
                        if self.on_publish is not None:
                            self._fire(self.on_publish, frame)
                        else:
                            await self.messages.put(frame)
            except asyncio.CancelledError:
                raise
            except _ConnackRejected:
                pass  # already reported via on_connect_error — one event
            except Exception as e:
                self._fire(self.on_disconnect, e)
            finally:
                self.connected.clear()
                self.connected_since = None
                self.client = None
                if self._ping_task is not None:
                    self._ping_task.cancel()
                    self._ping_task = None
                try:
                    await client.close()
                except Exception:
                    pass
            if self._stopped:
                return
            await asyncio.sleep(delay)
            if self.backoff == "exponential":
                delay = min(delay * 2, self.backoff_max)

    async def _keepalive(self, client: MQTTClient) -> None:
        """PINGREQ at half the keepalive interval — an idle link must not
        be culled by the broker's 1.5x keepalive reaper."""
        interval = max(1.0, self.client_kw.get("keepalive", 60) / 2)
        try:
            while True:
                await asyncio.sleep(interval)
                await client.ping()
        except (asyncio.CancelledError, ConnectionError):
            pass

    # -------------------------------------------------------------- actions

    async def subscribe(self, topic: str, qos: int = 0,
                        opts: Optional[SubOpts] = None):
        """Record for resubscribe-on-reconnect; subscribe now when up."""
        self._subs[topic] = opts or SubOpts(qos=qos)
        if self.connected.is_set() and self.client is not None:
            suback = await self.client.subscribe(topic,
                                                 opts=self._subs[topic])
            self._fire(self.on_subscribe, topic, suback)
            return suback
        return None

    async def unsubscribe(self, topic: str):
        self._subs.pop(topic, None)
        if self.connected.is_set() and self.client is not None:
            unsuback = await self.client.unsubscribe(topic)
            self._fire(self.on_unsubscribe, topic)
            return unsuback
        return None

    async def publish(self, topic: str, payload: bytes = b"",
                      qos: int = 0, retain: bool = False,
                      properties: Optional[Dict[str, Any]] = None):
        """Publish now, or queue while down (bounded; beyond the cap the
        publish is DROPPED with accounting, gen_mqtt_client.erl:658-660)."""
        if self.connected.is_set() and self.client is not None:
            return await self.client.publish(topic, payload, qos=qos,
                                             retain=retain,
                                             properties=properties)
        if len(self._queue) < self.max_queue_size:
            self._queue.append((topic, payload, qos, retain,
                                properties or {}))
        else:
            self.out_queue_dropped += 1
        return None

    def info(self) -> Dict[str, Any]:
        return {
            "connected": self.connected.is_set(),
            "out_queue_size": len(self._queue),
            "out_queue_dropped": self.out_queue_dropped,
            "subscriptions": sorted(self._subs),
        }
