"""Asyncio MQTT client (v4 + v5).

Plays the role of the reference's ``gen_mqtt_client`` behaviour
(``apps/vmq_commons/src/gen_mqtt_client.erl``): a programmatic client used
by the bridge for broker-to-broker links and by the test suites as the
"real protocol over TCP" driver (the reference suites build frames with the
parser's gen_* helpers and speak raw TCP — ``packet.erl``; this client is
that, structured).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Tuple

from .protocol import codec_v4, codec_v5
from .protocol.types import (
    PROTO_5,
    Auth,
    Connack,
    Connect,
    Disconnect,
    Frame,
    Pingreq,
    Pingresp,
    Puback,
    Pubcomp,
    Publish,
    Pubrec,
    Pubrel,
    SubOpts,
    Suback,
    Subscribe,
    Unsuback,
    Unsubscribe,
    Will,
)


class MQTTClient:
    def __init__(self, host: str, port: int, client_id: str = "",
                 proto_ver: int = 4, clean_start: bool = True,
                 username: Optional[str] = None, password: Optional[bytes] = None,
                 keepalive: int = 60, will: Optional[Will] = None,
                 properties: Optional[Dict[str, Any]] = None,
                 ssl_context=None):
        self.host, self.port = host, port
        self.client_id = client_id
        self.proto_ver = proto_ver
        self.codec = codec_v5 if proto_ver == PROTO_5 else codec_v4
        self.clean_start = clean_start
        self.username, self.password = username, password
        self.keepalive = keepalive
        self.will = will
        self.connect_properties = properties or {}
        self.ssl_context = ssl_context
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._buf = b""
        self._next_pid = 0
        self.connack: Optional[Connack] = None
        # inbound publishes land here; acks handled inline by recv loop
        self.messages: asyncio.Queue = asyncio.Queue()
        self.disconnect_frame: Optional[Disconnect] = None
        self._acks: Dict[int, asyncio.Future] = {}
        self._recv_task: Optional[asyncio.Task] = None
        self._auto_ack = True
        self.closed = False

    # ------------------------------------------------------------ plumbing

    def _pid(self) -> int:
        self._next_pid = (self._next_pid % 65535) + 1
        return self._next_pid

    def _send(self, frame: Frame) -> None:
        assert self._writer is not None
        self._writer.write(self.codec.serialise(frame))

    async def _read_frame(self) -> Optional[Frame]:
        while True:
            frame, rest = self.codec.parse(self._buf)
            self._buf = bytes(rest)
            if frame is not None:
                return frame
            chunk = await self._reader.read(65536)
            if not chunk:
                return None
            self._buf += chunk

    # ------------------------------------------------------------- connect

    async def connect(self, timeout: float = 5.0) -> Connack:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=self.ssl_context)
        self._send(Connect(
            proto_ver=self.proto_ver, client_id=self.client_id,
            username=self.username, password=self.password,
            clean_start=self.clean_start, keepalive=self.keepalive,
            will=self.will, properties=self.connect_properties,
        ))
        frame = await asyncio.wait_for(self._read_frame(), timeout)
        if isinstance(frame, Auth):
            # enhanced auth continuation is driven by the caller via auth()
            self._pending_auth = frame
            return frame
        if not isinstance(frame, Connack):
            raise ConnectionError(f"expected CONNACK, got {frame!r}")
        self.connack = frame
        self._recv_task = asyncio.get_event_loop().create_task(self._recv_loop())
        return frame

    async def auth(self, reason_code: int, properties: Dict[str, Any],
                   timeout: float = 5.0) -> Frame:
        """Send an AUTH frame during enhanced auth; returns the next
        CONNACK/AUTH frame."""
        self._send(Auth(reason_code=reason_code, properties=properties))
        frame = await asyncio.wait_for(self._read_frame(), timeout)
        if isinstance(frame, Connack):
            self.connack = frame
            self._recv_task = asyncio.get_event_loop().create_task(self._recv_loop())
        return frame

    async def _recv_loop(self) -> None:
        try:
            while True:
                frame = await self._read_frame()
                if frame is None:
                    break
                t = type(frame)
                if t is Publish:
                    if self._auto_ack and frame.qos == 1:
                        self._send(Puback(packet_id=frame.packet_id))
                    elif self._auto_ack and frame.qos == 2:
                        self._send(Pubrec(packet_id=frame.packet_id))
                    await self.messages.put(frame)
                elif t is Pubrel:
                    if self._auto_ack:
                        self._send(Pubcomp(packet_id=frame.packet_id))
                elif t in (Puback, Pubrec, Pubcomp, Suback, Unsuback):
                    if t is Pubrec:
                        self._send(Pubrel(packet_id=frame.packet_id))
                        continue  # wait for PUBCOMP to resolve the future
                    fut = self._acks.pop(frame.packet_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(frame)
                elif t is Pingresp:
                    pass
                elif t is Disconnect:
                    self.disconnect_frame = frame
                    await self.messages.put(frame)
                    break
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self.closed = True
            await self.messages.put(None)  # EOF marker

    # ------------------------------------------------------------- actions

    async def subscribe(self, topics, qos: int = 0,
                        properties: Optional[Dict[str, Any]] = None,
                        opts: Optional[SubOpts] = None,
                        timeout: float = 5.0) -> Suback:
        if isinstance(topics, str):
            topics = [topics]
        pid = self._pid()
        fut = asyncio.get_event_loop().create_future()
        self._acks[pid] = fut
        self._send(Subscribe(
            packet_id=pid,
            topics=[(t, opts or SubOpts(qos=qos)) for t in topics],
            properties=properties or {},
        ))
        return await asyncio.wait_for(fut, timeout)

    async def unsubscribe(self, topics, timeout: float = 5.0) -> Unsuback:
        if isinstance(topics, str):
            topics = [topics]
        pid = self._pid()
        fut = asyncio.get_event_loop().create_future()
        self._acks[pid] = fut
        self._send(Unsubscribe(packet_id=pid, topics=topics))
        return await asyncio.wait_for(fut, timeout)

    async def publish(self, topic: str, payload: bytes = b"", qos: int = 0,
                      retain: bool = False,
                      properties: Optional[Dict[str, Any]] = None,
                      timeout: float = 5.0) -> Optional[Frame]:
        pid = self._pid() if qos else None
        frame = Publish(topic=topic, payload=payload, qos=qos, retain=retain,
                        packet_id=pid, properties=properties or {})
        if qos == 0:
            self._send(frame)
            return None
        fut = asyncio.get_event_loop().create_future()
        self._acks[pid] = fut
        self._send(frame)
        return await asyncio.wait_for(fut, timeout)  # Puback or Pubcomp

    async def ping(self) -> None:
        self._send(Pingreq())

    async def recv(self, timeout: float = 5.0) -> Optional[Frame]:
        """Next inbound PUBLISH (or server DISCONNECT/None-EOF)."""
        return await asyncio.wait_for(self.messages.get(), timeout)

    async def disconnect(self, reason_code: int = 0,
                         properties: Optional[Dict[str, Any]] = None) -> None:
        if self._writer is not None and not self.closed:
            try:
                if self.proto_ver == PROTO_5:
                    self._send(Disconnect(reason_code=reason_code,
                                          properties=properties or {}))
                else:
                    self._send(Disconnect())
                await self._writer.drain()
            except ConnectionError:
                pass
        await self.close()

    async def close(self) -> None:
        self.closed = True
        if self._recv_task is not None:
            self._recv_task.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:
                pass
