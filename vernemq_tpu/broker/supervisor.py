"""Crash-restart supervision for broker background work.

The reference's OTP supervision tree (``vmq_server_sup.erl:43-58``,
one_for_one with max-restart intensity) restarts crashed children —
listeners, reporters, cluster writers — without taking the broker down.
asyncio has no supervisor, so this is the analog: named supervised tasks
that restart on unexpected exceptions with exponential backoff, restarts
surfaced in the ``supervisor_restarts`` metric, plus a listener watchdog
that re-binds a listener whose server socket died.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, Dict, Optional

log = logging.getLogger("vernemq_tpu.supervisor")


class Supervisor:
    """Restart-on-crash task supervision (one_for_one)."""

    def __init__(self, broker, backoff_initial: float = 0.5,
                 backoff_max: float = 30.0):
        self.broker = broker
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self._tasks: Dict[str, asyncio.Task] = {}
        self.restarts: Dict[str, int] = {}
        self._stopped = False

    def spawn(self, name: str, factory: Callable[[], Awaitable[Any]]) -> None:
        """Supervise ``factory``: it is (re)invoked to produce the child
        coroutine after every crash. Normal return or cancellation ends
        supervision (transient semantics — like OTP ``transient``)."""
        if name in self._tasks and not self._tasks[name].done():
            raise RuntimeError(f"supervised task {name!r} already running")
        self._tasks[name] = asyncio.get_event_loop().create_task(
            self._run(name, factory))

    async def _run(self, name: str,
                   factory: Callable[[], Awaitable[Any]]) -> None:
        backoff = self.backoff_initial
        while not self._stopped:
            try:
                await factory()
                return  # clean exit
            except asyncio.CancelledError:
                raise
            except Exception:
                if self._stopped:
                    return
                self.restarts[name] = self.restarts.get(name, 0) + 1
                self.broker.metrics.incr("supervisor_restarts")
                log.exception("supervised task %r crashed (restart #%d in "
                              "%.1fs)", name, self.restarts[name], backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self.backoff_max)

    def watch_listeners(self, interval: float = 1.0) -> None:
        """Listener watchdog: a listener whose asyncio server stopped
        serving (crash, EMFILE storm, ...) without being stopped through
        the manager is re-bound on its address — the role of ranch
        restarting a crashed acceptor pool under vmq_ranch_sup."""
        self.spawn("listener-watchdog", lambda: self._watch_listeners(interval))

    async def _watch_listeners(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            mgr = self.broker.listeners
            if mgr is None:
                continue
            for (addr, port), entry in list(mgr._listeners.items()):
                server = entry.get("server")
                srv = getattr(server, "_server", None)
                if srv is None or srv.is_serving():
                    continue
                self.restarts["listener"] = self.restarts.get("listener", 0) + 1
                self.broker.metrics.incr("supervisor_restarts")
                log.warning("listener %s:%d (%s) died; restarting",
                            addr, port, entry["kind"])
                mgr._listeners.pop((addr, port), None)
                try:
                    await mgr.start_listener(entry["kind"], addr, port,
                                             entry.get("opts"))
                except Exception:
                    log.exception("listener %s:%d restart failed; will "
                                  "retry on next tick", addr, port)
                    # leave the record out; retry happens because the next
                    # scan no longer sees it... so re-insert a dead record
                    mgr._listeners[(addr, port)] = entry

    def stop(self) -> None:
        self._stopped = True
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()
