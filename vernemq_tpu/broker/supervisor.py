"""Crash-restart supervision for broker background work.

The reference's OTP supervision tree (``vmq_server_sup.erl:43-58``,
one_for_one with max-restart intensity) restarts crashed children —
listeners, reporters, cluster writers — without taking the broker down.
asyncio has no supervisor, so this is the analog: named supervised tasks
that restart on unexpected exceptions with exponential backoff, restarts
surfaced in the ``supervisor_restarts`` metric, plus a listener watchdog
that re-binds a listener whose server socket died.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, Dict, Optional

from ..observability import events

log = logging.getLogger("vernemq_tpu.supervisor")


class Supervisor:
    """Restart-on-crash task supervision (one_for_one).

    Hardened against restart storms: backoff is exponential with jitter
    and a hard cap (no thundering-herd restarts, no busy-spin when a
    child crashes instantly every time), and a restart *budget* —
    more than ``max_restarts`` CONSECUTIVE crashy restarts (a stint
    healthier than the current backoff, or longer than
    ``restart_window`` seconds, resets the count) — past which
    supervision of that child ESCALATES instead of looping forever: the
    child is abandoned, ``supervisor_escalations`` counts it, and the
    broker's listeners are torn down so load balancers route around the
    sick node (the OTP max-intensity analog: a supervisor that gives up
    takes its subtree down rather than thrash). The budget is counted
    in restarts, not wall-clock: exponential backoff spaces crashes
    out, so a time window would never fill and escalation would be
    unreachable."""

    def __init__(self, broker, backoff_initial: float = 0.5,
                 backoff_max: float = 30.0, jitter: float = 0.1,
                 max_restarts: int = 0, restart_window: float = 60.0,
                 rng=None):
        import random

        self.broker = broker
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self.jitter = jitter
        # 0 = unlimited (no escalation) — the pre-hardening behavior
        self.max_restarts = max_restarts
        self.restart_window = restart_window
        self._rng = rng or random.Random()
        self._tasks: Dict[str, asyncio.Task] = {}
        self.restarts: Dict[str, int] = {}
        self.backoffs: Dict[str, float] = {}  # current per-child backoff
        self.escalated: Dict[str, int] = {}   # children given up on
        self._stopped = False

    def spawn(self, name: str, factory: Callable[[], Awaitable[Any]]) -> None:
        """Supervise ``factory``: it is (re)invoked to produce the child
        coroutine after every crash. Normal return or cancellation ends
        supervision (transient semantics — like OTP ``transient``)."""
        if name in self._tasks and not self._tasks[name].done():
            raise RuntimeError(f"supervised task {name!r} already running")
        self._tasks[name] = asyncio.get_event_loop().create_task(
            self._run(name, factory))

    async def _run(self, name: str,
                   factory: Callable[[], Awaitable[Any]]) -> None:
        backoff = self.backoff_initial
        consecutive = 0
        loop = asyncio.get_event_loop()
        while not self._stopped:
            started = loop.time()
            try:
                await factory()
                return  # clean exit
            except asyncio.CancelledError:
                raise
            except Exception:
                if self._stopped:
                    return
                self.restarts[name] = self.restarts.get(name, 0) + 1
                self.broker.metrics.incr("supervisor_restarts")
                events.emit("supervisor_restart", detail=name,
                            value=float(self.restarts[name]))
                # a healthy stint (longer than the current backoff, or
                # past the restart window outright) resets the ramp AND
                # the budget: only consecutive rapid crashes climb
                # toward the cap / escalation
                healthy_after = min(self.restart_window,
                                    max(backoff, self.backoff_initial))
                if loop.time() - started > healthy_after:
                    backoff = self.backoff_initial
                    consecutive = 0
                consecutive += 1
                if self.max_restarts and consecutive > self.max_restarts:
                    await self._escalate(name)
                    return
                log.exception("supervised task %r crashed (restart #%d in "
                              "%.1fs)", name, self.restarts[name], backoff)
                # jittered sleep, capped: crash-looping children settle
                # at backoff_max instead of busy-spinning, and several
                # children felled by one cause don't restart in lockstep
                await asyncio.sleep(
                    backoff * (1.0 + self.jitter * self._rng.random()))
                backoff = min(backoff * 2, self.backoff_max)
                self.backoffs[name] = backoff

    async def _escalate(self, name: str) -> None:
        """The restart budget is spent: stop supervising ``name`` and
        take the node out of rotation by tearing down its listeners —
        a broker that cannot keep its children alive must fail its
        health checks loudly, not limp with a dead subsystem."""
        self.escalated[name] = self.escalated.get(name, 0) + 1
        self.broker.metrics.incr("supervisor_escalations")
        events.emit("supervisor_escalation", detail=name)
        log.error("supervised task %r exceeded the restart budget "
                  "(%d consecutive crashy restarts); escalating: tearing "
                  "down listeners", name, self.max_restarts)
        mgr = getattr(self.broker, "listeners", None)
        if mgr is not None:
            try:
                await mgr.stop_all()
            except Exception:
                log.exception("listener teardown during escalation failed")

    def watch_listeners(self, interval: float = 1.0) -> None:
        """Listener watchdog: a listener whose asyncio server stopped
        serving (crash, EMFILE storm, ...) without being stopped through
        the manager is re-bound on its address — the role of ranch
        restarting a crashed acceptor pool under vmq_ranch_sup."""
        self.spawn("listener-watchdog", lambda: self._watch_listeners(interval))

    async def _watch_listeners(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            mgr = self.broker.listeners
            if mgr is None:
                continue
            for (addr, port), entry in list(mgr._listeners.items()):
                server = entry.get("server")
                srv = getattr(server, "_server", None)
                if srv is None or srv.is_serving():
                    continue
                self.restarts["listener"] = self.restarts.get("listener", 0) + 1
                self.broker.metrics.incr("supervisor_restarts")
                log.warning("listener %s:%d (%s) died; restarting",
                            addr, port, entry["kind"])
                mgr._listeners.pop((addr, port), None)
                try:
                    await mgr.start_listener(entry["kind"], addr, port,
                                             entry.get("opts"))
                except Exception:
                    log.exception("listener %s:%d restart failed; will "
                                  "retry on next tick", addr, port)
                    # leave the record out; retry happens because the next
                    # scan no longer sees it... so re-insert a dead record
                    mgr._listeners[(addr, port)] = entry

    def stop(self) -> None:
        self._stopped = True
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()
