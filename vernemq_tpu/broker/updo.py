"""Hot code upgrade for a running broker.

Reference analog: ``vmq_updo.erl`` — ``updated_modules/0`` diffs every
loaded module's version against the beam file on disk
(vmq_updo.erl:60-71), builds a high-level upgrade script from the
changed set, and ``run/0`` executes it through the release handler;
``dry_run/0`` returns the plan without acting (vmq_updo.erl:25-33).

The BEAM swaps code at the VM level: after a load, every process
executes the new code at its next fully-qualified call, with
``code_change`` migrating state.  CPython has no code server, so this
module reproduces the *effect* with in-place object patching:

1. ``diff()`` — like ``updated_modules/0``: hash each loaded
   ``vernemq_tpu`` module's source on disk against the digest recorded
   when it was loaded/upgraded; return the changed set.
2. ``run(dry_run=True)`` — the upgrade plan without acting
   (``vmq_updo:dry_run/0``).
3. ``run()`` — for each changed module: execute the new source into a
   *scratch* module, then graft it onto the live one.  Functions get
   their ``__code__`` / ``__defaults__`` / ``__kwdefaults__`` swapped
   in place and classes are patched member-by-member, so the OLD
   function/class objects stay canonical; every live reference —
   bound methods on live Session/Queue instances, registered hook
   callables, scheduled timer callbacks — runs the new code on its
   next call, exactly like an Erlang process returning through a
   fully-qualified call after a code swap.

Module-level data follows the BEAM split between code and state:
immutable values (the constants that live in code) are adopted from
the new version; mutable containers and instances (live state — the
process/ETS analog) are kept.  A module may define
``__updo__(old_namespace)`` for anything beyond that — the
``code_change`` analog, run after the graft with the pre-upgrade
namespace (the reference's extra-instruction script file,
vmq_updo.erl:38-47, serves the same role).

What cannot be hot-swapped is reported, never guessed: functions whose
closure cell layout changed (a ``__code__`` swap would corrupt the
cells) land in ``failed`` with the old code left active — mirroring
the release handler refusing a bad instruction rather than
half-applying it.  Native extensions (the ``.so`` codec/kvstore) need
a restart, like NIFs.

Scope note: like the reference's ``vmq-admin`` (which acts on the node
it talks to), an upgrade applies to the PROCESS serving the command.
In multi-process workers mode (broker/workers.py) run ``updo run``
against each worker's admin endpoint — or restart workers one at a
time, which the supervisor already handles.

Top-level side-effect constraint: ``run()`` RE-EXECUTES each changed
module's top-level code (with live siblings visible in ``sys.modules``)
to obtain the new definitions.  Module top-levels must therefore be
side-effect-free beyond defining names — a top-level that registers
hooks/metrics, starts threads, or mutates an imported live registry
would do so a SECOND time against live broker state on every
``updo run``.  This is the same contract the BEAM imposes (module
loading runs no user code; registrations happen in ``start`` callbacks)
— put such effects in an init function or guard them with an
idempotence check, and use ``__updo__`` for upgrade-time migrations.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import sys
import types
from typing import Any

log = logging.getLogger("vernemq_tpu.updo")

# packages under upgrade management; tests may extend temporarily
PREFIXES: tuple = ("vernemq_tpu",)

_IMMUTABLE = (int, float, complex, bool, str, bytes, tuple, frozenset,
              type(None))

_MISSING = object()  # distinguishes "absent in v1" from "was None"

# module name -> digest of the source that produced the loaded code
_loaded_digests: dict[str, str] = {}


def _source_path(mod: types.ModuleType) -> str | None:
    f = getattr(mod, "__file__", None)
    if f and f.endswith(".py"):
        return f
    return None  # native extensions need a restart, like NIFs


def _source_digest(mod: types.ModuleType) -> str | None:
    f = _source_path(mod)
    if not f:
        return None
    try:
        with open(f, "rb") as fh:
            return hashlib.sha1(fh.read()).hexdigest()
    except OSError:
        return None


def _tracked_modules() -> list[tuple[str, types.ModuleType]]:
    out = []
    for name, mod in list(sys.modules.items()):
        if mod is None or name == __name__:
            continue  # the upgrader itself is sticky (code:is_sticky)
        if not any(name == p or name.startswith(p + ".")
                   for p in PREFIXES):
            continue
        if _source_path(mod) is not None:
            out.append((name, mod))
    # parents before children, then stable by name
    out.sort(key=lambda kv: (kv[0].count("."), kv[0]))
    return out


def baseline() -> int:
    """Record the on-disk digest of every loaded module as 'current'.

    Called at boot (and implicitly per-module after each upgrade);
    ``diff()`` is relative to it.  Returns tracked-module count.
    """
    n = 0
    for name, mod in _tracked_modules():
        d = _source_digest(mod)
        if d:
            _loaded_digests[name] = d
            n += 1
    return n


def diff() -> list[str]:
    """Modules whose on-disk source differs from the loaded version
    (``vmq_updo:updated_modules/0``).  Modules first seen now are
    adopted as-loaded (nothing to upgrade)."""
    changed = []
    for name, mod in _tracked_modules():
        d = _source_digest(mod)
        if d is None:
            continue
        if name not in _loaded_digests:
            _loaded_digests[name] = d
        elif _loaded_digests[name] != d:
            changed.append(name)
    return changed


def _patch_function(old: types.FunctionType, new: types.FunctionType,
                    failures: list[str], where: str) -> bool:
    if old.__code__.co_freevars != new.__code__.co_freevars:
        failures.append(f"{where}: closure layout changed "
                        f"({old.__code__.co_freevars} -> "
                        f"{new.__code__.co_freevars})")
        return False
    old.__code__ = new.__code__
    old.__defaults__ = new.__defaults__
    old.__kwdefaults__ = new.__kwdefaults__
    old.__doc__ = new.__doc__
    old.__dict__.update(new.__dict__)
    old.__annotations__ = dict(getattr(new, "__annotations__", {}))
    return True


def _unwrap(obj: Any) -> Any:
    if isinstance(obj, (staticmethod, classmethod)):
        return obj.__func__
    return obj


def _rebind(obj: Any, live_globals: dict, scratch_globals: dict,
            failures: list[str] | None = None, where: str = "?",
            alias: dict[int, Any] | None = None) -> Any:
    """Re-home an object defined during the scratch exec onto the LIVE
    module's globals.  Without this, newly-added functions (and the
    methods of newly-added classes) would read and write the scratch
    namespace — invisible to the running broker.  Only objects whose
    ``__globals__`` IS the scratch dict are touched: functions imported
    from other modules keep their own namespaces.  (Patched old
    functions don't need this: their ``__globals__`` is already the
    live dict and only ``__code__`` is swapped.)  A scratch-global
    CLOSURE cannot be re-homed (its cells would be lost) — it is kept
    as-is but recorded in ``failures`` so the module lands in the
    failed/retryable set instead of reading invisible state silently.
    """
    if isinstance(obj, staticmethod):
        return staticmethod(_rebind(obj.__func__, live_globals,
                                    scratch_globals, failures, where,
                                    alias))
    if isinstance(obj, classmethod):
        return classmethod(_rebind(obj.__func__, live_globals,
                                   scratch_globals, failures, where,
                                   alias))
    if isinstance(obj, property):
        return property(*(f and _rebind(f, live_globals, scratch_globals,
                                        failures, where, alias)
                          for f in (obj.fget, obj.fset, obj.fdel)),
                        doc=obj.__doc__)
    if isinstance(obj, type):
        # a class born in the scratch exec is a fresh object — safe to
        # fix up in place: every scratch-global method gets re-homed,
        # and bases pointing at scratch counterparts of LIVE classes
        # (class New(Existing)) re-parent onto the live ones
        for attr, val in list(vars(obj).items()):
            fixed = _rebind(val, live_globals, scratch_globals,
                            failures, f"{where}.{attr}", alias)
            if fixed is not val:
                try:
                    setattr(obj, attr, fixed)
                except (AttributeError, TypeError):
                    pass
        if alias:
            new_bases = tuple(alias.get(id(b), b) for b in obj.__bases__)
            if new_bases != obj.__bases__:
                try:
                    obj.__bases__ = new_bases
                except TypeError as e:
                    if failures is not None:
                        failures.append(
                            f"{where}: new class inherits a live class "
                            f"but cannot be re-parented onto it: {e}")
        return obj
    if not isinstance(obj, types.FunctionType) \
            or obj.__globals__ is not scratch_globals:
        return obj  # data and foreign functions pass through
    if obj.__closure__ is not None:
        if failures is not None:
            failures.append(
                f"{where}: new closure-bearing function cannot be "
                f"re-homed onto the live module globals")
        return obj
    fn = types.FunctionType(obj.__code__, live_globals, obj.__name__,
                            obj.__defaults__, obj.__closure__)
    fn.__kwdefaults__ = obj.__kwdefaults__
    fn.__qualname__ = obj.__qualname__
    fn.__doc__ = obj.__doc__
    fn.__dict__.update(obj.__dict__)
    fn.__annotations__ = dict(getattr(obj, "__annotations__", {}))
    fn.__module__ = obj.__module__
    return fn


def _is_mutable_data(v: Any) -> bool:
    """Live-state heuristic: plain data that can be mutated in place
    (registries, caches) — the process/ETS analog the graft preserves."""
    return not isinstance(v, (types.FunctionType, type, staticmethod,
                              classmethod, property)) \
        and not isinstance(v, _IMMUTABLE)


def _patch_class(old: type, new: type, failures: list[str],
                 where: str, live_globals: dict,
                 scratch_globals: dict,
                 alias: dict[int, Any] | None = None) -> None:
    for attr, new_val in list(vars(new).items()):
        if attr in ("__dict__", "__weakref__"):
            continue
        old_val = vars(old).get(attr)
        nf, of = _unwrap(new_val), _unwrap(old_val)
        if isinstance(nf, types.FunctionType) \
                and isinstance(of, types.FunctionType) \
                and type(new_val) is type(old_val):
            # in-place __code__ graft only when the wrapper kind matches:
            # a @classmethod -> plain-method (or the reverse) change must
            # adopt the NEW descriptor, or the grafted code runs with the
            # wrong first-argument binding (cls where it expects self)
            _patch_function(of, nf, failures, f"{where}.{attr}")
        elif isinstance(new_val, type) and isinstance(old_val, type):
            _patch_class(old_val, new_val, failures, f"{where}.{attr}",
                         live_globals, scratch_globals, alias)
        elif attr in vars(old) and _is_mutable_data(old_val) \
                and _is_mutable_data(new_val):
            # class-level live state (e.g. a class-attribute registry)
            # is preserved, same rule as module-level data
            pass
        else:
            # new methods, properties, descriptors, constants
            try:
                setattr(old, attr,
                        _rebind(new_val, live_globals, scratch_globals,
                                failures, f"{where}.{attr}", alias))
            except (AttributeError, TypeError) as e:
                failures.append(f"{where}.{attr}: {e}")
    for attr in set(vars(old)) - set(vars(new)):
        if attr.startswith("__") and attr.endswith("__"):
            continue
        try:
            delattr(old, attr)
        except (AttributeError, TypeError):
            pass
    # base-class changes: map scratch-born bases to their live
    # counterparts and swap __bases__; CPython refuses incompatible
    # layouts — that refusal is reported, not guessed around
    new_bases = tuple((alias or {}).get(id(b), b) for b in new.__bases__)
    if old.__bases__ != new_bases:
        try:
            old.__bases__ = new_bases
        except TypeError as e:
            failures.append(f"{where}: base classes changed "
                            f"({old.__bases__} -> {new_bases}) and cannot "
                            f"be swapped live: {e}")


def _exec_fresh(mod: types.ModuleType) -> types.ModuleType:
    """Execute the on-disk source into a scratch module (the loaded
    one is untouched until the graft)."""
    spec = importlib.util.spec_from_file_location(
        mod.__name__, _source_path(mod),
        submodule_search_locations=getattr(mod, "__path__", None))
    fresh = importlib.util.module_from_spec(spec)
    # imports inside the fresh exec must resolve siblings to the LIVE
    # modules (sys.modules), so cross-module references keep identity
    spec.loader.exec_module(fresh)
    return fresh


def _upgrade_module(name: str, report: dict) -> None:
    mod = sys.modules[name]
    old_ns = dict(vars(mod))
    try:
        fresh = _exec_fresh(mod)
    except Exception as e:  # syntax/import error: nothing was touched
        report["failed"][name] = [f"load: {type(e).__name__}: {e}"]
        return

    failures: list[str] = []
    scratch = vars(fresh)
    # scratch object -> live counterpart, for every same-module pair the
    # graft will patch in place; lets base-class swaps resolve a scratch
    # base (class B(A)) to the LIVE patched A
    alias: dict[int, Any] = {
        id(nv): ov
        for attr, nv in scratch.items()
        if not attr.startswith("__")
        for ov in (old_ns.get(attr),)
        # kinds must MATCH: a function->class (or reverse) change is an
        # adoption, not an in-place patch pair
        if (isinstance(ov, type) and isinstance(nv, type))
        or (isinstance(ov, types.FunctionType)
            and isinstance(nv, types.FunctionType))
        if getattr(ov, "__module__", None) == name
    }
    for attr, new_val in scratch.items():
        if attr.startswith("__") and attr != "__updo__":
            continue
        old_val = old_ns.get(attr, _MISSING)
        if new_val is old_val:
            continue  # e.g. an imported live sibling module/object
        if isinstance(old_val, types.FunctionType) \
                and isinstance(new_val, types.FunctionType) \
                and old_val.__module__ == name:
            # old object stays canonical; module keeps exporting it
            _patch_function(old_val, new_val, failures, f"{name}.{attr}")
        elif isinstance(old_val, type) and isinstance(new_val, type) \
                and old_val.__module__ == name:
            _patch_class(old_val, new_val, failures, f"{name}.{attr}",
                         vars(mod), scratch, alias)
        elif attr in old_ns \
                and _is_mutable_data(old_val) and _is_mutable_data(new_val):
            # mutable module state (registries, caches) is preserved;
            # an immutable old value (CONN = None -> CONN = {}) is NOT
            # live state and adopts the new initialiser below
            pass
        else:
            # everything else is the new version's code/constants: new
            # names, changed immutables, and KIND changes (imported
            # helper -> local def, constant -> function, ...) all adopt
            # the new binding
            setattr(mod, attr, _rebind(new_val, vars(mod), scratch,
                                       failures, f"{name}.{attr}", alias))

    removed = []
    for attr, old_val in old_ns.items():
        if attr.startswith("__") or attr in vars(fresh):
            continue
        if getattr(old_val, "__module__", None) == name and \
                isinstance(old_val, (types.FunctionType, type)):
            removed.append(attr)
        try:
            delattr(mod, attr)
        except AttributeError:
            pass

    hook = vars(fresh).get("__updo__")
    if callable(hook):
        try:
            _rebind(hook, vars(mod), scratch,
                    failures, f"{name}.__updo__")(old_ns)
        except Exception as e:
            failures.append(f"{name}.__updo__: {type(e).__name__}: {e}")

    if removed:
        report["removed"][name] = removed
    if failures:
        # partially applied (the patched parts ARE live) — keep the old
        # digest so `updo diff` stays dirty and a fixed source can be
        # re-run; the release handler likewise refuses to mark a bad
        # instruction done
        report["failed"][name] = failures
        return
    d = _source_digest(mod)
    if d:
        _loaded_digests[name] = d
    report["upgraded"].append(name)


def run(dry_run: bool = False) -> dict:
    """Upgrade every changed module (``vmq_updo:run/0``); with
    ``dry_run=True`` return the plan only (``vmq_updo:dry_run/0``)."""
    changed = diff()
    report: dict = {"changed": changed, "upgraded": [], "failed": {},
                    "removed": {}, "dry_run": dry_run}
    if dry_run:
        return report
    for name in changed:
        _upgrade_module(name, report)
        if name in report["failed"]:
            log.warning("updo: %s NOT fully applied: %s", name,
                        "; ".join(report["failed"][name]))
        else:
            log.info("updo: upgraded %s", name)
    return report
