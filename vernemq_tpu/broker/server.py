"""asyncio TCP listeners + per-connection socket loop.

Mirrors the reference socket layer: one lightweight task per connection
(``vmq_ranch.erl:41-43`` — one Erlang process per socket), buffered reparse
of incoming bytes driving the session FSM (``vmq_ranch.erl:167-251``),
write coalescing per event-loop tick (the MSS flush-threshold batching of
``vmq_ranch.erl:253-262``), and protocol detection on the first CONNECT
frame choosing the v4 or v5 FSM (``vmq_mqtt_pre_init.erl:58-70``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional, Tuple

from ..protocol import codec_v4, codec_v5, fastpath, wire
from ..protocol.types import (
    PROTO_5,
    RC_PACKET_TOO_LARGE,
    Connect,
    ParseError,
)
from .broker import Broker
from .session import Session, Transport
from .websocket import WsError

log = logging.getLogger("vernemq_tpu.server")

CONNECT_TIMEOUT = 10.0
MAX_FRAME_SIZE = 268435455


class StreamTransport(Transport):
    """Write-coalescing wrapper over an asyncio StreamWriter: session
    writes within one loop tick collect into ONE iovec (a chunk list)
    that the flush hands to ``writelines`` — one C-level join + one
    syscall-bound send per loop iteration, however many small
    PUBACK/PUBLISH frames landed in it. Compared to the previous
    single-bytearray coalescer this removes the per-write append copy
    entirely: a fanout's shared payload bytes object is referenced from
    every recipient's iovec and only touched once, inside the
    transport's join. The list swap at flush keeps the PR 7
    swap-not-copy behaviour whether or not the native encoder is
    present."""

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self._chunks: list = []
        self._flush_scheduled = False
        self.closed = False

    def write(self, data: bytes) -> None:
        if self.closed:
            return
        self._chunks.append(data)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)

    def write_iov(self, chunks) -> None:
        """Queue a writev-ready iovec (e.g. the native encoder's
        (header, payload) pair) without assembling a per-frame bytes
        object."""
        if self.closed:
            return
        self._chunks.extend(chunks)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if self.closed or not self._chunks:
            return
        chunks, self._chunks = self._chunks, []
        try:
            if len(chunks) == 1:
                self._writer.write(chunks[0])
            else:
                self._writer.writelines(chunks)
        except Exception:
            self.closed = True

    def close(self) -> None:
        if self.closed:
            return
        self._flush()
        self.closed = True
        try:
            self._writer.close()
        except Exception:
            pass


def parse_nodelay_option(raw: str) -> Optional[bool]:
    """Extract the ``nodelay`` flag from the tcp_listen_options knob
    (vmq_server.schema:1454, an erlang proplist string). ``nodelay`` is
    the option that matters for publish latency; the rest of the
    proplist is accepted for compatibility (asyncio owns send
    timeouts/linger). Returns None when the option is absent."""
    if "nodelay" not in raw:
        return None
    return "{nodelay,true}" in raw.replace(" ", "")


def _apply_nodelay(writer: asyncio.StreamWriter, want: bool) -> None:
    sock = writer.get_extra_info("socket")
    if sock is not None:
        import socket as _socket

        try:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY,
                            1 if want else 0)
        except OSError:
            pass


def sniff_proto_ver(body: bytes) -> int:
    """Read the protocol level out of a CONNECT body without committing to a
    codec (vmq_mqtt_pre_init.erl:44-70)."""
    name, pos = wire.take_utf8(body, 0)
    if pos >= len(body):
        raise ParseError("malformed_connect")
    return body[pos] & 0x7F


async def mqtt_connection(
    broker: Broker,
    read_chunk,
    transport: Transport,
    peer: Tuple[str, int],
    max_frame_size: int = MAX_FRAME_SIZE,
    initial: bytes = b"",
    preauth_user: Optional[str] = None,
    mountpoint: str = "",
    allowed_protocol_versions: Optional[Tuple[int, ...]] = None,
) -> None:
    """The per-connection MQTT byte loop, transport-agnostic: ``read_chunk``
    is an awaitable returning the next bytes (b"" on EOF), ``transport``
    writes outbound frames. TCP, TLS, WebSocket and PROXY-wrapped listeners
    all drive their sockets through this one loop (the reference funnels all
    transports into the same FSM contract, vmq_ranch.erl:167-251).
    ``preauth_user`` overrides the CONNECT username (TLS client-cert CN or
    PROXY identity, vmq_ranch.erl:59-72); ``mountpoint`` is the listener's
    multitenancy prefix (per-listener mountpoint config)."""
    metrics = broker.metrics
    metrics.incr("socket_open")
    session: Optional[Session] = None
    buf = initial
    try:
        # ---- pre-init: wait for CONNECT, pick protocol ----------------
        first = wire.split_frame(buf, max_frame_size) if buf else None

        async def _read_connect():
            # wait_for (not asyncio.timeout) — the latter is 3.11+ and
            # this must run on the image's 3.10
            nonlocal buf
            f = first
            while f is None:
                chunk = await read_chunk()
                if not chunk:
                    return None
                metrics.incr("bytes_received", len(chunk))
                buf += chunk
                f = wire.split_frame(buf, max_frame_size)
            return f

        first = await asyncio.wait_for(_read_connect(), CONNECT_TIMEOUT)
        if first is None:
            return
        ptype, flags, body, rest = first
        if ptype != 1:  # must be CONNECT
            return
        proto_ver = sniff_proto_ver(body)
        if (allowed_protocol_versions is not None
                and proto_ver not in allowed_protocol_versions):
            # per-listener version gate (listener.*.allowed_protocol_versions,
            # vmq_server.schema): refuse like an unknown level
            if proto_ver == PROTO_5:
                transport.write(b"\x20\x03\x00\x84\x00")  # v5 rc=0x84
            else:
                transport.write(b"\x20\x02\x00\x01")  # v4 rc=1
            metrics.incr("mqtt_connect_error")
            return
        if proto_ver == PROTO_5:
            codec = codec_v5
        elif proto_ver in (3, 4):
            codec = codec_v4
        else:
            # unknown protocol level: v4-style CONNACK rc=1
            transport.write(b"\x20\x02\x00\x01")
            return
        gov = getattr(broker, "overload", None)
        if gov is not None and gov.refuse_connects():
            # L3 admission control (robustness/overload.py): refuse
            # before any session/auth/registry cost. This is the
            # earliest protocol-aware point we control — with asyncio
            # listeners the TLS handshake has already run by the time
            # the stream reaches us, so "before TLS" is only possible
            # for plain listeners (where there is no handshake to
            # save). v5: CONNACK 0x97 Quota exceeded; v3/4: rc=3
            # Server unavailable.
            metrics.incr("mqtt_connect_error")
            if proto_ver == PROTO_5:
                transport.write(b"\x20\x03\x00\x97\x00")
            else:
                transport.write(b"\x20\x02\x00\x03")
            return
        connect_frame = codec._parse_body(ptype, flags, body)
        if preauth_user is not None:
            connect_frame.username = preauth_user
        session = Session(broker, transport, proto_ver, peer=peer,
                          mountpoint=mountpoint)
        if max_frame_size and max_frame_size < MAX_FRAME_SIZE:
            # the cap THIS listener actually parses with — what the
            # CONNACK maximum_packet_size must announce (a later config
            # change or per-listener override must not let the two lie
            # apart)
            session.max_frame_in = max_frame_size
        ok = await session.handle_connect(connect_frame)
        if not ok and not session._pending_connect:
            return

        # ---- steady-state frame loop ---------------------------------
        # The wire plane (protocol/fastpath.py): each buffered chunk is
        # batch-parsed into a packed frame table in ONE call (native
        # codec when built, bit-identical pure-Python twin otherwise).
        # Admitted PUBLISHes — QoS0 AND QoS1/2 — flow from the table
        # straight into the routing fanout without materialising
        # frame/Msg objects (session.wire_publish_qos0/_qos), and the
        # 2-byte ack family resolves its pid against the in-flight
        # bookkeeping the same way (session.wire_ack); every other
        # record — reason-code acks, retained/dup publishes, protocol
        # edges, malformed input — materialises its frame object and
        # takes the classic handler unchanged.
        buf = bytes(rest)
        frames_run = 0
        v5 = codec is codec_v5
        rec_size = fastpath.REC_SIZE
        unpack_rec = fastpath.REC.unpack_from
        while not session.closed:
            if buf:
                t0 = time.monotonic()
                table, nrec, consumed = fastpath.parse_batch(
                    buf, max_frame_size, v5)
                metrics.observe("stage_wire_parse_ms",
                                (time.monotonic() - t0) * 1e3)
                fast_gate = nrec > 0 and session.wire_fast_ready()
                fast_pubs = 0
                fast_qpubs = 0
                try:
                    for off in range(0, nrec * rec_size, rec_size):
                        rec = unpack_rec(table, off)
                        handled = False
                        if fast_gate:
                            kind = rec[0]
                            if kind == fastpath.K_PUB0 \
                                    and rec[1] == 0x30:
                                if session.wire_publish_qos0(buf, rec):
                                    fast_pubs += 1
                                    handled = True
                            elif kind == fastpath.K_PUB \
                                    and rec[1] in (0x32, 0x34):
                                # QoS1/2, no retain, no dup: the dup
                                # retransmit and retained forms keep
                                # the classic path (dedup/store edges)
                                if session.wire_publish_qos(buf, rec):
                                    fast_qpubs += 1
                                    handled = True
                            elif kind == fastpath.K_ACK:
                                # always resolves (invalid pids count
                                # *_invalid_error exactly like classic)
                                session.wire_ack(rec)
                                handled = True
                        if not handled:
                            try:
                                frame = fastpath.materialize(
                                    codec, buf, rec, max_frame_size)
                            except ParseError as e:
                                if e.reason == "frame_too_large":
                                    # the metric monitoring keys on,
                                    # now that the parser (not the
                                    # session payload check) is the
                                    # enforcement point
                                    metrics.incr(
                                        "mqtt_invalid_msg_size_error")
                                    if session.proto_ver == PROTO_5 \
                                            and not session.closed:
                                        # tell a v5 client WHY before
                                        # dropping the socket (MQTT5
                                        # 3.2.2.3.6 / DISCONNECT 0x95)
                                        await session._disconnect_v5(
                                            RC_PACKET_TOO_LARGE)
                                raise
                            await session.handle_frame(frame)
                            if session.closed:
                                break
                            # every classic frame is an await — policy
                            # (governor level, hooks, tracer) may have
                            # moved while we yielded, so the remaining
                            # fast records must re-pass the gate
                            fast_gate = (fast_gate
                                         and session.wire_fast_ready())
                        frames_run += 1
                        if frames_run >= 64:
                            # bound the synchronous run per read chunk:
                            # a 64KB chunk can hold ~700 small
                            # PUBLISHes, and a handler that never truly
                            # awaits would process them all in ONE loop
                            # callback — a flood connection must not
                            # stall every other session's IO (and the
                            # sysmon sampler) for the whole chunk
                            frames_run = 0
                            await asyncio.sleep(0)
                            if session.closed:  # closed while yielded
                                break
                            # re-check the batch gate after yielding:
                            # the governor/hooks may have moved while
                            # we slept
                            fast_gate = (fast_gate
                                         and session.wire_fast_ready())
                finally:
                    # a mid-batch error (malformed frame after admitted
                    # publishes) must not lose the bookkeeping for
                    # fast-path messages already routed and delivered
                    if fast_pubs or fast_qpubs:
                        session.wire_fast_done(fast_pubs, fast_qpubs)
                if session.closed:
                    break
                buf = buf[consumed:] if consumed else buf
            if session.connected:
                chunk = await read_chunk()
            else:
                # still inside the CONNECT/enhanced-AUTH exchange: keep
                # the pre-init deadline so parked half-auth connections
                # can't pin sockets forever
                chunk = await asyncio.wait_for(read_chunk(), CONNECT_TIMEOUT)
            if not chunk:
                break
            metrics.incr("bytes_received", len(chunk))
            buf += chunk
    except (asyncio.TimeoutError, TimeoutError):
        pass
    except ParseError as e:
        log.debug("parse error from %s: %s", peer, e.reason)
        metrics.incr("socket_error")
    except WsError as e:
        log.debug("websocket error from %s: %s", peer, e)
        metrics.incr("socket_error")
    except ConnectionError:
        metrics.incr("socket_error")
    except Exception:
        log.exception("connection handler crashed")
        metrics.incr("socket_error")
    finally:
        if session is not None and not session.closed:
            await session.close("connection_lost")
        transport.close()
        metrics.incr("socket_close")


class MQTTServer:
    def __init__(self, broker: Broker, host: str = "127.0.0.1", port: int = 1883,
                 max_frame_size: int = 0, ssl_context=None,
                 proxy_protocol: bool = False,
                 use_identity_as_username: bool = False,
                 mountpoint: str = "",
                 allowed_protocol_versions=None,
                 max_connections: int = 0,
                 reuse_port: bool = False):
        self.broker = broker
        self.host = host
        self.port = port
        # per-listener override, else the broker-wide max_message_size
        # (the reference's semantic: vmq_parser.erl enforces it on every
        # packet type as a REMAINING-LENGTH cap — total accepted bytes
        # are at most cap + 5B of fixed header, the lenient direction
        # the spec allows relative to the announced value)
        self.max_frame_size = (max_frame_size
                               or broker.config.get("max_message_size", 0)
                               or MAX_FRAME_SIZE)
        self.ssl_context = ssl_context
        self.proxy_protocol = proxy_protocol
        self.use_identity_as_username = use_identity_as_username
        self.mountpoint = mountpoint
        self.allowed_protocol_versions = (
            tuple(allowed_protocol_versions)
            if allowed_protocol_versions else None)
        self.max_connections = int(max_connections or 0)
        self.connection_count = 0
        # SO_REUSEPORT lets N worker processes share one listen port with
        # kernel-level accept balancing (the multi-process scale-out path,
        # broker/workers.py — the vmq_ranch all-schedulers seat)
        self.reuse_port = reuse_port
        # parsed once at listener construction — the accept path only
        # applies the cached flag
        self._nodelay = parse_nodelay_option(
            str(broker.config.get("tcp_listen_options", "") or ""))
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, ssl=self.ssl_context,
            reuse_port=self.reuse_port or None,
        )
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        self.broker._servers.append(self._server)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if (self.max_connections
                and self.connection_count >= self.max_connections):
            # listener connection cap (listener.*.max_connections): refuse
            # at accept like ranch's max_connections
            self.broker.metrics.incr("socket_error")
            writer.close()
            return
        self.connection_count += 1
        try:
            await self._handle_conn_inner(reader, writer)
        finally:
            self.connection_count -= 1

    async def _handle_conn_inner(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername") or ("", 0)
        if self._nodelay is not None:
            _apply_nodelay(writer, self._nodelay)
        initial = b""
        preauth: Optional[str] = None
        if self.proxy_protocol:
            from .proxy_proto import ProxyProtoError, read_proxy_header

            try:
                info = await asyncio.wait_for(read_proxy_header(reader),
                                              CONNECT_TIMEOUT)
            except (ProxyProtoError, asyncio.TimeoutError, ConnectionError,
                    asyncio.IncompleteReadError, asyncio.LimitOverrunError):
                writer.close()
                return
            if info.src is not None:
                peer = info.src
            if self.use_identity_as_username:
                if not info.cn:
                    # identity mapping requires the PP2 SSL CN TLV — same
                    # policy as the TLS path (no silent fall-through)
                    writer.close()
                    return
                preauth = info.cn
        else:
            from .ssl_util import preauth_from_cert

            ok, preauth = preauth_from_cert(
                writer, self.use_identity_as_username, self.ssl_context)
            if not ok:
                writer.close()  # cert required for identity mapping
                return
        transport = StreamTransport(writer)
        try:
            await mqtt_connection(
                self.broker, lambda: reader.read(65536), transport, peer,
                self.max_frame_size, initial=initial, preauth_user=preauth,
                mountpoint=self.mountpoint,
                allowed_protocol_versions=self.allowed_protocol_versions)
        finally:
            try:
                await writer.wait_closed()
            except Exception:
                pass


async def start_broker(
    config=None, host: str = "127.0.0.1", port: int = 1883,
    node_name: str = "node1",
    cluster_listen: Optional[Tuple[str, int]] = None,
    join: Optional[Tuple[str, int]] = None,
    reuse_port: bool = False,
) -> Tuple[Broker, MQTTServer]:
    """Boot a broker with one MQTT listener (vmq_test_utils:setup-style
    convenience; port=0 picks a random free port). ``cluster_listen``
    additionally starts the inter-node channel listener (the reference's
    ``vmq`` listener type, vmq_ranch_config.erl:224-227); ``join`` dials a
    seed node. ``reuse_port`` lets worker processes share the MQTT port
    (broker/workers.py)."""
    broker = Broker(config, node_name=node_name)
    await broker.start()
    from .listeners import ListenerManager

    manager = ListenerManager(broker)
    server = await manager.start_listener(
        "mqtt", host, port, {"reuse_port": reuse_port} if reuse_port else None)
    if cluster_listen is not None:
        from ..cluster import Cluster

        cluster = Cluster(broker, cluster_listen[0], cluster_listen[1])
        await cluster.start()
        if join is not None:
            cluster.join(*join)
    return broker, server


def main() -> None:  # pragma: no cover
    import argparse

    parser = argparse.ArgumentParser(description="vernemq_tpu broker")
    parser.add_argument("--conf", default=None, metavar="PATH",
                        help="vernemq.conf-style config file (broker/conf.py)")
    parser.add_argument("--allow-anonymous", action="store_true",
                        help="accept connects without an auth plugin "
                             "(allow_anonymous=on)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=1883)
    parser.add_argument("--reg-view", default=None, choices=["trie", "tpu"],
                        help="subscription matcher (the default_reg_view "
                             "seam); overrides --conf when given")
    parser.add_argument("--tpu-mesh", default=None, metavar="BxS",
                        help="serve matching on a device mesh (e.g. 2x4: "
                             "batch x sub axes; implies --reg-view tpu)")
    parser.add_argument("--jax-platform", default=None,
                        help="force the JAX backend (e.g. cpu); note this "
                             "image's jax ignores the JAX_PLATFORMS env var — "
                             "only jax.config takes effect")
    parser.add_argument("--node-name", default="node1")
    parser.add_argument("--http-port", type=int, default=None,
                        help="start the HTTP endpoint (metrics/health/"
                             "status/mgmt API) on this port")
    parser.add_argument("--no-mgmt-auth", action="store_true",
                        help="disable api-key auth on the management API")
    parser.add_argument("--cluster-listen", default=None, metavar="HOST:PORT",
                        help="start the inter-node cluster listener")
    parser.add_argument("--join", default=None, metavar="HOST:PORT",
                        help="join an existing cluster via this seed node")
    args = parser.parse_args()
    if args.jax_platform:
        import jax

        jax.config.update("jax_platforms", args.jax_platform)

    def _addr(s):
        h, _, p = s.rpartition(":")
        return (h or "127.0.0.1", int(p))

    async def _run():
        from .config import Config

        cfg = Config.from_file(args.conf) if args.conf else Config()
        if args.reg_view:
            cfg.set("default_reg_view", args.reg_view)
        if args.tpu_mesh:
            if args.reg_view == "trie":
                parser.error("--tpu-mesh requires the tpu reg view; "
                             "drop --reg-view trie")
            cfg.set("tpu_mesh", args.tpu_mesh)
            cfg.set("default_reg_view", "tpu")
        if args.allow_anonymous:
            cfg.set("allow_anonymous", True)
        if args.http_port is not None:
            cfg.set("http_enabled", True)
            cfg.set("http_port", args.http_port)
            cfg.set("http_host", args.host)
        if args.no_mgmt_auth:
            cfg.set("http_mgmt_api_auth", False)
        broker, server = await start_broker(
            cfg, host=args.host,
            port=args.port, node_name=args.node_name,
            cluster_listen=_addr(args.cluster_listen) if args.cluster_listen else None,
            join=_addr(args.join) if args.join else None,
        )
        print(f"vernemq_tpu broker {args.node_name} listening on "
              f"{args.host}:{server.port}", flush=True)
        if broker.http is not None:
            print(f"http endpoint on {broker.http.host}:{broker.http.port}",
                  flush=True)
        if broker.cluster is not None:
            print(f"cluster listener on {broker.cluster.listen_host}:"
                  f"{broker.cluster.listen_port}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(_run())


if __name__ == "__main__":  # pragma: no cover
    main()
