"""Reference config-schema surface.

The reference compiles ``vernemq.conf`` through 217 cuttlefish mappings
(``apps/vmq_server/priv/vmq_server.schema``). This module is the
authoritative classification of that surface for the conf-file loader
(:mod:`vernemq_tpu.broker.conf`): every mapping name either

- maps onto a :data:`~vernemq_tpu.broker.config.DEFAULTS` knob (same
  name, an alias, or a unit conversion),
- is a listener-tree option (``listener.<kind>[.<name>].<opt>``), or
- is a **deliberate gap** — rejected with a reason naming the
  architectural difference, never silently dropped.

``tests/test_conf.py`` diffs this classification against the mapping
list extracted from the reference schema file, so coverage can't rot
silently.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

# --------------------------------------------------------------- flat knobs

#: schema names that resolve to a different DEFAULTS key
FLAT_ALIASES: Dict[str, str] = {
    # vmq_server.schema:62 — documented alias of max_message_size
    "message_size_limit": "max_message_size",
    # the storage engine is the C++ kvstore, not leveldb, but the knob's
    # meaning (message-store directory) carries over
    "leveldb_message_store.directory": "message_store_dir",
    # metadata directory (the plumtree/swc on-disk seat)
    "plumtree.directory": "metadata_dir",
    "plumtree.outstanding_limit": "plumtree_outstanding_limit",
    "plumtree.drop_i_have_threshold": "plumtree_drop_ihave_threshold",
    # release-script knobs; honored as base directories at boot
    "setup.data_dir": "data_dir",
    "setup.log_dir": "log_dir",
    # vmq_swc.schema's db_backend knob (leveldb/rocksdb/leveled there;
    # kvstore/bucketed here — the same engine-choice seam)
    "vmq_swc.db_backend": "swc_db_backend",
}

#: extension family: the adaptive overload governor
#: (robustness/overload.py). The reference exposes its load shedding
#: through vmq_ranch/vmq_queue internals without conf knobs; ours is
#: operator-tunable, so the flat ``overload_*`` DEFAULTS also get a
#: dotted ``overload.<knob>`` conf-tree spelling, consistent with the
#: reference's dotted trees (plumtree.*, setup.*).
FLAT_ALIASES.update({
    f"overload.{k[len('overload_'):]}": k
    for k in (
        "overload_mode", "overload_tick_ms", "overload_hold_s",
        "overload_exit_ratio", "overload_l1_enter", "overload_l2_enter",
        "overload_l3_enter", "overload_l1_throttle_ms",
        "overload_l2_client_rate", "overload_l2_burst",
        "overload_l3_disconnect_top", "overload_dispatch_budget_ms",
    )
})

#: extension family: the stall watchdog (robustness/watchdog.py) —
#: deadline abandonment for silent stalls; same dotted-tree spelling
#: discipline as overload.* above
FLAT_ALIASES.update({
    f"watchdog.{k[len('watchdog_'):]}": k
    for k in (
        "watchdog_enabled", "watchdog_tick_ms",
        "watchdog_dispatch_deadline_ms", "watchdog_rebuild_deadline_s",
        "watchdog_collector_expiry_budgets",
    )
})
FLAT_ALIASES["watchdog.cluster_stall_timeout_s"] = "cluster_stall_timeout_s"

#: extension family: the live-handoff state machine
#: (cluster/handoff.py) — freeze→drain→fence→adopt elastic
#: rebalancing; same dotted-tree spelling discipline as overload.*
FLAT_ALIASES.update({
    f"handoff.{k[len('handoff_'):]}": k
    for k in (
        "handoff_freeze_deadline_ms", "handoff_drain_deadline_s",
        "handoff_v5_redirect", "handoff_batch_max_sessions",
    )
})
FLAT_ALIASES["mqtt5.qos2_dedup_max"] = "qos2_dedup_max"

#: extension family: the membership health plane (cluster/health.py) —
#: accrual failure detection + the automatic rebalance planner. The
#: flat spellings keep their subsystem prefixes (health_*,
#: rebalance_*); the dotted tree groups them under cluster.* with the
#: other cluster knobs.
FLAT_ALIASES.update({
    f"cluster.{k}": k
    for k in (
        "health_enabled", "health_tick_ms", "health_window",
        "health_phi_suspect", "health_phi_down", "health_exit_ratio",
        "health_hold_s", "rebalance_enabled",
        "rebalance_require_quorum", "rebalance_debounce_s",
        "rebalance_cooldown_s", "rebalance_max_concurrent",
    )
})
FLAT_ALIASES["cluster.advertised_address"] = "cluster_advertised_address"

#: extension family: the multi-process session front end
#: (broker/workers.py / broker/match_service.py). The plumbing knobs
#: (ring/stats segment names, worker index) are set by the WorkerGroup
#: parent, never by conf files — only the operator-facing ones get a
#: dotted spelling.
FLAT_ALIASES.update({
    "workers.count": "workers",
    "workers.match_service_timeout_ms": "match_service_timeout_ms",
})

#: extension family: the hot-path flight recorder / stage histograms
#: (vernemq_tpu/observability/) — same dotted-tree discipline
FLAT_ALIASES.update({
    "observability.enabled": "observability_enabled",
    "observability.sample_n": "flight_recorder_sample_n",
    "observability.recorder_capacity": "flight_recorder_capacity",
    "observability.profiler_capacity": "profiler_capacity",
    "observability.events_capacity": "events_capacity",
    "observability.canary_enabled": "canary_enabled",
    "observability.canary_interval_ms": "canary_interval_ms",
    "observability.canary_slo_ms": "canary_slo_ms",
})

#: extension family: the mesh-native matcher (parallel/mesh_match.py)
#: + slice map (cluster/mesh_map.py) — same dotted-tree discipline
FLAT_ALIASES.update({
    "mesh.topology": "tpu_mesh",
    "mesh.native": "tpu_mesh_native",
})

#: extension family: the native wire plane (protocol/fastpath.py) —
#: same dotted-tree discipline
FLAT_ALIASES.update({
    "wire.fastpath_enabled": "wire_fastpath_enabled",
})

#: extension family: the unified storage tier (storage/segment.py +
#: storage/resume.py) — segment engine geometry, the budgeted
#: compaction driver, and batched reconnect-storm resumption
FLAT_ALIASES.update({
    "store.segment_max_bytes": "store_segment_max_bytes",
    "store.checkpoint_every_bytes": "store_checkpoint_every_bytes",
    "store.compact_interval_ms": "store_compact_interval_ms",
    "store.compact_budget_bytes": "store_compact_budget_bytes",
    "store.expire_sweep_budget": "store_expire_sweep_budget",
    "store.fsync": "msg_store_fsync",
    "store.group_commit": "msg_store_group_commit",
    "resume.batched": "resume_batched",
    "resume.window_us": "resume_window_us",
    "resume.max_batch": "resume_max_batch",
    "resume.host_threshold": "resume_host_threshold",
    "resume.expiry_ms": "resume_expiry_ms",
})

#: extension family: payload filtering & windowed aggregation
#: (vernemq_tpu/filters/) — the MQTT+ predicate/aggregate surface;
#: schema DEFINITIONS are replicated state (`vmq-admin schema set` /
#: the payload_schemas config list), these are the serving knobs
FLAT_ALIASES.update({
    "payload_schema.enabled": "payload_filters_enabled",
    "payload_schema.host_threshold": "predicate_host_threshold",
    "payload_schema.max_pairs": "predicate_max_pairs",
    "payload_schema.initial_windows": "aggregate_initial_windows",
    "payload_schema.max_windows": "aggregate_max_windows",
    "payload_schema.window_tick_ms": "aggregate_tick_ms",
})

#: reference knobs typed in MILLISECONDS whose internal knob is seconds
MS_TO_SECONDS = {
    "systree_interval",
    "graphite_interval",
    "graphite_connect_timeout",
    "graphite_reconnect_timeout",
}

#: knobs taking cuttlefish duration strings ("never", "1w", "30m", "0s");
#: parsed to seconds
DURATION_KEYS = {
    "persistent_client_expiration",
    "max_last_will_delay",
}

#: reference http_modules entries -> our admin/http module names
HTTP_MODULE_ALIASES = {
    "vmq_metrics_http": "metrics",
    "vmq_http_mgmt_api": "mgmt",
    "vmq_status_http": "status",
    "vmq_health_http": "health",
}

#: reference reg_views entries -> our reg-view seam names
REG_VIEW_ALIASES = {"vmq_reg_trie": "trie", "vmq_reg_tpu": "tpu",
                    "trie": "trie", "tpu": "tpu"}

# ------------------------------------------------------------ listener tree

#: conf-file listener kind -> ListenerManager kind
#: (vmq_ranch_config.erl:224-227) — single source for both the
#: classifier and the conf loader's settings builder
INTERNAL_KINDS: Dict[str, str] = {
    "tcp": "mqtt", "ssl": "mqtts", "ws": "ws", "wss": "wss",
    "http": "http", "https": "https", "vmq": "vmq", "vmqs": "vmqs",
}
LISTENER_KINDS = tuple(INTERNAL_KINDS)
TLS_KINDS = ("ssl", "wss", "https", "vmqs")

#: listener options whose values must be integers — non-numeric values
#: fail at parse time (ConfError), not at broker boot
INT_LISTENER_OPTS = {"max_connections", "nr_of_acceptors", "depth",
                     "max_frame_size"}

#: options valid on EVERY listener kind: schema spelling -> internal opt
COMMON_LISTENER_OPTS: Dict[str, str] = {
    "max_connections": "max_connections",
    "nr_of_acceptors": "nr_of_acceptors",
    "mountpoint": "mountpoint",
}

#: extra options per kind (schema spelling -> internal opt)
EXTRA_LISTENER_OPTS: Dict[str, Dict[str, str]] = {
    "tcp": {
        "proxy_protocol": "proxy_protocol",
        "proxy_protocol_use_cn_as_username":
            "proxy_protocol_use_cn_as_username",
        "allowed_protocol_versions": "allowed_protocol_versions",
    },
    "ws": {
        "proxy_protocol": "proxy_protocol",
        "proxy_protocol_use_cn_as_username":
            "proxy_protocol_use_cn_as_username",
        "allowed_protocol_versions": "allowed_protocol_versions",
    },
    "wss": {
        "allowed_protocol_versions": "allowed_protocol_versions",
    },
    "ssl": {
        "allowed_protocol_versions": "allowed_protocol_versions",
    },
    "http": {
        "proxy_protocol": "proxy_protocol",
        "proxy_protocol_use_cn_as_username":
            "proxy_protocol_use_cn_as_username",
        "http_modules": "http_modules",
    },
    "https": {"http_modules": "http_modules"},
    "vmq": {},
    "vmqs": {},
}

#: TLS options (only on TLS kinds): schema spelling -> internal opt
TLS_LISTENER_OPTS: Dict[str, str] = {
    "cafile": "cafile",
    "certfile": "certfile",
    "keyfile": "keyfile",
    "ciphers": "ciphers",
    "crlfile": "crl_file",
    "depth": "depth",
    "require_certificate": "require_certificate",
    "tls_version": "tls_version",
    "use_identity_as_username": "use_identity_as_username",
}

# --------------------------------------------------------- deliberate gaps

#: mapping name (or listener option) -> reason it is rejected. These are
#: architectural, not omissions: the error message names the reason so an
#: operator migrating a vernemq.conf knows what to do.
GAPS: Dict[str, str] = {
    "listener.http.$name.config_mod":
        "Erlang module hooks cannot be loaded; mount custom HTTP "
        "endpoints via admin/http.py modules instead",
    "listener.http.$name.config_fun":
        "Erlang module hooks cannot be loaded; mount custom HTTP "
        "endpoints via admin/http.py modules instead",
    "listener.https.$name.config_mod":
        "Erlang module hooks cannot be loaded; mount custom HTTP "
        "endpoints via admin/http.py modules instead",
    "listener.https.$name.config_fun":
        "Erlang module hooks cannot be loaded; mount custom HTTP "
        "endpoints via admin/http.py modules instead",
}

#: accepted-for-compatibility knobs with no behavioral effect here; the
#: conf loader logs the note once instead of erroring (an operator's
#: existing vernemq.conf must not fail to boot over a knob whose concern
#: does not exist in this architecture)
COMPAT_NOOPS: Dict[str, str] = {
    "queue_sup_sup_children":
        "queues live in an O(1) dict registry, not a supervisor tree; "
        "accepted for compatibility, no effect",
    "systree_reg_view":
        "systree publishes route through the configured default_reg_view; "
        "per-publisher views are not separated",
    "graphite_include_labels":
        "metrics are emitted unlabeled; accepted for compatibility",
    "nr_of_acceptors":
        "asyncio listeners have a single accept loop; accepted for "
        "compatibility, no effect",
    "proxy_protocol_use_cn_as_username":
        "PROXY v2 TLS CN forwarding is not extracted; use "
        "use_identity_as_username on TLS listeners instead",
    "shared_subscription_timeout_action":
        "remote shared-subscription deliveries are acked asynchronously; "
        "timed-out deliveries are retried by the queue, 'requeue' "
        "semantics are always in effect",
}


_LISTENER_RE = re.compile(r"^listener\.(?P<kind>[a-z]+)"
                          r"(?:\.(?P<rest>.+))?$")


def classify_listener_key(
    key: str,
) -> Optional[Tuple[str, Optional[str], Optional[str], Optional[str]]]:
    """Classify a ``listener.*`` conf key.

    Returns ``(scope, kind, name, opt)`` where scope is one of
    ``"global-opt"`` (listener.<opt>), ``"kind-opt"``
    (listener.<kind>.<opt>), ``"addr"`` (listener.<kind>.<name>), or
    ``"name-opt"`` (listener.<kind>.<name>.<opt>) — or None if the key
    is not a listener key. Raises KeyError with a reason for unknown
    kinds/options and deliberate gaps.

    Disambiguation rule (same as cuttlefish's): a third segment that is
    a known option name for the kind is a kind-level default; anything
    else is a listener name (you cannot name a listener 'mountpoint').
    """
    if not key.startswith("listener."):
        return None
    parts = key.split(".")
    if len(parts) == 2:
        opt = parts[1]
        if opt not in COMMON_LISTENER_OPTS:
            raise KeyError(
                f"unknown global listener option {opt!r} "
                f"(valid: {', '.join(sorted(COMMON_LISTENER_OPTS))})")
        return ("global-opt", None, None, COMMON_LISTENER_OPTS[opt])
    kind = parts[1]
    if kind not in LISTENER_KINDS:
        raise KeyError(f"unknown listener kind {kind!r} "
                       f"(valid: {', '.join(LISTENER_KINDS)})")
    valid = dict(COMMON_LISTENER_OPTS)
    valid.update(EXTRA_LISTENER_OPTS.get(kind, {}))
    if kind in TLS_KINDS:
        valid.update(TLS_LISTENER_OPTS)
    if len(parts) == 3:
        seg = parts[2]
        if seg in valid:
            return ("kind-opt", kind, None, valid[seg])
        return ("addr", kind, seg, None)
    name, opt = parts[2], ".".join(parts[3:])
    gap = GAPS.get(f"listener.{kind}.$name.{opt}")
    if gap is not None:
        raise KeyError(f"deliberate gap: {gap}")
    if opt not in valid:
        # tolerate our own extension opts that predate this schema layer
        if opt in ("max_frame_size", "buffer_sizes"):
            return ("name-opt", kind, name, opt)
        raise KeyError(
            f"unknown listener option {opt!r} for kind {kind!r} "
            f"(valid: {', '.join(sorted(valid))})")
    return ("name-opt", kind, name, valid[opt])


_DUR_RE = re.compile(r"(\d+)\s*(ms|[smhdwy])")
_DUR_SECONDS = {"ms": 0.001, "s": 1, "m": 60, "h": 3600, "d": 86400,
                "w": 604800, "y": 31557600}


def parse_duration(raw: str) -> int:
    """Cuttlefish duration string -> whole seconds. Accepts ``never``
    (0), bare integers (seconds), and concatenated units (``1w2d``,
    ``30m``). Non-zero sub-second values round UP to 1s — truncating to
    0 would invert the semantics (0 means "never" for
    persistent_client_expiration)."""
    s = raw.strip().lower()
    if s in ("never", "0"):
        return 0
    if s.isdigit():
        return int(s)
    total = 0.0
    pos = 0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            break
        total += int(m.group(1)) * _DUR_SECONDS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"bad duration {raw!r} "
                         "(expected e.g. never, 1w, 30m, 1w2d)")
    if 0 < total < 1:
        return 1
    return int(total)


def reference_mapping_names(schema_text: str):
    """Extract the mapping names from a cuttlefish schema file (for the
    coverage test)."""
    return re.findall(r'\{mapping,\s*"([^"]+)"', schema_text)
