"""HAProxy PROXY protocol v1/v2 (``vmq_ranch_proxy_protocol.erl``).

A load balancer in front of the broker prepends one header carrying the
real client address (and, for v2 with TLS, the client-cert common name via
the PP2_SUBTYPE_SSL_CN TLV) before the MQTT byte stream starts. The
listener reads it, rewrites the peer, and can use the CN as the
authenticated username (``vmq_ranch.erl:59-72`` CN-as-username support).
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass
from typing import Optional, Tuple

V2_SIG = b"\r\n\r\n\x00\r\nQUIT\n"

# v2 TLV types (PP2)
PP2_TYPE_SSL = 0x20
PP2_SUBTYPE_SSL_CN = 0x22


class ProxyProtoError(Exception):
    pass


@dataclass
class ProxyInfo:
    src: Optional[Tuple[str, int]]  # real client address; None for LOCAL
    dst: Optional[Tuple[str, int]]
    cn: Optional[str] = None  # client-cert common name (v2 SSL TLV)


async def read_proxy_header(reader: asyncio.StreamReader) -> ProxyInfo:
    """Consume exactly one PROXY header from the stream; the MQTT bytes
    start right after (no buffered overshoot — reads are exact-length)."""
    probe = await reader.readexactly(1)
    if probe == b"P":
        line = probe + await reader.readuntil(b"\r\n")
        return _parse_v1(line)
    if probe == b"\r":
        rest = await reader.readexactly(len(V2_SIG) - 1)
        if probe + rest != V2_SIG:
            raise ProxyProtoError("bad v2 signature")
        return await _parse_v2(reader)
    raise ProxyProtoError("not a PROXY header")


def _parse_v1(line: bytes) -> ProxyInfo:
    if len(line) > 107:
        raise ProxyProtoError("v1 header too long")
    parts = line.decode("ascii", "replace").rstrip("\r\n").split(" ")
    if parts[0] != "PROXY":
        raise ProxyProtoError("bad v1 magic")
    if len(parts) >= 2 and parts[1] == "UNKNOWN":
        return ProxyInfo(src=None, dst=None)
    if len(parts) != 6 or parts[1] not in ("TCP4", "TCP6"):
        raise ProxyProtoError("bad v1 fields")
    try:
        return ProxyInfo(src=(parts[2], int(parts[4])),
                         dst=(parts[3], int(parts[5])))
    except ValueError as e:
        raise ProxyProtoError(f"bad v1 ports: {e}") from None


async def _parse_v2(reader: asyncio.StreamReader) -> ProxyInfo:
    hdr = await reader.readexactly(4)
    ver_cmd, fam, length = hdr[0], hdr[1], struct.unpack(">H", hdr[2:4])[0]
    if ver_cmd >> 4 != 2:
        raise ProxyProtoError("bad v2 version")
    body = await reader.readexactly(length) if length else b""
    cmd = ver_cmd & 0x0F
    if cmd == 0x00:  # LOCAL (health check): no address override
        return ProxyInfo(src=None, dst=None)
    if cmd != 0x01:
        raise ProxyProtoError("bad v2 command")
    import socket

    src = dst = None
    off = 0
    proto = fam >> 4
    if proto == 0x1:  # AF_INET
        if length < 12:
            raise ProxyProtoError("short v2 inet body")
        s, d, sp, dp = struct.unpack(">4s4sHH", body[:12])
        src = (socket.inet_ntop(socket.AF_INET, s), sp)
        dst = (socket.inet_ntop(socket.AF_INET, d), dp)
        off = 12
    elif proto == 0x2:  # AF_INET6
        if length < 36:
            raise ProxyProtoError("short v2 inet6 body")
        s, d, sp, dp = struct.unpack(">16s16sHH", body[:36])
        src = (socket.inet_ntop(socket.AF_INET6, s), sp)
        dst = (socket.inet_ntop(socket.AF_INET6, d), dp)
        off = 36
    else:  # AF_UNSPEC / AF_UNIX: ignore addresses
        return ProxyInfo(src=None, dst=None)
    cn = _find_cn(body[off:])
    return ProxyInfo(src=src, dst=dst, cn=cn)


def _find_cn(tlvs: bytes) -> Optional[str]:
    """Walk v2 TLVs for the SSL sub-TLV carrying the client-cert CN."""
    i = 0
    while i + 3 <= len(tlvs):
        t = tlvs[i]
        ln = struct.unpack(">H", tlvs[i + 1:i + 3])[0]
        v = tlvs[i + 3:i + 3 + ln]
        if t == PP2_TYPE_SSL and len(v) >= 5:
            # client(1) verify(4) then sub-TLVs
            j = 5
            while j + 3 <= len(v):
                st = v[j]
                sln = struct.unpack(">H", v[j + 1:j + 3])[0]
                if st == PP2_SUBTYPE_SSL_CN:
                    return v[j + 3:j + 3 + sln].decode("utf-8", "replace")
                j += 3 + sln
        i += 3 + ln
    return None


def build_v1(src: Tuple[str, int], dst: Tuple[str, int]) -> bytes:
    fam = "TCP6" if ":" in src[0] else "TCP4"
    return (f"PROXY {fam} {src[0]} {dst[0]} {src[1]} {dst[1]}\r\n"
            .encode("ascii"))


def build_v2(src: Tuple[str, int], dst: Tuple[str, int],
             cn: Optional[str] = None) -> bytes:
    import socket

    body = (socket.inet_pton(socket.AF_INET, src[0])
            + socket.inet_pton(socket.AF_INET, dst[0])
            + struct.pack(">HH", src[1], dst[1]))
    if cn is not None:
        cn_b = cn.encode()
        sub = bytes([PP2_SUBTYPE_SSL_CN]) + struct.pack(">H", len(cn_b)) + cn_b
        ssl_v = b"\x01" + b"\x00\x00\x00\x00" + sub
        body += bytes([PP2_TYPE_SSL]) + struct.pack(">H", len(ssl_v)) + ssl_v
    return V2_SIG + b"\x21\x11" + struct.pack(">H", len(body)) + body
