"""TLS listener support (``vmq_ssl.erl``): server SSLContext construction
from listener options and client-cert → username extraction
(``vmq_ssl.erl:4`` ``socket_to_common_name/1``)."""

from __future__ import annotations

import ssl
from typing import Any, Dict, Optional, Tuple


def make_server_context(opts: Dict[str, Any]) -> ssl.SSLContext:
    """Options follow the reference listener schema: certfile, keyfile,
    cafile, require_certificate, ciphers, tls_version."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    certfile = opts.get("certfile")
    if not certfile:
        raise ValueError("TLS listener needs certfile")
    ctx.load_cert_chain(certfile, opts.get("keyfile") or None)
    cafile = opts.get("cafile")
    if cafile:
        ctx.load_verify_locations(cafile)
    if opts.get("require_certificate"):
        ctx.verify_mode = ssl.CERT_REQUIRED
    elif cafile:
        ctx.verify_mode = ssl.CERT_OPTIONAL
    crl_file = opts.get("crl_file")
    if crl_file:
        # load at startup, not only at the first periodic refresh — a
        # revoked cert must not be accepted during the first
        # crl_refresh_interval window (vmq_crl_srv checks on listener start)
        ctx.load_verify_locations(cafile=crl_file)
        ctx.verify_flags |= ssl.VERIFY_CRL_CHECK_LEAF
    ciphers = opts.get("ciphers")
    if ciphers:
        ctx.set_ciphers(ciphers)
    tls_version = opts.get("tls_version")
    if tls_version:
        minimum = {
            "tlsv1.2": ssl.TLSVersion.TLSv1_2,
            "tlsv1.3": ssl.TLSVersion.TLSv1_3,
        }.get(str(tls_version).lower())
        if minimum is not None:
            ctx.minimum_version = minimum
    return ctx


def make_client_context(opts: Dict[str, Any]) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    cafile = opts.get("cafile")
    if cafile:
        ctx.load_verify_locations(cafile)
    else:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if opts.get("certfile"):
        ctx.load_cert_chain(opts["certfile"], opts.get("keyfile") or None)
    if not opts.get("verify_hostname", False):
        ctx.check_hostname = False
    return ctx


def preauth_from_cert(writer, use_identity_as_username: bool,
                      ssl_context) -> "Tuple[bool, Optional[str]]":
    """Shared TLS identity-mapping policy for all listener types: when
    use_identity_as_username is on, a verified client cert CN is required —
    (ok, username). ok=False → the listener must drop the connection."""
    if not use_identity_as_username or ssl_context is None:
        return True, None
    cn = peer_common_name(writer)
    if cn is None:
        return False, None
    return True, cn


def peer_common_name(writer) -> Optional[str]:
    """CN of the verified client certificate on an asyncio TLS connection
    (socket_to_common_name)."""
    cert = writer.get_extra_info("peercert")
    if not cert:
        return None
    for rdn in cert.get("subject", ()):
        for key, value in rdn:
            if key == "commonName":
                return value
    return None
