"""MQTT over WebSocket (``vmq_websocket.erl``): RFC 6455 server handshake
negotiating the ``mqtt`` / ``mqttv3.1`` subprotocols
(``vmq_websocket.erl:37-50``), binary frames carrying the MQTT byte stream
into the same session loop all other transports use. No cowboy — the
handshake and framing are implemented directly over asyncio streams."""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
import struct
from typing import Optional, Tuple

from .session import Transport

log = logging.getLogger("vernemq_tpu.websocket")

GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
SUBPROTOCOLS = ("mqtt", "mqttv3.1")

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

MAX_WS_FRAME = 1 << 24
MAX_WS_MESSAGE = 1 << 26  # cumulative cap across fragments (DoS guard)


class WsError(Exception):
    pass


def accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + GUID).encode()).digest()).decode()


async def server_handshake(reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter,
                           path_prefix: str = "/mqtt") -> Optional[str]:
    """Read the HTTP Upgrade request, answer 101. Returns the negotiated
    subprotocol (or None on a failed handshake, after answering 400/404)."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
        return None
    lines = head.decode("latin1").split("\r\n")
    try:
        method, path, _ = lines[0].split(" ", 2)
    except ValueError:
        return None
    if path_prefix and not path.split("?", 1)[0].startswith(path_prefix):
        writer.write(b"HTTP/1.1 404 Not Found\r\nContent-Length: 0\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()
        return None
    headers = {}
    for ln in lines[1:]:
        if ":" in ln:
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
    key = headers.get("sec-websocket-key")
    upgrade_ok = (
        method == "GET"
        and "websocket" in headers.get("upgrade", "").lower()
        and "upgrade" in headers.get("connection", "").lower()
        and key is not None
    )
    offered = [p.strip() for p in
               headers.get("sec-websocket-protocol", "").split(",") if p.strip()]
    chosen = next((p for p in offered if p in SUBPROTOCOLS), None)
    if not upgrade_ok or (offered and chosen is None):
        writer.write(b"HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()
        return None
    resp = (
        "HTTP/1.1 101 Switching Protocols\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Accept: {accept_key(key)}\r\n"
    )
    if chosen:
        resp += f"Sec-WebSocket-Protocol: {chosen}\r\n"
    writer.write((resp + "\r\n").encode())
    await writer.drain()
    return chosen or "mqtt"


def encode_frame(opcode: int, payload: bytes, mask: bool = False) -> bytes:
    b0 = 0x80 | opcode  # FIN always set (no outbound fragmentation)
    n = len(payload)
    if n < 126:
        hdr = bytes([b0, (0x80 if mask else 0) | n])
    elif n < 65536:
        hdr = bytes([b0, (0x80 if mask else 0) | 126]) + struct.pack(">H", n)
    else:
        hdr = bytes([b0, (0x80 if mask else 0) | 127]) + struct.pack(">Q", n)
    if mask:
        import os

        key = os.urandom(4)
        masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return hdr + key + masked
    return hdr + payload


class WsConnection:
    """Frame reader/writer over asyncio streams; handles control frames and
    reassembles fragmented messages."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, require_masked: bool = True):
        self.reader = reader
        self.writer = writer
        self.require_masked = require_masked
        self._frag: bytearray = bytearray()
        self._frag_opcode: Optional[int] = None
        self.closed = False

    async def _read_frame(self) -> Tuple[int, bool, bytes]:
        hdr = await self.reader.readexactly(2)
        fin = bool(hdr[0] & 0x80)
        if hdr[0] & 0x70:
            raise WsError("RSV bits set")
        opcode = hdr[0] & 0x0F
        masked = bool(hdr[1] & 0x80)
        n = hdr[1] & 0x7F
        if n == 126:
            n = struct.unpack(">H", await self.reader.readexactly(2))[0]
        elif n == 127:
            n = struct.unpack(">Q", await self.reader.readexactly(8))[0]
        if n > MAX_WS_FRAME:
            raise WsError("frame too large")
        if masked:
            key = await self.reader.readexactly(4)
            data = await self.reader.readexactly(n)
            payload = bytes(b ^ key[i % 4] for i, b in enumerate(data))
        else:
            if self.require_masked and opcode in (OP_BINARY, OP_TEXT, OP_CONT):
                raise WsError("client frames must be masked")
            payload = await self.reader.readexactly(n)
        return opcode, fin, payload

    async def read_message(self) -> bytes:
        """Next data message's payload; b'' on close/EOF. Pings are answered
        inline."""
        while True:
            if self.closed:
                return b""
            try:
                opcode, fin, payload = await self._read_frame()
            except (asyncio.IncompleteReadError, ConnectionError):
                self.closed = True
                return b""
            if opcode == OP_PING:
                self.send(OP_PONG, payload)
                continue
            if opcode == OP_PONG:
                continue
            if opcode == OP_CLOSE:
                self.send(OP_CLOSE, payload[:2])
                self.closed = True
                return b""
            if opcode in (OP_BINARY, OP_TEXT):
                if not fin:
                    self._frag_opcode = opcode
                    self._frag = bytearray(payload)
                    continue
                return payload
            if opcode == OP_CONT:
                if self._frag_opcode is None:
                    raise WsError("unexpected continuation")
                if len(self._frag) + len(payload) > MAX_WS_MESSAGE:
                    raise WsError("fragmented message too large")
                self._frag += payload
                if fin:
                    out = bytes(self._frag)
                    self._frag = bytearray()
                    self._frag_opcode = None
                    return out
                continue
            raise WsError(f"bad opcode {opcode}")

    def send(self, opcode: int, payload: bytes) -> None:
        if self.closed:
            return
        try:
            self.writer.write(encode_frame(opcode, payload))
        except Exception:
            self.closed = True


class WebSocketTransport(Transport):
    """Session-facing transport: MQTT bytes written by the session are
    coalesced per event-loop tick into one binary WS frame (the MSS-flush
    batching of the TCP path, vmq_ranch.erl:253-262)."""

    def __init__(self, ws: WsConnection):
        self.ws = ws
        self._buf = bytearray()
        self._flush_scheduled = False
        self.closed = False

    def write(self, data: bytes) -> None:
        if self.closed:
            return
        self._buf += data
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if self.closed or not self._buf:
            return
        self.ws.send(OP_BINARY, bytes(self._buf))
        self._buf.clear()

    def close(self) -> None:
        if self.closed:
            return
        self._flush()
        self.ws.send(OP_CLOSE, b"\x03\xe8")  # 1000 normal closure
        self.closed = True
        self.ws.closed = True
        try:
            self.ws.writer.close()
        except Exception:
            pass


class WebSocketServer:
    """``mqttws``/``mqttwss`` listener (vmq_ranch_config.erl:224-227)."""

    def __init__(self, broker, host: str = "127.0.0.1", port: int = 8080,
                 ssl_context=None, max_frame_size: int = 0,
                 use_identity_as_username: bool = False, mountpoint: str = "",
                 allowed_protocol_versions=None, max_connections: int = 0,
                 reuse_port: bool = False):
        self.broker = broker
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self.max_frame_size = max_frame_size
        self.use_identity_as_username = use_identity_as_username
        self.mountpoint = mountpoint
        self.allowed_protocol_versions = (
            tuple(allowed_protocol_versions)
            if allowed_protocol_versions else None)
        self.max_connections = int(max_connections or 0)
        self.connection_count = 0
        self.reuse_port = reuse_port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, ssl=self.ssl_context,
            reuse_port=self.reuse_port or None)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        self.broker._servers.append(self._server)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        if (self.max_connections
                and self.connection_count >= self.max_connections):
            # listener connection cap, same contract as MQTTServer
            self.broker.metrics.incr("socket_error")
            writer.close()
            return
        self.connection_count += 1
        try:
            await self._handle_inner(reader, writer)
        finally:
            self.connection_count -= 1

    async def _handle_inner(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        from .server import MAX_FRAME_SIZE, mqtt_connection

        peer = writer.get_extra_info("peername") or ("", 0)
        from .ssl_util import preauth_from_cert

        ok, preauth = preauth_from_cert(
            writer, self.use_identity_as_username, self.ssl_context)
        if not ok:
            writer.close()
            return
        try:
            subproto = await asyncio.wait_for(
                server_handshake(reader, writer), 10.0)
        except (asyncio.TimeoutError, ConnectionError):
            writer.close()
            return
        if subproto is None:
            writer.close()
            return
        ws = WsConnection(reader, writer)
        transport = WebSocketTransport(ws)
        try:
            # malformed ws frames (WsError) are handled inside the shared
            # connection loop alongside MQTT parse errors
            await mqtt_connection(
                self.broker, ws.read_message, transport, peer,
                # same fallback chain as MQTTServer: per-listener
                # override, else the broker-wide max_message_size
                # total-frame cap, else unlimited
                (self.max_frame_size
                 or self.broker.config.get("max_message_size", 0)
                 or MAX_FRAME_SIZE),
                preauth_user=preauth, mountpoint=self.mountpoint,
                allowed_protocol_versions=self.allowed_protocol_versions)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass
