"""Broker-internal message record.

Equivalent of the reference's ``#vmq_msg{}`` record (msg_ref, routing key,
payload, QoS, retain/dup flags, mountpoint, v5 properties; see
``vmq_cluster_com.erl:212-248`` for the field set) — the unit that flows
registry → queue → session, independent of the wire frames.
"""

from __future__ import annotations

import itertools
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

SubscriberId = Tuple[str, str]  # (mountpoint, client_id) — vmq_types.hrl

_ref_counter = itertools.count()
_node_seed = os.urandom(4).hex()


def new_msg_ref() -> bytes:
    """Unique message reference (the reference uses a 16-byte ref; ours is
    node-seed + counter, unique per broker process)."""
    return f"{_node_seed}:{next(_ref_counter)}".encode()


@dataclass
class Msg:
    topic: Tuple[str, ...]  # routing key as word tuple
    payload: bytes
    qos: int = 0
    retain: bool = False
    dup: bool = False
    mountpoint: str = ""
    msg_ref: bytes = field(default_factory=new_msg_ref)
    properties: Dict[str, Any] = field(default_factory=dict)
    # expiry: absolute monotonic deadline derived from the v5
    # message_expiry_interval property (vmq_mqtt5_fsm message expiry)
    expires_at: Optional[float] = None
    # $share sender info: set when delivered via a shared subscription
    sg_policy: Optional[str] = None

    def with_qos(self, qos: int) -> "Msg":
        if qos == self.qos:
            return self
        return Msg(
            topic=self.topic,
            payload=self.payload,
            qos=qos,
            retain=self.retain,
            dup=self.dup,
            mountpoint=self.mountpoint,
            msg_ref=self.msg_ref,
            properties=self.properties,
            expires_at=self.expires_at,
        )


def wire_v4_qos(msg: "Msg", pid: int) -> bytes:
    """The v4 QoS>0 PUBLISH wire frame for ``msg`` with ``pid`` patched
    in: across recipients the frame differs ONLY in the 2-byte packet id
    (v4 has no per-session properties; dup retries bypass this), so
    serialise once per Msg and copy+patch per recipient instead of
    re-running the codec — the QoS1/2 analog of :func:`wire_v4_qos0`."""
    tpl = getattr(msg, "_wire_v4_tpl", None)
    if tpl is None:
        from ..protocol import codec_v4
        from ..protocol import topic as T
        from ..protocol.types import Publish

        topic_str = T.unword(list(msg.topic))
        frame = Publish(topic=topic_str, payload=msg.payload, qos=msg.qos,
                        retain=msg.retain, dup=False, packet_id=pid,
                        properties={})
        data = codec_v4.serialise(frame)
        # build the template only from the SECOND recipient on: a
        # fanout-1 message would pay the bytearray+patch copies for
        # nothing and retain a second full frame copy while it sits in
        # waiting_acks/offline queues
        if getattr(msg, "_wire_v4_seen", False):
            # the 2-byte packet id immediately precedes the payload in a
            # v4 PUBLISH — derive the offset from the serialised bytes
            # so it can never disagree with the codec
            msg._wire_v4_tpl = (bytearray(data),
                                len(data) - len(msg.payload) - 2)
        else:
            msg._wire_v4_seen = True
        return data
    buf, off = tpl
    buf[off] = (pid >> 8) & 0xFF
    buf[off + 1] = pid & 0xFF
    return bytes(buf)


def wire_v4_iov_qos0(msg: "Msg") -> tuple:
    """Writev-ready v4 QoS0 PUBLISH: ``(header, payload)`` with the
    header cached on the Msg — the payload bytes object is shared
    across every recipient's transport iovec and never copied into a
    per-frame buffer (protocol/fastpath.py encode seam). Falls back to
    the single cached frame when the header encoder refuses (so the
    canonical codec error surfaces)."""
    iov = getattr(msg, "_wire_v4_q0_iov", None)
    if iov is None:
        from ..protocol import fastpath
        from ..protocol import topic as T

        try:
            hdr = fastpath.publish_header(
                T.unword(list(msg.topic)), 0, bool(msg.retain), False,
                None, len(msg.payload))
        except ValueError:
            return (wire_v4_qos0(msg),)
        iov = msg._wire_v4_q0_iov = (hdr, msg.payload)
    return iov


def wire_v4_iov_qos(msg: "Msg", pid: int) -> tuple:
    """Writev-ready v4 QoS>0 PUBLISH: per-recipient frames differ only
    in the 2-byte packet id, which sits at the END of the header — so
    the cached header template is patched per recipient and the shared
    payload rides the iovec uncopied (the iov analog of
    :func:`wire_v4_qos`)."""
    tpl = getattr(msg, "_wire_v4_hdr_tpl", None)
    if tpl is None:
        from ..protocol import fastpath
        from ..protocol import topic as T

        try:
            hdr = fastpath.publish_header(
                T.unword(list(msg.topic)), msg.qos, bool(msg.retain),
                False, pid, len(msg.payload))
        except ValueError:
            return (wire_v4_qos(msg, pid),)
        msg._wire_v4_hdr_tpl = bytearray(hdr)
        return (hdr, msg.payload)
    tpl[-2] = (pid >> 8) & 0xFF
    tpl[-1] = pid & 0xFF
    return (bytes(tpl), msg.payload)


def wire_batch_iovs(arena: bytes, offsets, payload: bytes) -> list:
    """Per-recipient writev iovecs over a batched header arena
    (``fastpath.publish_headers_batch``): recipient *i*'s header is the
    zero-copy memoryview slice ``arena[offsets[i]:offsets[i+1]]``, and
    the shared payload bytes object rides every iovec uncopied — the
    whole fanout touches ONE arena allocation plus the payload the
    parser already sliced."""
    mv = memoryview(arena)
    return [(mv[offsets[i]:offsets[i + 1]], payload)
            for i in range(len(offsets) - 1)]


def wire_v4_qos0(msg: "Msg") -> bytes:
    """The v4 QoS0 PUBLISH wire frame for ``msg``, cached on the Msg:
    identical for every v4 QoS0 recipient (no packet id, no props, no
    per-session state), so fanout serialises once. Shared by the
    session send path and the registry's batched fanout — ONE
    serialisation site, one cache slot."""
    data = getattr(msg, "_wire_v4_q0", None)
    if data is None:
        from ..protocol import codec_v4
        from ..protocol import topic as T
        from ..protocol.types import Publish

        frame = Publish(topic=T.unword(list(msg.topic)),
                        payload=msg.payload, qos=0, retain=msg.retain,
                        dup=False, packet_id=None, properties={})
        data = codec_v4.serialise(frame)
        msg._wire_v4_q0 = data
    return data
