"""Broker metrics: counter/gauge registry with Prometheus text exposition.

Mirrors the reference metric system (``vmq_metrics.erl``): named counters
incremented on every protocol event, gauge providers sampled at scrape time,
per-metric type/description metadata (``vmq_metrics.erl:627-1080``), and a
``check_rate`` helper backing ``max_message_rate`` throttling
(``vmq_metrics.erl:286``). The reference keeps counters in a wait-free C NIF
(mzmetrics); here registered counters live in the C++ counter block
(``native/counters.cc``) behind per-thread Python increment buffers — the
buffer bounds ctypes-call frequency (flush every ``_FLUSH_OPS``), and reads
sum the native block plus every thread's live buffer, so totals are fresh
and nothing strands on an idle pool thread.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..observability import histogram as _hist

COUNTERS: List[Tuple[str, str]] = [
    # socket / session counters (vmq_metrics.hrl names)
    ("socket_open", "The number of AF_INET opens."),
    ("socket_close", "The number of AF_INET closes."),
    ("socket_error", "The number of socket errors."),
    ("bytes_received", "The total number of bytes received."),
    ("bytes_sent", "The total number of bytes sent."),
    ("mqtt_connect_received", "The number of CONNECT packets received."),
    ("mqtt_connack_sent", "The number of CONNACK packets sent."),
    # v4 per-return-code CONNACK counters (vmq_metrics.erl:655-660)
    ("mqtt_connack_accepted_sent",
     "The number of times a connection has been accepted."),
    ("mqtt_connack_unacceptable_protocol_sent",
     "The number of times the broker could not support the requested "
     "protocol."),
    ("mqtt_connack_identifier_rejected_sent",
     "The number of times a client was rejected due to an unacceptable "
     "identifier."),
    ("mqtt_connack_server_unavailable_sent",
     "The number of times a client was rejected due to the broker being "
     "unavailable."),
    ("mqtt_connack_bad_credentials_sent",
     "The number of times a client sent bad credentials."),
    ("mqtt_connack_not_authorized_sent",
     "The number of times a client was rejected due to insufficient "
     "authorization."),
    ("mqtt_publish_received", "The number of PUBLISH packets received."),
    ("mqtt_publish_sent", "The number of PUBLISH packets sent."),
    ("mqtt_puback_received", "The number of PUBACK packets received."),
    ("mqtt_puback_sent", "The number of PUBACK packets sent."),
    ("mqtt_pubrec_received", "The number of PUBREC packets received."),
    ("mqtt_pubrec_sent", "The number of PUBREC packets sent."),
    ("mqtt_pubrel_received", "The number of PUBREL packets received."),
    ("mqtt_pubrel_sent", "The number of PUBREL packets sent."),
    ("mqtt_pubcomp_received", "The number of PUBCOMP packets received."),
    ("mqtt_pubcomp_sent", "The number of PUBCOMP packets sent."),
    ("mqtt_subscribe_received", "The number of SUBSCRIBE packets received."),
    ("mqtt_suback_sent", "The number of SUBACK packets sent."),
    ("mqtt_unsubscribe_received", "The number of UNSUBSCRIBE packets received."),
    ("mqtt_unsuback_sent", "The number of UNSUBACK packets sent."),
    ("mqtt_pingreq_received", "The number of PINGREQ packets received."),
    ("mqtt_pingresp_sent", "The number of PINGRESP packets sent."),
    ("mqtt_disconnect_received", "The number of DISCONNECT packets received."),
    ("mqtt_disconnect_sent", "The number of DISCONNECT packets sent (MQTT5)."),
    ("mqtt_auth_received", "The number of AUTH packets received (MQTT5)."),
    ("mqtt_auth_sent", "The number of AUTH packets sent (MQTT5)."),
    ("mqtt_connect_error", "Failed CONNECT attempts."),
    ("mqtt_publish_error", "Failed PUBLISH attempts."),
    ("mqtt_publish_auth_error", "Unauthorized PUBLISH attempts."),
    ("mqtt_subscribe_error", "Failed SUBSCRIBE attempts."),
    ("mqtt_subscribe_auth_error", "Unauthorized SUBSCRIBE attempts."),
    ("mqtt_unsubscribe_error", "Failed UNSUBSCRIBE attempts."),
    ("mqtt_invalid_msg_size_error", "Oversized messages dropped."),
    ("mqtt_puback_invalid_error",
     "The number of unexpected PUBACK messages received."),
    ("mqtt_pubrec_invalid_error",
     "The number of unexpected PUBREC messages received."),
    ("mqtt_pubcomp_invalid_error",
     "The number of unexpected PUBCOMP messages received."),
    ("mqtt_publish_throttled",
     "PUBLISHes paused by max_message_rate / overload shedding."),
    ("queue_setup", "The number of queue processes created."),
    ("queue_teardown", "The number of queue processes terminated."),
    ("queue_message_in", "Messages enqueued."),
    ("queue_message_out", "Messages delivered from queues."),
    ("queue_message_drop", "Messages dropped (queue full / offline QoS0)."),
    ("queue_message_expired", "Expired messages dropped from queues."),
    ("queue_message_unhandled", "Messages not handled (offline session)."),
    ("queue_initialized_from_storage", "Queues re-initialized from offline storage."),
    ("client_expired", "Persistent sessions expired."),
    ("cluster_bytes_received", "Bytes received over cluster channels."),
    ("cluster_bytes_sent", "Bytes sent over cluster channels."),
    ("cluster_bytes_dropped", "Bytes dropped on cluster channels."),
    ("cluster_frames_dropped", "Frames dropped on cluster channels."),
    ("cluster_frames_shed_qos0",
     "Buffered QoS0 cluster frames evicted to make room for QoS>=1 "
     "traffic (also counted in cluster_frames_dropped)."),
    ("cluster_spool_journaled",
     "QoS>=1 cluster frames journaled to the delivery spool."),
    ("cluster_spool_replayed",
     "Spooled cluster frames replayed after reconnect/ack timeout."),
    ("cluster_spool_deduped",
     "Replayed cluster frames suppressed by the receiver dedup window."),
    ("cluster_spool_acks_sent",
     "Cumulative spool acks sent back to origin nodes."),
    ("cluster_spool_overflow",
     "Frames refused by the spool byte cap (sent best-effort instead)."),
    ("cluster_spool_errors",
     "Spool journal write failures (frame sent best-effort instead)."),
    ("cluster_publish_drop",
     "Remote publish forwards dropped (buffer full / spool refused "
     "while the stream was paused)."),
    ("cluster_stall_reconnects",
     "Cluster channels cycled by the ack-progress stall detector "
     "(unacked spooled bytes with no cumulative-ack progress for "
     "cluster_stall_timeout_s; the spool replays on reconnect)."),
    ("netsplit_detected", "Netsplits detected."),
    ("netsplit_resolved", "Netsplits resolved."),
    ("router_matches_local", "Subscriptions matched for local delivery."),
    ("router_matches_remote", "Subscriptions matched for remote delivery."),
    ("tpu_match_batches", "Batched TPU match kernel invocations."),
    ("tpu_match_publishes", "Publishes matched on the TPU path."),
    ("msg_store_ops_write", "Message store writes."),
    ("msg_store_ops_delete", "Message store deletes."),
    ("msg_store_write_errors",
     "Message store writes that failed (message kept in memory only)."),
    ("msg_store_read_errors",
     "Message store recovery reads that failed (batched resume AND "
     "per-session fallback; the session resumes with what storage "
     "could serve)."),
    ("msg_store_recover_skipped",
     "Corrupt message-store records skipped during recovery."),
    ("msg_store_fsync_coalesced",
     "Per-record fsyncs coalesced into one group commit at the "
     "flush-tick boundary (msg_store_fsync on)."),
    ("store_compactions",
     "Budgeted store maintenance passes that reclaimed garbage "
     "(segment evacuations / native compactions)."),
    ("store_compacted_bytes",
     "Garbage bytes reclaimed by budgeted store compaction."),
    ("store_compact_paused",
     "Maintenance ticks skipped while the store breaker was open "
     "(append-only degraded mode)."),
    ("store_compact_errors",
     "Store compaction steps that failed or were abandoned at the "
     "watchdog deadline (fed to the store breaker)."),
    ("store_recover_fallbacks",
     "Engine opens that discarded an unusable checkpoint and fell "
     "back to the full segment scan."),
    ("store_bucket_probe_hits",
     "Bucketed-store reads probing a bucket the sid→bucket membership "
     "index named that held messages."),
    ("store_bucket_probe_misses",
     "Bucketed-store reads probing a bucket whose membership turned "
     "out stale (cleaned from the index)."),
    ("msg_store_expired_swept",
     "Expired parked offline message copies deleted by the budgeted "
     "TTL sweep riding the store maintenance tick."),
    ("retain_messages_stored", "Retained messages persisted."),
    # robustness (supervision tree analog + fault harness)
    ("supervisor_restarts", "Supervised tasks restarted after a crash."),
    ("supervisor_escalations",
     "Supervised tasks abandoned after exceeding the restart budget "
     "(listeners torn down)."),
    ("sysmon_long_schedule",
     "Event-loop lag events over the sysmon threshold."),
    ("sysmon_large_heap",
     "Forced GCs after crossing the memory high watermark."),
    # adaptive overload governor (robustness/overload.py): one counter
    # per shed stage so operators see WHICH response is carrying load
    ("overload_publish_throttled",
     "PUBLISHes delayed by the governor's graded read throttle (L1+)."),
    ("overload_rate_limited",
     "PUBLISHes delayed by the per-client token bucket at overload "
     "level 2+."),
    ("overload_qos0_shed",
     "QoS0 publishes shed at the fanout admission gate at overload "
     "level 2+."),
    ("overload_replay_deferred",
     "Retained-replay flushes deferred at overload level 2+."),
    ("overload_connects_refused",
     "CONNECTs refused at the listener while at overload level 3."),
    ("overload_talker_disconnects",
     "Heaviest-talker sessions disconnected (Server busy) entering "
     "overload level 3."),
    # observability (admin/tracer.py): frames the per-client tracer's
    # rate limiter suppressed — a traced storm is visibly truncated
    ("trace_rate_limited",
     "Traced frames suppressed by the tracer rate limiter "
     "(max_rate); the trace output carries a '... N frames "
     "suppressed' marker when the window reopens."),
    # payload filtering & windowed aggregation (vernemq_tpu/filters/):
    # the predicate_*/aggregate_* families — one counter per path so
    # operators see device-vs-host split, escapes, and the zero-cost
    # skip gate working
    ("predicate_dispatches",
     "Predicate-phase device dispatches (one per fold batch carrying "
     "compiled predicates)."),
    ("predicate_pairs_evaluated",
     "(matched-subscriber x predicate) pairs evaluated on the device "
     "path."),
    ("predicate_host_evals",
     "Predicate pairs evaluated by the exact host evaluator "
     "(breaker-open/degraded, sub-threshold batches, and "
     "unrepresentable escapes)."),
    ("predicate_escapes",
     "Predicate pairs host-resolved because the predicate cannot be "
     "represented as one device row (conjunctions, enum alphabets "
     "past 64 codes)."),
    ("predicate_rows_filtered",
     "Matched fanout rows removed by payload predicates before any "
     "per-subscriber queue work."),
    ("predicate_phase_skips",
     "Fold batches that skipped the predicate phase entirely (no "
     "compiled predicates for the batch — the zero-cost gate)."),
    ("predicate_device_failures",
     "Predicate-phase device failures (dispatch errors and watchdog "
     "stalls) fed to the predicate breaker."),
    ("predicate_degraded_sheds",
     "Predicate dispatches refused while the predicate breaker was "
     "open (host evaluator served)."),
    ("predicate_errors",
     "Predicate-phase internal errors that delivered a batch "
     "unfiltered (fail-open, logged loudly)."),
    ("aggregate_values_folded",
     "Payload values folded into aggregation windows (device and "
     "host paths)."),
    ("aggregate_windows_closed",
     "Aggregation windows closed (count target reached or time "
     "window elapsed)."),
    ("aggregate_publishes",
     "Synthesized aggregate PUBLISHes emitted by closed windows."),
    ("aggregate_publishes_delivered",
     "Synthesized aggregate PUBLISHes enqueued to a live subscriber "
     "queue."),
    ("aggregate_window_overflow",
     "Aggregation subscriptions served raw per-message delivery "
     "because the window table hit aggregate_max_windows."),
    # QoS2 exactly-once dedup bound (broker/session.py awaiting_rel):
    # the per-session pid-window is capped at qos2_dedup_max — a
    # slow-release storm evicts oldest-first instead of growing the
    # dict unboundedly (groundwork for the native bitmap in ROADMAP)
    ("qos2_dedup_evictions",
     "QoS2 awaiting-release pids evicted oldest-first because a "
     "session's dedup window hit qos2_dedup_max; an evicted pid's DUP "
     "retransmission re-routes (at-least-once degradation, counted)."),
    # live handoff (cluster/handoff.py): the freeze→drain→fence→adopt
    # state machine moving mesh slices and sessions between nodes
    ("handoff_started",
     "Live handoffs admitted (freeze phase entered) for mesh slices "
     "and session migrations."),
    ("handoff_completed",
     "Live handoffs that reached adopt: the successor owns the unit "
     "and replayed exactly-once; zero QoS>=1 loss."),
    ("handoff_rollbacks",
     "Live handoffs rolled back at a phase failure or watchdog "
     "deadline — the unit un-froze and the old owner kept serving."),
    ("handoff_fenced_writes",
     "Late writes caught by a handoff fence: stale lower-epoch mesh "
     "slice claims rejected, plus post-fence queue arrivals swept to "
     "the new owner instead of landing locally."),
    ("handoff_batch_fence_writes",
     "Shared fence writes issued by batched session handoffs — one "
     "per (batch, target), amortizing the per-session record rewrite "
     "a bulk drain used to pay."),
    # membership health plane (cluster/health.py): accrual failure
    # detector verdicts + the automatic rebalance planner's actions
    # and refusals
    ("member_suspect_transitions",
     "Peers the accrual failure detector marked suspect (phi crossed "
     "health_phi_suspect, or the outbound channel tore)."),
    ("member_down_transitions",
     "Peers the accrual failure detector declared down (phi crossed "
     "health_phi_down); each verdict notes the rebalance planner."),
    ("member_alive_transitions",
     "Peers re-admitted to alive after sustaining low suspicion for "
     "the full hysteresis hold (health_exit_ratio/health_hold_s)."),
    ("handoff_auto_rebalances",
     "Automatic slice-rebalance cycles the planner drove to the "
     "handoff engine (join/alive membership changes)."),
    ("handoff_auto_evacuations",
     "Subscriber records auto-evacuated off a down member onto the "
     "least-loaded survivors by the rebalance planner."),
    ("handoff_auto_skipped_no_quorum",
     "Planner cycles refused because this node could not see a "
     "majority of the joined membership (netsplit minority sits "
     "still)."),
    ("handoff_auto_skipped_breaker",
     "Planner cycles refused because the handoff circuit breaker was "
     "open (repeated rollbacks; a probe must recover it first)."),
    ("handoff_auto_suppressed",
     "Planner cycles suppressed by the per-peer cooldown — the "
     "anti-ping-pong rail for flapping members."),
    ("handoff_auto_limited",
     "Handoffs refused by the global concurrent-handoff limiter "
     "(rebalance_max_concurrent already in flight)."),
]


class Metrics:
    #: buffered increments per thread before a native flush: one ctypes
    #: fetch_add costs ~10x a dict add, and the publish path fires several
    #: counters per delivery (profiled at 13% of broker wall time at 10k
    #: pubs/s) — batching keeps the native block the source of truth with
    #: a bounded lag of < _FLUSH_OPS increments per writer thread
    _FLUSH_OPS = 64

    def __init__(self, native: bool = True) -> None:
        import threading

        self._counters: Dict[str, int] = {name: 0 for name, _ in COUNTERS}
        self._descriptions: Dict[str, str] = dict(COUNTERS)
        # labeled series, keyed (family, (("label","value"),...)) — the
        # reference's per-reason-code counter families
        # (vmq_metrics.erl:787-915: mqtt_connack_sent / mqtt_disconnect_*
        # by reason_code). Event-rate mutation only (CONNACK/DISCONNECT),
        # so a plain dict is fine.
        self._labeled: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], int] = {}
        self._gauge_providers: List[Callable[[], Dict[str, float]]] = []
        self._gauge_desc: Dict[str, str] = {}
        self._rate_state: Dict[object, Tuple[float, int]] = {}
        # worker-mode scrape aggregation hook: a callable returning
        # peer workers' histogram blocks (name -> (counts, sum, count))
        # merged into prometheus_text/histogram_snapshot
        self.histogram_extra: Optional[
            Callable[[], Dict[str, Tuple[List[int], float, int]]]] = None
        # wait-free native counter block for the registered counters (the
        # mzmetrics seat); unknown/dynamic names stay in the dict
        self._native = None
        self._native_idx: Dict[str, int] = {}
        self._tl = threading.local()
        # every thread's live buffer, registered at creation: reads SUM
        # these (dict.get is GIL-atomic) on top of the native block, so
        # another thread's buffered increments are visible immediately —
        # buffering bounds ctypes-call frequency, not read freshness,
        # and nothing is lost if a pool thread goes idle. Entries carry a
        # weakref to their owner thread so reads can sweep buffers of
        # dead threads (fold residuals into the native block once) —
        # otherwise executor churn grows the list without bound.
        self._bufs: List[Tuple[object, Dict[int, int]]] = []
        self._bufs_lock = threading.Lock()
        if native:
            try:
                from ..native import counters as nc

                if nc.available():
                    self._native = nc.CounterBlock([n for n, _ in COUNTERS])
                    self._native_idx = {
                        n: i for i, n in enumerate(n for n, _ in COUNTERS)}
            except Exception:  # toolchain missing etc. — pure-Python path
                self._native = None

    def incr(self, name: str, n: int = 1) -> None:
        idx = self._native_idx.get(name)
        if idx is None:
            self._counters[name] = self._counters.get(name, 0) + n
            return
        tl = self._tl
        buf = getattr(tl, "buf", None)
        if buf is None:
            import threading
            import weakref

            buf = tl.buf = {}
            tl.ops = 0
            with self._bufs_lock:
                self._bufs.append(
                    (weakref.ref(threading.current_thread()), buf))
        buf[idx] = buf.get(idx, 0) + n
        tl.ops += 1
        if tl.ops >= self._FLUSH_OPS:
            self._flush_own()

    def observe(self, name: str, ms: float) -> None:
        """Record one latency observation into a registered stage
        histogram (observability/histogram.py). The registry is
        process-global; this seam exists so layers holding a Metrics
        handle (cluster spool, queues) need no second import."""
        _hist.observe(name, ms)  # lint: observe-passthrough

    def histogram_snapshot(self) -> Dict[str, Tuple[List[int], float, int]]:
        """Merged histogram families: this process's registry plus
        whatever ``histogram_extra`` contributes (the broker wires the
        other workers' shm stat-slot blocks in worker mode) — name ->
        (bucket counts incl. overflow, sum_ms, count)."""
        snap = _hist.snapshot_all()
        extra = self.histogram_extra
        if extra is not None:
            try:
                for name, peer in extra().items():
                    cur = snap.get(name)
                    snap[name] = (_hist.merge(cur, peer)
                                  if cur is not None else peer)
            except Exception:
                pass  # a torn slot read must never break the scrape
        return snap

    def incr_labeled(self, name: str, n: int = 1, **labels: str) -> None:
        """Count into a labeled series (per-reason-code families). The
        flat family counter is incremented separately by the caller where
        the reference keeps both (e.g. mqtt_connack_sent)."""
        key = (name, tuple(sorted(labels.items())))
        self._labeled[key] = self._labeled.get(key, 0) + n

    def _flush_own(self) -> None:
        """Drain this thread's buffered increments into the native block
        (one ctypes call per touched counter instead of per increment)."""
        tl = self._tl
        buf = getattr(tl, "buf", None)
        if buf:
            native_incr = self._native.incr
            for idx, n in list(buf.items()):
                native_incr(idx, n)
            buf.clear()
        tl.ops = 0

    def _swept_pending(
        self,
    ) -> Tuple[List[Dict[int, int]], Dict[int, int]]:
        """Snapshot live threads' buffers, sweeping dead-thread entries
        (bounds _bufs under executor/thread churn). A dead thread can no
        longer mutate its buffer, so its residual counts are folded into
        the native block exactly once AND returned — callers took their
        native reading before this call, so they must add the residuals
        themselves to see them this read; later reads get them from the
        native block. Per-key dict.get on live buffers is GIL-atomic, so
        other threads' buffers are read without locks; a racing flush
        could briefly double- or under-count by one buffer's worth
        (< _FLUSH_OPS) — monotonic-exact totals land at the next read."""
        live: List[Dict[int, int]] = []
        residual: Dict[int, int] = {}
        with self._bufs_lock:
            kept = []
            for wr, buf in self._bufs:
                t = wr()
                if t is not None and t.is_alive():
                    kept.append((wr, buf))
                    live.append(buf)
                else:
                    for idx, n in list(buf.items()):
                        residual[idx] = residual.get(idx, 0) + n
                    buf.clear()
            self._bufs = kept
            # fold under the lock: once the entries are gone from
            # _bufs, a concurrent reader can only see the residuals via
            # the native block — folding outside the lock would open a
            # window where a scrape reads a non-monotonic dip
            if residual:
                native_incr = self._native.incr
                for idx, n in residual.items():
                    native_incr(idx, n)
        return live, residual

    def _pending(self, idx: int) -> int:
        """Buffered (not yet natively flushed) increments for one counter
        that a native reading taken BEFORE this call does not include:
        live threads' buffers plus just-folded dead-thread residuals."""
        live, residual = self._swept_pending()
        return sum(b.get(idx, 0) for b in live) + residual.get(idx, 0)

    def value(self, name: str) -> int:
        idx = self._native_idx.get(name)
        if idx is not None:
            self._flush_own()
            return self._native.read(idx) + self._pending(idx)
        return self._counters.get(name, 0)

    def describe(self, name: str) -> str:
        return self._descriptions.get(name) or self._gauge_desc.get(name, "")

    def register_gauges(
        self, provider: Callable[[], Dict[str, float]], descriptions: Dict[str, str]
    ) -> None:
        """Pluggable gauge providers, like the reference's pluggable
        ``metrics/0`` modules (vmq_metrics.erl metrics plugins)."""
        self._gauge_providers.append(provider)
        self._gauge_desc.update(descriptions)

    def check_rate(self, key: object, max_per_sec: int) -> bool:
        """Sliding-window rate check for max_message_rate
        (vmq_metrics.erl:286). True = within budget."""
        if max_per_sec <= 0:
            return True
        now = time.monotonic()
        start, count = self._rate_state.get(key, (now, 0))
        if now - start >= 1.0:
            start, count = now, 0
        count += 1
        self._rate_state[key] = (start, count)
        return count <= max_per_sec

    def rate_wait_s(self, key: object) -> float:
        """Seconds until ``key``'s current rate window rolls over — the
        precise pause for a throttled publisher (the old path slept a
        blind 1.0s however much of the window had already elapsed)."""
        start, _ = self._rate_state.get(key, (0.0, 0))
        # +2ms past the rollover so the post-wake re-check lands firmly
        # inside the fresh window despite timer/float granularity
        return max(0.005, start + 1.0 - time.monotonic() + 0.002)

    def drop_rate_state(self, key: object) -> None:
        self._rate_state.pop(key, None)

    def _native_totals(self) -> Dict[str, int]:
        """Native block snapshot plus every thread's buffered counts —
        one sweep for the whole scrape (snapshot is taken first, so
        just-folded dead-thread residuals are added explicitly)."""
        self._flush_own()
        snap = self._native.snapshot()
        live, residual = self._swept_pending()
        for name, idx in self._native_idx.items():
            snap[name] += (sum(b.get(idx, 0) for b in live)
                           + residual.get(idx, 0))
        return snap

    def all_metrics(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self._counters)
        if self._native is not None:
            out.update(self._native_totals())
        for (name, labels), val in self._labeled.items():
            lbl = ",".join(f'{k}="{v}"' for k, v in labels)
            out[f"{name}{{{lbl}}}"] = val
        for provider in self._gauge_providers:
            out.update(provider())
        # histogram families surface in the $SYS feed as count/sum
        # scalars (rate + mean are derivable); the bucket vectors are
        # Prometheus-exposition-only and the quantiles are the graphite
        # reporter's <name>.p50/p99/p999 — one home per representation
        for name, snap in self.histogram_snapshot().items():
            _counts, s, n = snap
            out[f"{name}_count"] = float(n)
            out[f"{name}_sum"] = round(s, 3)
        return out

    def prometheus_text(self, node: str = "local") -> str:
        """Prometheus exposition format (vmq_metrics_http.erl:42-84).
        Labeled series join their flat family under ONE HELP/TYPE header
        (exposition-format requirement: one metadata block per family,
        samples contiguous)."""
        lines: List[str] = []
        gauges: Dict[str, float] = {}
        for provider in self._gauge_providers:
            gauges.update(provider())
        counters = dict(self._counters)
        if self._native is not None:
            counters.update(self._native_totals())
        labeled: Dict[str, List[Tuple[str, int]]] = {}
        for (name, labels), val in sorted(self._labeled.items()):
            lbl = "".join(f',{k}="{v}"' for k, v in labels)
            labeled.setdefault(name, []).append((lbl, val))
        for name in sorted(set(counters) | set(labeled)):
            desc = self._descriptions.get(name, name)
            lines.append(f"# HELP {name} {desc}")
            lines.append(f"# TYPE {name} counter")
            if name in counters:
                lines.append(f'{name}{{node="{node}"}} {counters[name]}')
            for lbl, val in labeled.get(name, ()):
                lines.append(f'{name}{{node="{node}"{lbl}}} {val}')
        for name, val in sorted(gauges.items()):
            desc = self._gauge_desc.get(name, name)
            lines.append(f"# HELP {name} {desc}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f'{name}{{node="{node}"}} {val}')
        # stage latency histograms: proper _bucket/_sum/_count families
        # with cumulative le buckets (observability/histogram.py); in
        # worker mode the snapshot already merged every live worker's
        # shm slot, so any worker's scrape is the node-level view
        helps = dict(_hist.STAGE_FAMILIES)
        for name, snap in sorted(self.histogram_snapshot().items()):
            counts, s, n = snap
            lines.append(f"# HELP {name} {helps.get(name, name)}")
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for i, bound in enumerate(_hist.BUCKET_BOUNDS_MS):
                cum += counts[i]
                lines.append(f'{name}_bucket{{node="{node}",'
                             f'le="{bound:g}"}} {cum}')
            cum += counts[_hist.N_BUCKETS]
            lines.append(
                f'{name}_bucket{{node="{node}",le="+Inf"}} {cum}')
            lines.append(f'{name}_sum{{node="{node}"}} {round(s, 6)}')
            lines.append(f'{name}_count{{node="{node}"}} {n}')
        return "\n".join(lines) + "\n"
