"""Plugin hook engine: registry + the three call conventions.

Mirrors the reference hook dispatcher semantics (``vmq_plugin.erl`` /
``vmq_plugin_mgr.erl``): named hook points, multiple handlers in priority
order, and the call conventions ``only`` (first registered handler),
``all`` (every handler, results ignored), ``all_till_ok`` (auth chains —
first ``ok``/``(ok, changes)`` wins, ``"next"`` falls through, an error
stops the chain; ``vmq_plugin.erl:16-20``). The reference recompiles the
dispatch module at runtime via smerl (``vmq_plugin_mgr.erl:729-747``);
table-driven dispatch is the idiomatic Python equivalent — same observable
behavior, no codegen.

Hook names follow the reference behaviours (vernemq_dev): auth_on_register,
auth_on_publish, auth_on_subscribe, on_register, on_publish, on_subscribe,
on_unsubscribe, on_deliver, on_offline_message, on_client_wakeup,
on_client_offline, on_client_gone, on_message_drop, plus the `_m5` variants
and on_auth_m5. Handlers may be sync or async; the broker awaits async ones.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Tuple

# sentinel return values
NEXT = "next"  # fall through to the next handler in an all_till_ok chain
OK = "ok"


class HookError(Exception):
    def __init__(self, reason: Any):
        super().__init__(str(reason))
        self.reason = reason


class HookRegistry:
    def __init__(self) -> None:
        self._hooks: Dict[str, List[Tuple[int, Callable]]] = {}

    def register(self, name: str, fn: Callable, priority: int = 0) -> None:
        """Register a handler; lower priority runs first (the reference
        orders by plugin registration order)."""
        self._hooks.setdefault(name, []).append((priority, fn))
        self._hooks[name].sort(key=lambda t: t[0])

    def unregister(self, name: str, fn: Callable) -> bool:
        lst = self._hooks.get(name, [])
        for i, (_, f) in enumerate(lst):
            if f is fn:
                del lst[i]
                return True
        return False

    def handlers(self, name: str) -> List[Callable]:
        return [f for _, f in self._hooks.get(name, [])]

    def has(self, name: str) -> bool:
        return bool(self._hooks.get(name))

    async def _call(self, fn: Callable, args: tuple) -> Any:
        res = fn(*args)
        if inspect.isawaitable(res):
            res = await res
        return res

    async def only(self, name: str, *args: Any) -> Any:
        """Call the first registered handler (vmq_plugin:only/2).
        Raises HookError('no_matching_hook_found') when none registered."""
        lst = self._hooks.get(name)
        if not lst:
            raise HookError("no_matching_hook_found")
        return await self._call(lst[0][1], args)

    async def all(self, name: str, *args: Any) -> List[Any]:
        """Call every handler, collect results (vmq_plugin:all/2)."""
        return [await self._call(f, args) for _, f in self._hooks.get(name, [])]

    async def all_till_ok(self, name: str, *args: Any) -> Any:
        """Auth-chain convention (vmq_plugin:all_till_ok/2): handlers return
        ``"ok"`` (accept), ``("ok", modifiers_dict)`` (accept with changes),
        ``"next"`` (ask the next handler), or ``("error", reason)`` /
        raise to reject. No handler registered, or every handler says
        ``next`` → HookError('no_matching_hook_found') — the caller decides
        the default (default-deny for auth, vmq_auth.erl:3-8)."""
        for _, f in self._hooks.get(name, []):
            res = await self._call(f, args)
            if res == NEXT:
                continue
            if res == OK or res is True:
                return OK
            if isinstance(res, tuple) and len(res) == 2 and res[0] == OK:
                return res
            if isinstance(res, tuple) and len(res) == 2 and res[0] == "error":
                raise HookError(res[1])
            raise HookError(res)
        raise HookError("no_matching_hook_found")
