"""Retained-message store.

Mirrors ``vmq_retain_srv.erl``: a write-through cache of retained messages
keyed by (mountpoint, topic), with wildcard lookup on subscribe. The
reference does a full-table ETS scan for wildcard filters
(``vmq_retain_srv.erl:75-97`` — its own "TODO optimize"); we instead keep
retained topics in a trie and walk it with the filter (exact descent on
words, children fan-out on ``+``, subtree collect on ``#``) — O(matches)
instead of O(table). Persistence to the metadata store is write-behind via
``dirty`` tracking (vmq_retain_srv.erl:186-191,223-237).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from ..protocol.topic import HASH, PLUS


class _RNode:
    __slots__ = ("children", "value")

    def __init__(self) -> None:
        self.children: Dict[str, _RNode] = {}
        self.value: Any = None  # retained payload record, None = no retained msg here


class RetainStore:
    def __init__(self, on_dirty: Optional[Callable[[str, Tuple[str, ...], Any], None]] = None):
        self._roots: Dict[str, _RNode] = {}  # per-mountpoint retain trees
        self._count = 0
        self._bytes = 0  # approximate payload+topic bytes (retain_memory)
        # write-behind hook: called with (mountpoint, topic, value|None) on
        # every mutation so the metadata store persists + replicates deltas
        # (vmq_retain_srv dirty table + metadata events,
        # vmq_retain_srv.erl:180-191)
        self._on_dirty = on_dirty

    def __len__(self) -> int:
        return self._count

    def memory(self) -> int:
        """Approximate bytes held by retained messages (the reference's
        ``retain_memory`` gauge — there ETS words, here payload + topic
        bytes + a fixed per-entry overhead)."""
        return self._bytes

    @staticmethod
    def _vsize(topic: Sequence[str], value: Any) -> int:
        payload = getattr(value, "payload", value)
        try:
            p = len(payload)
        except TypeError:
            p = 64
        return 64 + sum(len(w) + 8 for w in topic) + p

    def insert(self, mountpoint: str, topic: Sequence[str], value: Any) -> None:
        """Store/replace the retained message for a topic
        (vmq_retain_srv:insert/3)."""
        self._insert(mountpoint, topic, value)
        if self._on_dirty:
            self._on_dirty(mountpoint, tuple(topic), value)

    def _insert(self, mountpoint: str, topic: Sequence[str], value: Any) -> None:
        node = self._roots.setdefault(mountpoint, _RNode())
        for w in topic:
            node = node.children.setdefault(w, _RNode())
        if node.value is None:
            self._count += 1
        else:
            self._bytes -= self._vsize(topic, node.value)
        node.value = value
        self._bytes += self._vsize(topic, value)

    def delete(self, mountpoint: str, topic: Sequence[str]) -> bool:
        """Remove retained message (empty retained payload deletes,
        vmq_reg.erl:274-283)."""
        ok = self._delete(mountpoint, topic)
        if ok and self._on_dirty:
            self._on_dirty(mountpoint, tuple(topic), None)
        return ok

    def apply_remote(self, mountpoint: str, topic: Sequence[str], value: Any) -> None:
        """Apply a replicated change without re-firing the dirty hook (the
        metadata-event consumer path, vmq_retain_srv.erl:180-185)."""
        if value is None:
            self._delete(mountpoint, topic)
        else:
            self._insert(mountpoint, topic, value)

    def _delete(self, mountpoint: str, topic: Sequence[str]) -> bool:
        root = self._roots.get(mountpoint)
        if root is None:
            return False
        path: List[Tuple[_RNode, str]] = []
        node = root
        for w in topic:
            nxt = node.children.get(w)
            if nxt is None:
                return False
            path.append((node, w))
            node = nxt
        if node.value is None:
            return False
        self._bytes -= self._vsize(topic, node.value)
        node.value = None
        self._count -= 1
        for parent, w in reversed(path):
            child = parent.children[w]
            if child.value is not None or child.children:
                break
            del parent.children[w]
        return True

    def read(self, mountpoint: str, topic: Sequence[str]) -> Any:
        node = self._roots.get(mountpoint)
        if node is None:
            return None
        for w in topic:
            node = node.children.get(w)
            if node is None:
                return None
        return node.value

    def match_filter(
        self, mountpoint: str, filter_words: Sequence[str]
    ) -> List[Tuple[Tuple[str, ...], Any]]:
        """All retained (topic, value) whose topic matches the subscription
        filter — the retained-replay lookup on SUBSCRIBE
        (vmq_retain_srv:match_fold, vmq_reg.erl:380-418). Applies the
        MQTT-4.7.2-1 rule: root-level wildcards skip ``$``-topics."""
        root = self._roots.get(mountpoint)
        if root is None:
            return []
        out: List[Tuple[Tuple[str, ...], Any]] = []
        self._walk(root, list(filter_words), 0, (), out)
        return out

    def _collect_subtree(self, node: _RNode, path: Tuple[str, ...], out: list) -> None:
        if node.value is not None:
            out.append((path, node.value))
        for w, child in node.children.items():
            self._collect_subtree(child, path + (w,), out)

    def _walk(
        self,
        node: _RNode,
        fw: List[str],
        i: int,
        path: Tuple[str, ...],
        out: List[Tuple[Tuple[str, ...], Any]],
    ) -> None:
        if i == len(fw):
            if node.value is not None:
                out.append((path, node.value))
            return
        w = fw[i]
        if w == HASH:
            # '#' matches parent level too
            for cw, child in node.children.items():
                if i == 0 and cw.startswith("$"):
                    continue
                self._collect_subtree(child, path + (cw,), out)
            if node.value is not None:
                out.append((path, node.value))
        elif w == PLUS:
            for cw, child in node.children.items():
                if i == 0 and cw.startswith("$"):
                    continue
                self._walk(child, fw, i + 1, path + (cw,), out)
        else:
            child = node.children.get(w)
            if child is not None:
                self._walk(child, fw, i + 1, path + (w,), out)

    def items(self, mountpoint: Optional[str] = "") -> Iterator:
        """Iterate retained rows. With a named ``mountpoint`` (default
        ``""``) yields ``(topic, value)`` pairs, back-compat. With
        ``mountpoint=None`` iterates EVERY mountpoint, yielding
        ``(mountpoint, topic, value)`` triples — the all-mountpoints
        walk the admin/QL surface and the device-index warm load need."""
        if mountpoint is None:
            out_all: List[Tuple[str, Tuple[str, ...], Any]] = []
            for mp, root in self._roots.items():
                rows: List[Tuple[Tuple[str, ...], Any]] = []
                self._collect_subtree(root, (), rows)
                out_all.extend((mp, t, v) for t, v in rows)
            return iter(out_all)
        root = self._roots.get(mountpoint)
        if root is None:
            return iter(())
        out: List[Tuple[Tuple[str, ...], Any]] = []
        self._collect_subtree(root, (), out)
        return iter(out)
