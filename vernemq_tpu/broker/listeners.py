"""Runtime listener management (``vmq_ranch_config.erl``).

Listener kinds map to transports exactly like the reference
(``vmq_ranch_config.erl:224-227``): ``mqtt``/``mqtts`` plain and TLS MQTT,
``ws``/``wss`` (the reference's ``mqttws``/``mqttwss``) WebSocket MQTT,
``http``/``https`` the admin endpoints, ``vmq``/``vmqs`` the cluster
data-plane channel. Listeners can be started/stopped/reconfigured at
runtime via ``vmq-admin listener ...``."""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("vernemq_tpu.listeners")

KINDS = ("mqtt", "mqtts", "ws", "wss", "http", "https", "vmq", "vmqs")
# accept the reference's own names too
ALIASES = {"mqttws": "ws", "mqttwss": "wss"}


class ListenerManager:
    def __init__(self, broker):
        self.broker = broker
        broker.listeners = self
        # (addr, port) -> {"kind":…, "server":…, "opts":…}
        self._listeners: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self._start_tasks: List[asyncio.Task] = []

    # ------------------------------------------------------------- lifecycle

    async def start_listener(self, kind: str, addr: str, port: int,
                             opts: Optional[Dict[str, Any]] = None):
        """Start one listener; returns the server object. ``opts`` follows
        the reference listener schema (max_connections is advisory here;
        TLS opts per make_server_context; ``mountpoint`` for multitenancy)."""
        kind = ALIASES.get(kind, kind)
        if kind not in KINDS:
            raise ValueError(f"unknown listener kind {kind!r}")
        # fault-injection point: a simulated bind failure (EADDRINUSE,
        # EMFILE, ...) — the watchdog's rebind-retry path is exercised
        # by tests/test_restart_storm.py through this hook. Async
        # variant: latency faults must not block the event loop.
        from ..robustness import faults

        await faults.inject_async("listener.bind")
        opts = dict(opts or {})
        ssl_context = None
        if kind in ("mqtts", "wss", "https", "vmqs"):
            from .ssl_util import make_server_context

            ssl_context = make_server_context(opts)
        server: Any
        if kind in ("mqtt", "mqtts"):
            from .server import MQTTServer

            server = MQTTServer(
                self.broker, addr, port,
                max_frame_size=int(opts.get("max_frame_size", 0) or 0),
                ssl_context=ssl_context,
                proxy_protocol=bool(opts.get("proxy_protocol")),
                use_identity_as_username=bool(
                    opts.get("use_identity_as_username")),
                mountpoint=str(opts.get("mountpoint", "")),
                allowed_protocol_versions=opts.get(
                    "allowed_protocol_versions"),
                max_connections=int(opts.get("max_connections", 0) or 0),
                reuse_port=bool(opts.get("reuse_port")))
            await server.start()
            port = server.port
        elif kind in ("ws", "wss"):
            from .websocket import WebSocketServer

            server = WebSocketServer(
                self.broker, addr, port, ssl_context=ssl_context,
                max_frame_size=int(opts.get("max_frame_size", 0) or 0),
                use_identity_as_username=bool(
                    opts.get("use_identity_as_username")),
                mountpoint=str(opts.get("mountpoint", "")),
                allowed_protocol_versions=opts.get(
                    "allowed_protocol_versions"),
                max_connections=int(opts.get("max_connections", 0) or 0),
                reuse_port=bool(opts.get("reuse_port")))
            await server.start()
            port = server.port
        elif kind in ("http", "https"):
            from ..admin.http import DEFAULT_MODULES, HttpServer

            modules = opts.get("http_modules") or DEFAULT_MODULES
            server = HttpServer(self.broker, addr, port,
                                modules=tuple(modules),
                                ssl_context=ssl_context)
            await server.start()
            port = server.port
        else:  # vmq / vmqs — the cluster data-plane listener
            if self.broker.cluster is not None:
                # stop_listener schedules Cluster.stop() as a task; a
                # stop-then-start sequence must wait for that detach
                # instead of refusing against the half-stopped cluster.
                # Never gather OURSELVES: an admin `listener start` runs
                # inside a tracked task, and awaiting it here would
                # deadlock the listener manager permanently.
                cur = asyncio.current_task()
                pending = [t for t in self._start_tasks
                           if not t.done() and t is not cur]
                if pending:
                    await asyncio.gather(*pending, return_exceptions=True)
            if self.broker.cluster is None:
                from ..cluster import Cluster

                cluster = Cluster(self.broker, addr, port)
                try:
                    await cluster.start()
                except BaseException:
                    # __init__ attached broker.cluster/metadata hooks; a
                    # failed bind (stolen port, moved cert) must detach or
                    # every later start hits 'already running' forever
                    await cluster.stop()
                    raise
                server = cluster
                port = cluster.listen_port
            else:
                raise ValueError("cluster listener already running")
        self._listeners[(addr, port)] = {
            "kind": kind, "server": server, "opts": opts,
            "ssl_context": ssl_context,
        }
        log.info("started %s listener on %s:%d", kind, addr, port)
        return server

    def listener_records(self) -> List[Dict[str, Any]]:
        """Raw listener records (kind/server/opts/ssl_context) — consumed
        by the CRL refresher and introspection."""
        return list(self._listeners.values())

    def stop_listener(self, addr: str, port: int) -> None:
        """Stop accepting on a listener but KEEP its configuration so
        ``restart`` can bring it back (vmq_ranch_config suspend/resume
        split between listener stop and listener delete)."""
        entry = self._listeners.get((addr, port))
        if entry is None:
            raise KeyError(f"no listener on {addr}:{port}")
        server = entry["server"]
        entry["server"] = None  # stopped; opts/kind retained for restart
        stop = getattr(server, "stop", None) if server is not None else None
        if stop is not None:
            task = asyncio.get_event_loop().create_task(stop())
            self._track(task)

    def delete_listener(self, addr: str, port: int) -> None:
        """Stop (if running) and forget the listener entirely."""
        if (addr, port) in self._listeners:
            self.stop_listener(addr, port)
        self._listeners.pop((addr, port), None)

    async def restart_listener(self, addr: str, port: int):
        """Stop-and-start with the retained kind/opts (listener restart).
        A fixed port is required: a port-0 listener's bound port is its
        identity, and rebinding 0 would mint a different one."""
        entry = self._listeners.get((addr, port))
        if entry is None:
            raise KeyError(f"no listener on {addr}:{port}")
        if entry["server"] is not None:
            server = entry["server"]
            entry["server"] = None
            stop = getattr(server, "stop", None)
            if stop is not None:
                await stop()
        # the record stays until the new server is up: a failed start
        # (moved cert, stolen port) must leave the listener stopped and
        # RESTARTABLE, never erase its configuration. start_listener
        # overwrites the record on success.
        return await self.start_listener(entry["kind"], addr, port,
                                         entry["opts"])

    async def stop_all(self) -> None:
        for addr, port in list(self._listeners):
            try:
                self.delete_listener(addr, port)
            except KeyError:
                pass
        for t in self._start_tasks:
            try:
                await t
            except Exception:
                pass
        self._start_tasks.clear()

    def _track(self, task: asyncio.Task) -> None:
        """Retain a pending stop/start task (pruning finished ones — a
        long-lived broker restarts listeners indefinitely)."""
        self._start_tasks = [t for t in self._start_tasks if not t.done()]
        self._start_tasks.append(task)

    def track_start_task(self, task: asyncio.Task) -> None:
        """Keep a handle on listener starts launched from sync command
        context so failures surface in logs."""
        def _done(t: asyncio.Task) -> None:
            if not t.cancelled() and t.exception() is not None:
                log.error("listener start failed", exc_info=t.exception())

        task.add_done_callback(_done)
        self._track(task)

    # ---------------------------------------------------------------- admin

    def show(self) -> List[Dict[str, Any]]:
        rows = []
        for (addr, port), entry in sorted(self._listeners.items()):
            rows.append({
                "type": entry["kind"], "address": addr, "port": port,
                "mountpoint": entry["opts"].get("mountpoint", ""),
                "status": ("running" if entry["server"] is not None
                           else "stopped"),
            })
        return rows
