"""Subscriber DB: subscription records in the replicated metadata store.

Mirrors ``vmq_subscriber_db.erl``: store/read/fold/delete over the
metadata facade under a dedicated prefix (``vmq_subscriber_db.erl:26-54``)
plus change-event subscription (``:56-71``). The record keeps the
reference's subscriber format — node + clean_session + per-filter
subinfo (``vmq_subscriber.erl:35-48``) — so queue migration can remap the
node field the same way (``change_node``, ``vmq_subscriber.erl:97-128``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from ..protocol.types import SubOpts
from .message import SubscriberId

PREFIX = "subscriber"

Filter = Tuple[str, ...]


def opts_to_dict(opts: SubOpts) -> Dict[str, Any]:
    d = {
        "qos": opts.qos,
        "nl": opts.no_local,
        "rap": opts.rap,
        "rh": opts.retain_handling,
    }
    sub_id = getattr(opts, "subscription_id", None)
    if sub_id:
        d["sid"] = sub_id
    # MQTT+ payload-filter suffix (vernemq_tpu/filters/): carried
    # UNCONDITIONALLY — a node with payload filters disabled must
    # round-trip a replicated filtered subscription verbatim (re-storing
    # the record must never truncate it into a plain topic sub; the
    # "flt" cluster capability advertises which peers evaluate it)
    flt = getattr(opts, "filter_expr", None)
    if flt:
        d["flt"] = flt
    return d


def opts_from_dict(d: Dict[str, Any]) -> SubOpts:
    opts = SubOpts(qos=d.get("qos", 0), no_local=d.get("nl", False),
                   rap=d.get("rap", False), retain_handling=d.get("rh", 0))
    if "sid" in d:
        opts.subscription_id = d["sid"]
    if "flt" in d:
        opts.filter_expr = d["flt"]
    return opts


class SubscriberRecord:
    """One subscriber's replicated state: which node owns its queue, its
    clean-session flag, and its subscriptions."""

    __slots__ = ("node", "clean_session", "subs", "queue_opts")

    def __init__(self, node: str, clean_session: bool,
                 subs: Optional[Dict[Filter, SubOpts]] = None,
                 queue_opts: Optional[Dict[str, Any]] = None):
        self.node = node
        self.clean_session = clean_session
        self.subs: Dict[Filter, SubOpts] = subs or {}
        # durable queue parameters (session_expiry etc.) so offline queues
        # re-created at boot keep their semantics (vmq_reg_mgr boot path)
        self.queue_opts: Dict[str, Any] = queue_opts or {}

    def to_term(self) -> Dict[str, Any]:
        return {
            "node": self.node,
            "clean": self.clean_session,
            "subs": {f: opts_to_dict(o) for f, o in self.subs.items()},
            "qopts": self.queue_opts,
        }

    @classmethod
    def from_term(cls, t: Optional[Dict[str, Any]]) -> Optional["SubscriberRecord"]:
        if t is None:
            return None
        return cls(
            t["node"], t["clean"],
            {tuple(f): opts_from_dict(o) for f, o in t["subs"].items()},
            dict(t.get("qopts") or {}),
        )


class SubscriberDB:
    def __init__(self, metadata, node_name: str):
        self.metadata = metadata
        self.node_name = node_name

    def store(self, sid: SubscriberId, record: SubscriberRecord) -> None:
        self.metadata.put(PREFIX, tuple(sid), record.to_term())

    def store_many(
            self, pairs: Iterable[Tuple[SubscriberId,
                                        SubscriberRecord]]) -> int:
        """Store a batch of records as ONE logical write — the batched
        handoff's shared fence. The metadata facade has no multi-key
        primitive across its backends (LWW put vs SWC dotted puts), so
        physically this loops ``put``; the batching contract lives one
        level up: the caller bumps the fence counter and journals the
        fence event ONCE per batch, not per record. Returns the number
        of records stored."""
        n = 0
        for sid, record in pairs:
            self.metadata.put(PREFIX, tuple(sid), record.to_term())
            n += 1
        return n

    def read(self, sid: SubscriberId) -> Optional[SubscriberRecord]:
        return SubscriberRecord.from_term(
            self.metadata.get(PREFIX, tuple(sid)))

    def delete(self, sid: SubscriberId) -> None:
        self.metadata.delete(PREFIX, tuple(sid))

    def fold(self) -> Iterable[Tuple[SubscriberId, SubscriberRecord]]:
        for key, term in self.metadata.fold(PREFIX):
            yield (key[0], key[1]), SubscriberRecord.from_term(term)

    def fold_raw(self) -> Iterable[Tuple[SubscriberId, Dict[str, Any]]]:
        """Stream the raw stored terms WITHOUT materialising
        SubscriberRecord/SubOpts objects: the boot warm-load walks
        every stored subscriber and builds its routing rows straight
        from the terms (interning shared opts shapes), so a huge
        restart doesn't allocate a record object graph per parked
        session just to throw it away."""
        for key, term in self.metadata.fold(PREFIX):
            yield (key[0], key[1]), term

    def subscribe_db_events(
        self, fn: Callable[[SubscriberId, Optional[SubscriberRecord],
                            Optional[SubscriberRecord], str], None]) -> None:
        """fn(sid, old_record, new_record, origin) on every change — local
        writes fire synchronously (read-your-writes for the local trie,
        matching the reference's synchronous trie events); replicated
        writes carry the originating node so consumers can tell a remote
        remap (→ create the offline queue here, vmq_reg_mgr.erl:155-243)
        from their own."""

        def _on_change(key, old, new, origin):
            fn((key[0], key[1]),
               SubscriberRecord.from_term(old),
               SubscriberRecord.from_term(new), origin)

        self.metadata.subscribe(PREFIX, _on_change)
