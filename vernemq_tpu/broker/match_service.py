"""Shared-memory device-match service: ONE matcher process serving N
SO_REUSEPORT session workers.

The multi-process front end (``broker/workers.py``) shards sessions
across N worker processes — parse, auth, session FSM, queues and the
cluster data plane all run worker-local. Matching is the one hot-path
piece that must NOT be replicated per worker: the device table is big
(HBM-resident at scale) and the whole point of the batch pipeline is to
coalesce EVERY concurrent publish on the node into few large dispatches.
So one **match service** process owns the subscription trie + device
mirror, and each worker talks to it over two shared-memory rings
(:class:`~vernemq_tpu.parallel.shm_ring.ShmRing`):

- worker -> service: pickled records, in order per worker —
  ``("fold", req_id, mountpoint, topics)`` publish batches, and the
  subscription write path ``("sub"|"unsub", mountpoint, filter, key,
  opts)`` + ``("resync", node)`` stream that keeps the service's table
  the union of every worker's locally-owned rows;
- service -> worker: ``(req_id, "ok", rows_per_topic)`` match results
  (or ``(req_id, "err", reason)``).

The service-side drainer feeds fold requests from ALL workers into the
same :class:`~vernemq_tpu.models.tpu_matcher.BatchCollector` the
in-process path uses — the submitters are now processes instead of
tasks, and K worker batches super-batch into one ``match_many``
dispatch exactly as K tasks did. Rows come back **node-qualified**
(``opts.node`` names the owning worker); the worker-side stub localizes
them — own rows stay direct, foreign rows collapse to node-pointer rows
— so ``route_rows`` sees exactly what the worker's own trie fold would
have produced.

Degradation is the usual discipline: a full ring, a dead service or a
timed-out reply raises :class:`DeviceDegraded` through the worker's
client breaker, and the worker's BatchCollector serves the flush from
its LOCAL trie (every worker keeps the full replicated trie — it is the
correctness oracle, results are identical). A respawned service starts
empty under a new epoch; every worker notices the epoch bump in the
stats block and replays its owned rows (``resync``), healing the
partition without operator action.

Pickle is safe here: both ring ends are processes of the same broker
install on one host, created by the same parent — the rings are not a
network surface.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import pickle
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Iterable, List, Optional, Sequence, \
    Set, Tuple

from ..models.trie import SubscriptionTrie
from ..models.tpu_matcher import DeviceDegraded
from ..observability import events as _events
from ..observability import histogram as obs
from ..parallel.shm_ring import RingClosed, RingFull, ShmRing, \
    WorkerStatsBlock
from ..robustness import watchdog as watchdog_mod
from ..robustness.breaker import CircuitBreaker

log = logging.getLogger("vernemq_tpu.match_service")

#: pickled records keep tuple identity cheap (protocol 5 memoizes the
#: interned per-batch topic words)
_PICKLE = 5


def _enc(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=_PICKLE)


def _dec(data: bytes) -> Any:
    return pickle.loads(data)


def owned_delta(node: str, key: Any, opts: Any) -> bool:
    """Should a worker forward this registry delta to the service?

    Node-pointer rows never forward (the service derives pointers per
    querying worker from ``opts.node``). Plain-sid rows only ever fire
    locally (``reg._trie_add/_trie_remove`` emit them when node ==
    self), so they forward. Shared-group adds are emitted by EVERY
    worker for every replicated record — only the owner forwards;
    removes carry no opts, so they forward from everyone and the
    service applies them idempotently."""
    if isinstance(key, str):
        return False
    if isinstance(key, tuple) and len(key) == 3 and key[0] == "$g":
        if opts is None:
            return True
        return getattr(opts, "node", node) == node
    return True


def localize_rows(rows: Iterable[Tuple], node: str) -> List[Tuple]:
    """Translate service (node-qualified) rows into the shape THIS
    worker's own trie fold would return: own plain rows stay direct,
    foreign plain rows become node-pointer rows (route_rows dedups the
    forwards per node), shared rows pass through (their opts.node
    already drives the shared-sub policy)."""
    out: List[Tuple] = []
    for fw, key, opts in rows:
        if isinstance(key, tuple) and len(key) == 3 and key[0] == "$g":
            out.append((fw, key, opts))
            continue
        owner = getattr(opts, "node", None) if opts is not None else None
        if owner is None or owner == node:
            out.append((fw, key, opts))
        else:
            out.append((fw, owner, None))
    return out


class _ServiceRegistryShim:
    """The minimal registry surface TpuRegView/BatchCollector need,
    backed by the service's own sub state: ``trie(mp)`` (warm-load +
    host fallback oracle) and ``fold_subscriptions(mp)``."""

    def __init__(self, service: "MatchService"):
        self._service = service

    def trie(self, mountpoint: str = "") -> SubscriptionTrie:
        return self._service.trie(mountpoint)

    def fold_subscriptions(self, mountpoint: str = ""):
        return self.trie(mountpoint).entries()


class MatchService:
    """Service-process core: subscription state + the drainer that
    super-batches ring fold requests into the match pipeline."""

    def __init__(self, stats: WorkerStatsBlock,
                 rings: Sequence[Tuple[ShmRing, ShmRing]],
                 view: str = "trie",
                 tpu_opts: Optional[Dict[str, Any]] = None,
                 collector_window_us: int = 200,
                 super_batch_k: int = 8):
        self.stats = stats
        self.rings = list(rings)  # [(req, resp), ...] per worker
        for _req, resp in self.rings:
            # this process is the sole producer of every response ring:
            # a predecessor's orderly close() left them marked closed,
            # and without this reset a respawned service could never
            # answer a fold again (workers would degrade to the local
            # trie forever despite the epoch-bump resync)
            resp.mark_open()
        self.view_kind = view
        self._tries: Dict[str, SubscriptionTrie] = {}
        # (mountpoint, filter, key) -> opts; the dedup/idempotency layer
        # that makes worker resync replays and duplicate shared-row
        # removes harmless
        self._subs: Dict[Tuple[str, Tuple[str, ...], Any], Any] = {}
        self.ops_applied = 0
        self.stale_unsubs = 0
        # ring index -> node name, learned from each worker's "resync"
        # announcement (always its first record): lets apply_unsub
        # reject a previous owner's racing remove after a reconnect
        # handed the row to another worker
        self._ring_node: Dict[int, str] = {}
        self.folds = 0
        self.fold_pubs = 0
        self.resyncs = 0
        self.fold_errors = 0
        self.responses_dropped = 0
        self._pending_resp: List[Deque[Tuple[float, bytes]]] = \
            [deque() for _ in self.rings]
        self._view = None
        self._collector = None
        if view == "tpu":
            from ..models.tpu_matcher import BatchCollector, TpuRegView

            shim = _ServiceRegistryShim(self)
            opts = dict(tpu_opts or {})
            self._view = TpuRegView(shim, **opts)
            self._collector = BatchCollector(
                self._view, window_us=collector_window_us,
                super_batch_k=super_batch_k)

    # --------------------------------------------------------- sub state

    def trie(self, mountpoint: str = "") -> SubscriptionTrie:
        t = self._tries.get(mountpoint)
        if t is None:
            t = self._tries[mountpoint] = SubscriptionTrie()
        return t

    def _emit_tpu_delta(self, op: str, mp: str, fw, key, opts) -> None:
        if self._view is not None:
            try:
                self._view.on_delta(op, mp, list(fw), key, opts)
            except Exception:
                log.exception("device-table delta failed (the trie "
                              "oracle stays correct; dispatch degrades)")

    def apply_sub(self, mp: str, fw, key, opts) -> None:
        fw = tuple(fw)
        k = (mp, fw, key)
        prev = self._subs.get(k, _MISSING)
        if prev is not _MISSING and _opts_eq(prev, opts):
            return  # duplicate forward (resync replay): no-op
        self._subs[k] = opts
        self.trie(mp).add(list(fw), key, opts)
        self._emit_tpu_delta("add", mp, fw, key, opts)
        self.ops_applied += 1
        self.stats.bump_generation()

    def apply_unsub(self, mp: str, fw, key,
                    from_node: Optional[str] = None) -> None:
        fw = tuple(fw)
        k = (mp, fw, key)
        if from_node is not None and not (
                isinstance(key, tuple) and len(key) == 3
                and key[0] == "$g"):
            # plain rows only ever fire from their owner's worker: an
            # unsub from any OTHER ring is a previous owner's racing
            # remove after a reconnect moved the client — the new
            # owner's re-add must survive it. Shared ($g) removes are
            # deliberately exempt: every worker forwards them for every
            # replicated record and the pop below dedups.
            cur = self._subs.get(k, _MISSING)
            if cur is not _MISSING and \
                    getattr(cur, "node", from_node) != from_node:
                self.stale_unsubs += 1
                return
        if self._subs.pop(k, _MISSING) is _MISSING:
            return  # duplicate/unknown remove: idempotent
        self.trie(mp).remove(list(fw), key)
        self._emit_tpu_delta("remove", mp, fw, key, None)
        self.ops_applied += 1
        self.stats.bump_generation()

    def apply_resync(self, node: str) -> None:
        """A worker (re)starts its forward stream: drop every row it
        owns — it replays them all right after, so a respawned worker
        (same identity, empty session set) can never leave stale rows
        matching into its dead sessions."""
        self.resyncs += 1
        dead = [(mp, fw, key) for (mp, fw, key), opts in self._subs.items()
                if _row_owner(key, opts) == node]
        for mp, fw, key in dead:
            self.apply_unsub(mp, fw, key)
        self.stats.bump_generation()

    # ------------------------------------------------------------ serving

    def subscriptions(self) -> int:
        return len(self._subs)

    def handle_record(self, widx: int, raw: bytes) -> None:
        try:
            rec = _dec(raw)
            kind = rec[0]
        except Exception:
            log.exception("undecodable ring record from worker %d", widx)
            return
        if kind == "fold":
            _, req_id, mp, topics = rec[:4]
            # flight-recorder envelope: a 5th element marks a traced
            # fold — the reply then carries this process's receive/done
            # CLOCK_MONOTONIC stamps + pid so the worker's recorder can
            # split the ring round trip into request transit / service
            # residency / reply transit (recorder.PublishTrace.meta)
            traced = len(rec) > 4 and bool(rec[4])
            t_recv = time.monotonic() if traced else 0.0
            self.folds += 1
            self.fold_pubs += len(topics)
            if self._collector is not None:
                fut = self._collector.submit_batch(mp, topics)

                def _done(f, widx=widx, req_id=req_id,
                          mp=mp, topics=topics, t_recv=t_recv,
                          traced=traced):
                    exc = f.exception()
                    if exc is not None:
                        # the collector itself degrades to the service
                        # trie internally; an error here is exceptional
                        self.fold_errors += 1
                        self._respond(widx,
                                      (req_id, "err", repr(exc)))
                    elif traced:
                        self._respond(widx, (req_id, "ok", f.result(),
                                             self._fold_meta(t_recv)))
                    else:
                        self._respond(widx, (req_id, "ok", f.result()))

                fut.add_done_callback(_done)
            else:
                trie = self.trie(mp)
                rows = [trie.match(list(t)) for t in topics]
                if traced:
                    self._respond(widx, (req_id, "ok", rows,
                                         self._fold_meta(t_recv)))
                else:
                    self._respond(widx, (req_id, "ok", rows))
        elif kind == "sub":
            _, mp, fw, key, opts = rec
            self.apply_sub(mp, fw, key, opts)
        elif kind == "unsub":
            _, mp, fw, key = rec
            self.apply_unsub(mp, fw, key,
                             from_node=self._ring_node.get(widx))
        elif kind == "resync":
            self._ring_node[widx] = rec[1]
            self.apply_resync(rec[1])
        else:
            log.warning("unknown ring record kind %r from worker %d",
                        kind, widx)

    @staticmethod
    def _fold_meta(t_recv: float) -> Dict[str, float]:
        return {"svc_recv": t_recv, "svc_done": time.monotonic(),
                "svc_pid": os.getpid()}

    #: unsent responses older than this are dropped — the worker's fold
    #: timed out long ago and is serving its local trie already
    RESP_TTL_S = 10.0

    def _respond(self, widx: int, payload: Tuple) -> None:
        data = _enc(payload)
        ring = self.rings[widx][1]
        try:
            if not ring.push(data):
                self._pending_resp[widx].append((time.monotonic(), data))
        except (RingClosed, RingFull):
            self.responses_dropped += 1

    def _retry_pending(self) -> None:
        now = time.monotonic()
        for widx, pend in enumerate(self._pending_resp):
            while pend:
                ts, data = pend[0]
                if now - ts > self.RESP_TTL_S:
                    pend.popleft()
                    self.responses_dropped += 1
                    continue
                try:
                    if not self.rings[widx][1].push(data):
                        break
                except (RingClosed, RingFull):
                    self.responses_dropped += 1
                pend.popleft()

    def poll_once(self, max_records: int = 64) -> int:
        """One drain pass over every worker's request ring; returns the
        number of records handled."""
        n = 0
        for widx, (req, _resp) in enumerate(self.rings):
            for raw in req.pop_many(max_records):
                self.handle_record(widx, raw)
                n += 1
        self._retry_pending()
        return n

    def publish_stats(self) -> None:
        self.stats.service_heartbeat()
        self.stats.set_service_counters(self.ops_applied, self.folds,
                                        self.fold_pubs)
        # the device-side stage histograms (dispatch/delta/rebuild/
        # collector wait) live in THIS process; publishing the packed
        # block is the only way they reach a worker's scrape endpoint
        try:
            self.stats.write_service_hist(obs.pack_all())
            self.stats.write_service_events(_events.journal().pack())
        except Exception:
            pass  # an old-layout block (no hist region) stays healthy

    async def run(self, stop: asyncio.Event,
                  idle_min_s: float = 0.0003,
                  idle_max_s: float = 0.005) -> None:
        """The drainer loop: busy while records flow, exponential
        poll backoff when idle (bounded at ``idle_max_s`` so fold
        latency stays sub-window even from cold)."""
        idle = idle_min_s
        last_hb = 0.0
        while not stop.is_set():
            n = self.poll_once()
            now = time.monotonic()
            if now - last_hb >= 0.25:
                self.publish_stats()
                last_hb = now
            if n:
                idle = idle_min_s
                # yield even when busy: in view='tpu' mode the fold
                # replies come from BatchCollector call_later flushes and
                # executor-completion callbacks on THIS loop — a sustained
                # record stream (e.g. a worker's resync replay) must not
                # starve them or every in-flight fold times out
                await asyncio.sleep(0)
            else:
                await asyncio.sleep(idle)
                idle = min(idle * 2, idle_max_s)

    def close(self) -> None:
        for req, resp in self.rings:
            try:
                resp.mark_closed()
            except Exception:
                pass
        if self._view is not None:
            self._view.close()


_MISSING = object()


def _opts_eq(a: Any, b: Any) -> bool:
    # SubOpts is a dataclass whose generated __eq__ ignores the
    # dynamically-assigned .node — but node is exactly what changes when
    # a reconnecting client lands on a different worker (ownership
    # transfer). Swallowing that re-add as a duplicate leaves the row
    # owned by the OLD worker, whose racing unsub then deletes it.
    try:
        return (a == b
                and getattr(a, "node", None) == getattr(b, "node", None))
    except Exception:
        return False


def _row_owner(key: Any, opts: Any) -> Optional[str]:
    if opts is not None:
        node = getattr(opts, "node", None)
        if node is not None:
            return node
    return None


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


class _ResponseMux:
    """Demultiplex the (single-consumer) response ring across concurrent
    fold threads: exactly one waiting thread drains the ring at a time;
    everyone else waits on the condition for its req_id to land."""

    #: stored replies nobody claims (their fold timed out and forgot the
    #: req_id before the drain landed it) are pruned after this long —
    #: req ids are pid-salted and never reused, so an unclaimed entry is
    #: garbage forever and a persistently-slow service would otherwise
    #: grow ``_resp`` without bound
    STALE_TTL_S = 30.0

    def __init__(self, ring: ShmRing):
        self._ring = ring
        self._cond = threading.Condition()
        self._resp: Dict[int, Tuple[float, str, Any]] = {}
        self._draining = False
        self._last_prune = 0.0

    def wait_for(self, req_id: int,
                 deadline: float) -> Tuple[str, Any, Optional[dict]]:
        while True:
            with self._cond:
                if req_id in self._resp:
                    _, status, payload, meta = self._resp.pop(req_id)
                    return (status, payload, meta)
                if self._draining:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError("match service reply timeout")
                    self._cond.wait(min(remaining, 0.05))
                    continue
                self._draining = True
            try:
                got = self._drain(req_id, deadline)
                if got is not None:
                    return got
            finally:
                with self._cond:
                    self._draining = False
                    self._cond.notify_all()

    def _drain(self, req_id: int,
               deadline: float) -> Optional[Tuple[str, Any,
                                                  Optional[dict]]]:
        while True:
            recs = self._ring.pop_many()
            if recs:
                now = time.monotonic()
                with self._cond:
                    out = None
                    for raw in recs:
                        try:
                            rec = _dec(raw)
                            rid, status, payload = rec[0], rec[1], rec[2]
                            meta = rec[3] if len(rec) > 3 else None
                        except Exception:
                            continue
                        if rid == req_id:
                            out = (status, payload, meta)
                        else:
                            self._resp[rid] = (now, status, payload, meta)
                    if self._resp and now - self._last_prune > 1.0:
                        self._last_prune = now
                        cutoff = now - self.STALE_TTL_S
                        for rid in [r for r, (ts, *_)
                                    in self._resp.items() if ts < cutoff]:
                            del self._resp[rid]
                    self._cond.notify_all()
                    if out is not None:
                        return out
                continue
            if time.monotonic() >= deadline:
                raise TimeoutError("match service reply timeout")
            if self._ring.closed:
                raise RingClosed(self._ring.name)
            time.sleep(0.0003)

    def forget(self, req_id: int) -> None:
        with self._cond:
            self._resp.pop(req_id, None)


class MatchServiceClient:
    """Worker-side stub: marshals fold batches and subscription write
    ops into the request ring, demuxes replies, tracks the service
    epoch and replays owned rows after a service respawn."""

    #: op backlog bound while the ring is full / the service is down:
    #: past it the backlog is dropped and a FULL resync is owed (the
    #: resync replays everything, so dropping loses nothing). A resync
    #: replay itself never contributes more than RESYNC_CHUNK queued
    #: rows (the pump backpressures on backlog depth), so overflow only
    #: ever means live deltas alone outran the ring — re-arming the
    #: resync then cannot livelock.
    MAX_OP_BACKLOG = 65536
    #: resync rows encoded per pump call while the backlog has room —
    #: bounds the per-tick event-loop hold (a million-row replay streams
    #: across ticks instead of freezing session IO for one giant encode)
    RESYNC_CHUNK = 2048
    #: max rows replayed per keeper tick when the ring keeps up
    RESYNC_TICK_BUDGET = 16384

    def __init__(self, req_ring: str, resp_ring: str, stats_block: str,
                 worker_index: int, node_name: str,
                 timeout_ms: float = 2000.0,
                 breaker: Optional[CircuitBreaker] = None):
        self.req = ShmRing.attach(req_ring)
        self.resp = ShmRing.attach(resp_ring)
        self.stats = WorkerStatsBlock.attach(stats_block)
        self.worker_index = worker_index
        self.node_name = node_name
        self.timeout_s = timeout_ms / 1e3
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, backoff_initial=0.5, backoff_max=5.0,
            name="match_client")
        self._mux = _ResponseMux(self.resp)
        self._req_lock = threading.Lock()  # single-producer discipline
        # drain stale replies a dead predecessor (same worker identity,
        # earlier pid) never read, and salt req ids with the pid: a
        # leftover reply must never satisfy a NEW request's id
        while self.resp.pop_many(256):
            pass
        self._ids = itertools.count(((os.getpid() & 0xFFFF) << 32) + 1)
        self._op_backlog: Deque[bytes] = deque()
        # the construction-time epoch is the one this client serves
        # against; a mismatch later (service respawned) fences folds to
        # the local trie until the keeper finishes the resync. start()
        # arms the first-boot announcement resync; keeper-less direct
        # use (unit tests, tooling) serves immediately.
        self._need_resync = False
        self._seen_epoch: int = self.stats.epoch()
        # active chunked resync: a snapshot of owned rows still to
        # stream, and the keys live ops superseded since the snapshot
        # (their snapshot rows must not replay over the newer op)
        self._resync_rows: Optional[Deque[Tuple]] = None
        self._resync_superseded: Set[Tuple] = set()
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        self.folds_sent = 0
        self.fold_pubs_sent = 0
        self.fold_timeouts = 0
        self.fold_stalls = 0
        self.fold_degraded = 0
        self.fold_held = 0
        self.ops_sent = 0
        self.ops_dropped = 0
        self.resyncs_sent = 0

    # ------------------------------------------------------------- fold

    def fold(self, mountpoint: str,
             topics: Sequence[Tuple[str, ...]],
             meta_out: Optional[dict] = None) -> List[List[Tuple]]:
        """Round-trip one batch of publish topics through the service.
        BLOCKING — call from an executor/sacrificial thread only (the
        BatchCollector already runs its flushes there). Raises
        DeviceDegraded when the service can't serve promptly; the
        caller's shed path serves the local trie.

        ``meta_out`` (flight recorder): when given, the fold is marked
        traced in the envelope and this dict is filled with the ring
        send/receive stamps plus the service's own receive/done stamps
        and pid — the cross-process half of ONE publish record."""
        if self._closed:
            raise DeviceDegraded("match service client closed")
        if not self.breaker.allow():
            self.fold_degraded += 1
            raise DeviceDegraded("match service circuit open")
        if self._op_backlog or self._need_resync \
                or self._resync_rows is not None \
                or self.stats.epoch() != self._seen_epoch:
            # ordering fence: a queued ("sub", ...) op means the service
            # trie is missing an already-SUBACKed row — a fold pushed
            # now would overtake it in the ring and return results the
            # in-process (synchronous trie add) path could never produce.
            # Same for an epoch bump the keeper hasn't resynced yet (a
            # respawned service is empty) and for an in-flight resync
            # replay (service state is partial). Serve the local trie
            # until the op channel is caught up. NOT a breaker event:
            # the service isn't failing, we are simply not allowed to
            # overtake our own write stream.
            self.fold_held += 1
            raise DeviceDegraded("match service op backlog pending")
        req_id = next(self._ids)
        if meta_out is None:
            data = _enc(("fold", req_id, mountpoint,
                         [tuple(t) for t in topics]))
        else:
            data = _enc(("fold", req_id, mountpoint,
                         [tuple(t) for t in topics], True))
        send_t = time.monotonic()
        try:
            with self._req_lock:
                ok = self.req.push(data)
        except (RingClosed, RingFull) as e:
            self._fold_failed()
            raise DeviceDegraded(f"match service ring: {e!r}") from e
        if not ok:
            self._fold_failed()
            raise DeviceDegraded("match service request ring full")
        self.folds_sent += 1
        self.fold_pubs_sent += len(topics)
        deadline = time.monotonic() + self.timeout_s
        try:
            status, payload, meta = self._mux.wait_for(req_id, deadline)
        except TimeoutError as e:
            self.fold_timeouts += 1
            self._mux.forget(req_id)
            self._fold_failed()
            raise DeviceDegraded("match service reply timeout") from e
        except RingClosed as e:
            self._fold_failed()
            raise DeviceDegraded("match service ring closed") from e
        recv_t = time.monotonic()
        # per-fold ring round trip (request push -> reply landed): the
        # seam the match_service_timeout_ms knob is judged against.
        # Straggler-guarded: a watchdog-abandoned fold's late reply
        # must not record its wedge-inflated RTT into the tuning base
        if not watchdog_mod.current_op_abandoned():
            obs.observe("stage_ring_rtt_ms", (recv_t - send_t) * 1e3)
        if status != "ok":
            self._fold_failed()
            raise DeviceDegraded(f"match service error: {payload}")
        if meta_out is not None:
            meta_out["send_t"] = send_t
            meta_out["recv_t"] = recv_t
            if meta:
                meta_out.update(meta)
        if not watchdog_mod.current_op_abandoned():
            # a watchdog-abandoned fold's straggler reply must not close
            # the breaker its own stall just fed (record_stall) — same
            # guard as TpuMatcher._record_device_success
            self.breaker.record_success()
        return [localize_rows(rows, self.node_name) for rows in payload]

    def _fold_failed(self) -> None:
        if watchdog_mod.current_op_abandoned():
            # the stall already recorded this fold's failure at
            # abandonment; a late timeout/error must not double-count
            return
        if self.breaker.record_failure():
            log.error("match service path OPENED (worker %d): folds "
                      "degrade to the local trie until a probe succeeds",
                      self.worker_index)

    # ------------------------------------------------- subscription ops

    def send_op(self, record: Tuple) -> None:
        """Queue one subscription write op (loop-side, non-blocking).
        Ring-full ops buffer in the backlog; overflow forces a full
        resync instead of silently dropping a row."""
        if self._closed:
            return
        if self._resync_rows is not None and record[0] in ("sub", "unsub"):
            # a live op during an active resync wins over the snapshot:
            # its row must not be replayed underneath (a snapshot sub
            # landing after a live unsub would resurrect a dead row)
            self._resync_superseded.add(
                (record[1], tuple(record[2]), record[3]))
        self._op_backlog.append(_enc(record))
        if len(self._op_backlog) > self.MAX_OP_BACKLOG:
            self.ops_dropped += len(self._op_backlog)
            self._op_backlog.clear()
            self._resync_rows = None
            self._resync_superseded = set()
            self._need_resync = True
        self._flush_ops()

    def _flush_ops(self) -> int:
        sent = 0
        while self._op_backlog:
            data = self._op_backlog[0]
            try:
                with self._req_lock:
                    ok = self.req.push(data)
            except RingFull:
                # this record can NEVER fit (> ring capacity / 2):
                # keeping it at the backlog head would wedge every op
                # behind it until the overflow resync loops on the same
                # row — drop it and count, the local trie still serves
                self._op_backlog.popleft()
                self.ops_dropped += 1
                log.error("match service op record exceeds ring bound; "
                          "dropped (%dB)", len(data))
                continue
            except RingClosed:
                break
            if not ok:
                break
            self._op_backlog.popleft()
            self.ops_sent += 1
            sent += 1
        return sent

    def resync(self, registry) -> None:
        """Replay every locally-owned row: the service dropped (or never
        had) this worker's rows — announce ownership, then stream them
        through the same ordered op channel.

        The replay is CHUNKED: this call only snapshots row references
        (no pickling) and enqueues the ownership marker; the keeper
        pumps the snapshot into the ring RESYNC_CHUNK rows at a time,
        so a million-row replay never freezes the worker loop for one
        giant encode and never balloons the op backlog past its bound.
        Folds degrade to the local trie while the replay is in flight
        (the fold() ordering fence), so partial service state is never
        served."""
        self.resyncs_sent += 1
        rows: Deque[Tuple] = deque()
        for mp in list(getattr(registry, "_tries", {})):
            for fw, key, opts in registry.fold_subscriptions(mp):
                if owned_delta(self.node_name, key, opts) \
                        and not isinstance(key, str) and opts is not None:
                    rows.append((mp, tuple(fw), key, opts))
        self._op_backlog.appendleft(_enc(("resync", self.node_name)))
        self._resync_rows = rows
        self._resync_superseded = set()
        self._pump_resync()

    def _pump_resync(self) -> None:
        """Stream queued resync rows into the op channel, bounded per
        call: at most RESYNC_TICK_BUDGET rows encoded, never growing the
        backlog past RESYNC_CHUNK (ring-full backpressure — the next
        tick resumes where this one stopped)."""
        rows = self._resync_rows
        if rows is None:
            return
        budget = self.RESYNC_TICK_BUDGET
        while rows and budget > 0:
            if len(self._op_backlog) >= self.RESYNC_CHUNK:
                if not self._flush_ops():
                    return  # ring full: resume next tick
                continue
            mp, fw, key, opts = rows.popleft()
            if (mp, fw, key) in self._resync_superseded:
                continue
            self._op_backlog.append(_enc(("sub", mp, fw, key, opts)))
            budget -= 1
        self._flush_ops()
        if not rows:
            self._resync_rows = None
            self._resync_superseded = set()

    # ------------------------------------------------------- supervision

    def generation(self) -> int:
        return self.stats.generation()

    def service_info(self) -> Dict[str, Any]:
        return self.stats.service_info()

    def start(self, registry, interval_s: float = 0.25) -> None:
        """Loop-side keeper task: flushes the op backlog and watches the
        service epoch — a bump means the service respawned empty, so
        every owned row replays (partition healing). The first tick
        always resyncs: a respawned WORKER (same identity, fresh
        sessions) must drop its predecessor's stale rows even when the
        service epoch never moved."""
        self._need_resync = True

        async def _keeper() -> None:
            while not self._closed:
                try:
                    epoch = self.stats.epoch()
                    if epoch and (self._need_resync
                                  or epoch != self._seen_epoch):
                        # resync() installs _resync_rows before _seen_epoch
                        # advances or _need_resync clears, so the fold()
                        # fence never has a gap between "replay needed"
                        # and "replay in flight" — clearing the flag first
                        # would open the fence for the whole snapshot
                        # build when the epoch never moved (worker
                        # respawn); a resync() failure retries next tick
                        self.resync(registry)
                        self._seen_epoch = epoch
                        self._need_resync = False
                    elif self._resync_rows is not None:
                        self._pump_resync()
                    elif self._op_backlog:
                        self._flush_ops()
                except Exception:
                    log.exception("match service keeper tick failed")
                await asyncio.sleep(interval_s)

        self._task = asyncio.get_event_loop().create_task(_keeper())

    def stats_dict(self) -> Dict[str, float]:
        return {
            "match_client_folds": float(self.folds_sent),
            "match_client_fold_pubs": float(self.fold_pubs_sent),
            "match_client_timeouts": float(self.fold_timeouts),
            "match_client_stalls": float(self.fold_stalls),
            "match_client_degraded": float(self.fold_degraded),
            "match_client_held": float(self.fold_held),
            "match_client_ops_sent": float(self.ops_sent),
            "match_client_ops_dropped": float(self.ops_dropped),
            "match_client_resyncs": float(self.resyncs_sent),
            "match_client_breaker_state": float(self.breaker.state),
            "match_client_op_backlog": float(len(self._op_backlog)),
        }

    def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.req.close()
        self.resp.close()
        self.stats.close()


class _ClientMatcherStub:
    """What BatchCollector sees as 'the matcher' in client mode: stall
    reports feed the client breaker (a deadline-abandoned ring fold is
    a service failure like any other)."""

    def __init__(self, client: MatchServiceClient):
        self._client = client

    def record_stall(self, exc: Optional[BaseException] = None) -> None:
        self._client.fold_stalls += 1
        self._client._fold_failed()


class ShmMatchView:
    """The reg-view seam adapter workers mount at ``reg_views["tpu"]``:
    fold batches go to the match service over the rings; subscription
    deltas forward ownership-filtered; everything degrades to the
    worker's local trie through the standard shed exceptions."""

    name = "tpu"
    #: BatchCollector probes this: fold_batch/fold_many accept a
    #: meta_out box that comes back filled with the cross-process ring
    #: stamps for a traced flush (flight recorder envelope)
    fold_meta_capable = True

    def __init__(self, registry, client: MatchServiceClient):
        self.registry = registry
        self.client = client
        self._stub = _ClientMatcherStub(client)

    # BatchCollector surface ------------------------------------------

    def matcher(self, mountpoint: str = "") -> _ClientMatcherStub:
        return self._stub

    def fold(self, mountpoint: str, topic: Sequence[str]) -> List[Tuple]:
        try:
            return self.client.fold(mountpoint, [tuple(topic)])[0]
        except DeviceDegraded:
            return self.registry.trie(mountpoint).match(list(topic))

    def fold_batch(self, mountpoint: str,
                   topics: Sequence[Sequence[str]],
                   lock_timeout: Optional[float] = None,
                   meta_out: Optional[dict] = None):
        return self.client.fold(mountpoint, [tuple(t) for t in topics],
                                meta_out=meta_out)

    def fold_many(self, mountpoint: str,
                  batches: Sequence[Sequence[Sequence[str]]],
                  lock_timeout: Optional[float] = None,
                  meta_out: Optional[dict] = None):
        flat: List[Tuple[str, ...]] = []
        for b in batches:
            flat.extend(tuple(t) for t in b)
        rows = self.client.fold(mountpoint, flat, meta_out=meta_out)
        out, i = [], 0
        for b in batches:
            out.append(rows[i:i + len(b)])
            i += len(b)
        return out

    def supports_many(self, mountpoint: str = "") -> bool:
        return True

    # registry delta feed ---------------------------------------------

    def on_delta(self, op: str, mountpoint: str, filter_words, key,
                 opts) -> None:
        if not owned_delta(self.client.node_name, key, opts):
            return
        if op == "add":
            self.client.send_op(("sub", mountpoint, tuple(filter_words),
                                 key, opts))
        else:
            self.client.send_op(("unsub", mountpoint,
                                 tuple(filter_words), key))

    # admin/metrics surface -------------------------------------------

    def breaker_status(self) -> Dict[str, Any]:
        return {"(match-service)": self.client.breaker.status()}

    def close(self) -> None:
        self.client.close()


# ---------------------------------------------------------------------------
# service process entry point (spawn-safe, top-level)
# ---------------------------------------------------------------------------


def _service_main(stats_name: str,
                  ring_names: List[Tuple[str, str]],
                  view: str, epoch: int,
                  tpu_opts: Optional[Dict[str, Any]] = None) -> None:
    import faulthandler
    import signal

    dump_s = int(os.environ.get("TIER1_FAULTHANDLER_S") or 0)
    if dump_s > 0:
        # hung-child forensics: same contract as tests/conftest.py —
        # the parent's wall kills us, but the log says where we hung
        faulthandler.enable()
        faulthandler.dump_traceback_later(dump_s, repeat=True, exit=False)
    if view == "tpu":
        plats = os.environ.get("JAX_PLATFORMS")
        if plats and plats != "axon":
            import jax

            jax.config.update("jax_platforms", plats)

    async def amain() -> None:
        stats = WorkerStatsBlock.attach(stats_name)
        rings = [(ShmRing.attach(rq), ShmRing.attach(rs))
                 for rq, rs in ring_names]
        svc = MatchService(stats, rings, view=view, tpu_opts=tpu_opts)
        stats.set_service(epoch, os.getpid())
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except (NotImplementedError, RuntimeError):
                pass
        try:
            await svc.run(stop)
        finally:
            svc.close()
            for rq, rs in rings:
                rq.close()
                rs.close()
            stats.close()

    asyncio.run(amain())
