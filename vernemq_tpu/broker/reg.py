"""Registry: subscribe/unsubscribe/register ops and the publish fanout.

Mirrors ``apps/vmq_server/src/vmq_reg.erl``:

- the **reg-view seam** (``vmq_reg_view.erl:20-27``): a RegView exposes
  ``fold(topic) -> match rows``; ``TrieRegView`` (host trie) and the TPU
  engine's view are interchangeable via config ``default_reg_view``;
- ``publish``: retain set/delete first, then fold the view; per matched row
  enqueue locally, collect shared-subscription group members for policy
  selection, forward remote-node pointers to the cluster channel
  (``vmq_reg.erl:265-353``);
- RAP flag: live-routed deliveries clear the retain flag unless the v5
  retain-as-published option is set (``vmq_reg.erl:355-360``);
- ``no_local``: a subscriber never receives its own publishes on a no-local
  subscription (``vmq_reg.erl:330-341``);
- subscribe triggers retained replay per filter (``vmq_reg.erl:380-418``)
  honoring v5 retain-handling;
- shared-subscription member selection by policy with online members
  preferred (``vmq_shared_subscriptions.erl:26-63,90-106``).

Single-node in round 1: remote-node entries and the is_ready CAP gate are
wired (cluster layer fills them in), with local behavior already faithful.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
import time
from typing import TYPE_CHECKING, Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..models.trie import SubscriptionTrie
from ..protocol import fastpath
from ..protocol.topic import is_shared, unshare
from ..protocol.types import PROTO_5, SubOpts
from .message import Msg, SubscriberId, wire_batch_iovs, wire_v4_iov_qos0
from .queue import OFFLINE, ONLINE, QueueOpts, SubscriberQueue
from .subscriber_db import (SubscriberDB, SubscriberRecord, opts_from_dict,
                            opts_to_dict)

if TYPE_CHECKING:
    from .broker import Broker

log = logging.getLogger("vernemq_tpu.reg")


def _varint_len(n: int) -> int:
    """Bytes of an MQTT variable-length integer encoding ``n``."""
    if n < 128:
        return 1
    if n < 16_384:
        return 2
    if n < 2_097_152:
        return 3
    return 4


class RetainedMsg:
    """Stored retained message (#retain_msg{}, vmq_reg.erl:281-287)."""

    __slots__ = ("payload", "properties", "expiry_ts", "qos")

    def __init__(self, payload: bytes, properties: Dict[str, Any], qos: int,
                 expiry_ts: Optional[float] = None):
        self.payload = payload
        self.properties = properties
        self.qos = qos
        self.expiry_ts = expiry_ts


class TrieRegView:
    """Default reg view: fold over the host subscription trie
    (vmq_reg_trie:fold/4)."""

    name = "trie"

    def __init__(self, registry: "Registry"):
        self._registry = registry

    def fold(self, mountpoint: str, topic: Sequence[str]):
        """Yield match rows: (filter, key, subopts). Keys are SubscriberId
        for plain subs or ("$g", group, SubscriberId) for shared subs."""
        return self._registry.trie(mountpoint).match(topic)


_accel_probe_result: Optional[bool] = None


def _probe_is_risky() -> bool:
    """True when touching the JAX backend might HANG (the axon/TPU
    tunnel holds a process-wide lock through a wedged init). A local
    backend forced via env or jax.config (cpu — the test and
    --jax-platform paths) cannot hang, so the subprocess probe and its
    trie-serving window are skipped entirely and reg_view("tpu") is
    deterministic."""
    import os
    import sys as _sys

    plats = os.environ.get("JAX_PLATFORMS", "")
    jm = _sys.modules.get("jax")
    if jm is not None:
        try:
            cfg = jm.config.jax_platforms
            if cfg:
                plats = cfg
        except Exception:
            pass
    if not plats:
        return True  # default platform resolution may pick the tunnel
    return any(p.strip() in ("", "axon", "tpu")
               for p in plats.split(","))


def _probe_accelerator(timeout: float = 60.0) -> bool:
    """True iff the default JAX backend initialises and executes. Runs in
    a SUBPROCESS with a hard timeout: a wedged accelerator tunnel hangs
    backend init indefinitely and holds a process-wide lock, so an
    in-process attempt can never be abandoned (bench.py learned this the
    hard way in r1). The subprocess honours JAX_PLATFORMS via jax.config
    because this image's jax ignores the env var."""
    global _accel_probe_result
    if _accel_probe_result is not None:
        return _accel_probe_result
    import subprocess
    import sys as _sys

    code = (
        "import os, jax, numpy as np, jax.numpy as jnp\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "if p and p != 'axon':\n"
        "    jax.config.update('jax_platforms', p)\n"
        "np.asarray((jax.device_put(jnp.ones((8, 8))) + 1).sum())\n"
    )
    try:
        r = subprocess.run([_sys.executable, "-c", code],
                           capture_output=True, timeout=timeout)
        _accel_probe_result = r.returncode == 0
    except subprocess.SubprocessError:
        _accel_probe_result = False
    return _accel_probe_result



class Registry:
    def __init__(self, broker: "Broker"):
        self.broker = broker
        self.node_name = broker.node_name
        self._tries: Dict[str, SubscriptionTrie] = {}  # per-mountpoint
        # subscriber DB over the replicated metadata store
        # (vmq_subscriber_db.erl); the trie is maintained purely from its
        # change events — local writes fire them synchronously
        # (read-your-writes), remote writes arrive via metadata replication
        # (vmq_reg_trie.erl:198-210 event consumption)
        self.db = SubscriberDB(broker.metadata, broker.node_name)
        self.db.subscribe_db_events(self._on_subs_event)
        self.queues: Dict[SubscriberId, SubscriberQueue] = {}
        self.reg_views: Dict[str, Any] = {"trie": TrieRegView(self)}
        self._accel_probe_task: Optional[Any] = None
        self.fanout_fast_pubs = 0
        # remote plain subscriptions collapse to one node-pointer trie row
        # per (mountpoint, filter, node), refcounted
        # (vmq_reg_trie.erl:503-520 remote-subs handling)
        self._remote_refs: Dict[Tuple[str, Tuple[str, ...], str], int] = {}
        # remote-node fanout hooks, filled by the cluster layer:
        self.remote_publish = None  # fn(node, msg) (vmq_cluster:publish/2)
        self.remote_enqueue_nowait = None  # fn(node, sid, [msg]) shared subs

    def bootstrap(self) -> None:
        """Warm-load routing state from a persisted subscriber DB —
        STREAMING: the raw stored terms go straight to trie rows (the
        fresh-record case of the change-event diff, with no
        SubscriberRecord allocation per record and the common plain
        opts shapes interned to a handful of shared objects), and
        offline queues for persistent sessions homed here re-create
        with the lazy-recovery pattern — the stored backlog loads on
        first attach (via the ResumeCollector) or at drain. Boot cost
        is one trie add per filter plus one queue object per parked
        session, never a whole-DB object graph (the async trie
        warm-load of ``vmq_reg_trie.erl:144-149``;
        ``vmq_reg_mgr.erl:64-72``)."""
        interned: Dict[Tuple, SubOpts] = {}
        for sid, term in self.db.fold_raw():
            if term is None:
                continue
            mountpoint = sid[0]
            node = term["node"]
            for f, od in (term.get("subs") or {}).items():
                fw = tuple(f)
                if "sid" in od or "flt" in od:
                    # subscription-id / payload-filter rows keep their
                    # own opts object (the filter engine refcounts and
                    # windows per row — these must not be shared)
                    opts = opts_from_dict(od)
                else:
                    k = (od.get("qos", 0), od.get("nl", False),
                         od.get("rap", False), od.get("rh", 0))
                    opts = interned.get(k)
                    if opts is None:
                        opts = interned[k] = opts_from_dict(od)
                self._trie_add(mountpoint, fw, sid, node, opts)
            if (node == self.node_name and not term.get("clean", True)
                    and sid not in self.queues):
                queue = self._start_queue(
                    sid, _qopts_from_dict(dict(term.get("qopts") or {}),
                                          self.broker.config))
                self.broker.recover_offline(sid, queue, lazy=True)
                queue._arm_expiry()  # session/persistent expiry clock

    @property
    def subscriptions(self) -> Dict[SubscriberId, Dict[Tuple[str, ...], SubOpts]]:
        """Local-view of the subscriber DB (introspection/back-compat)."""
        return {sid: rec.subs for sid, rec in self.db.fold()}

    def trie(self, mountpoint: str = "") -> SubscriptionTrie:
        t = self._tries.get(mountpoint)
        if t is None:
            t = self._tries[mountpoint] = SubscriptionTrie()
        return t

    def reg_view(self, name: Optional[str] = None):
        name = name or self.broker.config.default_reg_view
        view = self.reg_views.get(name)
        if view is None and name == "tpu":
            global _accel_probe_result
            if _accel_probe_result is None and not _probe_is_risky():
                # a local backend (forced cpu) cannot hang: build the
                # view directly — no probe window, deterministic for
                # tests and --jax-platform runs
                _accel_probe_result = True
            if _accel_probe_result is None:
                # a wedged accelerator tunnel HANGS jax backend init
                # (holding a process-wide lock). The probe subprocess
                # itself burns its full timeout when the tunnel is
                # wedged, so it must NEVER run on the event loop (it
                # would freeze every session for the duration): kick it
                # off on an executor thread and serve the host trie
                # until the verdict is in.
                self._start_accel_probe()
                return self.reg_views["trie"]
            if _accel_probe_result is False:
                # degrade loudly to the host trie (the reg-view seam is
                # exactly the place the reference lets deployments pick
                # a view) and keep re-probing so the engine comes back
                # without a broker restart
                log.error("accelerator backend unavailable/hung; "
                          "default_reg_view=tpu falling back to the host "
                          "trie view (will re-probe)")
                self.reg_views["tpu"] = self.reg_views["trie"]
                self._arm_accel_recovery()
                self._mesh_claims_check(self.reg_views["trie"])
                return self.reg_views["trie"]
            view = self.reg_views["tpu"] = self._make_tpu_view()
            self._mesh_claims_check(view)
        if view is None:
            raise KeyError(f"unknown reg view {name!r}")
        return view

    def _mesh_claims_check(self, view) -> None:
        """The tpu view just materialized: if it is serving WITHOUT its
        mesh (tpu_mesh unsatisfiable / accel down — the documented loud
        single-chip degrade), retract this node's gossiped slice claims
        so the cluster never sees it advertising slices it cannot serve
        (boot claims happen before the lazy view exists, so this is the
        first point the truth is known)."""
        mm = getattr(self.broker, "mesh_map", None)
        if mm is None:
            return
        try:
            st = getattr(view, "mesh_status", None)
            if st is None or st() is None:
                mm.release_local()
        except Exception:
            log.exception("mesh slice-claim check failed")

    def _make_tpu_view(self):
        from ..models.tpu_matcher import TpuRegView

        cfg = self.broker.config
        return TpuRegView(
            self, max_fanout=cfg.tpu_max_fanout,
            flat_avg=cfg.tpu_flat_avg,
            use_pallas=cfg.tpu_use_pallas,
            packed_io=cfg.tpu_packed_io,
            breaker_enabled=cfg.get("tpu_breaker_enabled", True),
            breaker_failure_threshold=cfg.get(
                "tpu_breaker_failure_threshold", 3),
            breaker_backoff_initial=cfg.get(
                "tpu_breaker_backoff_initial_ms", 200) / 1e3,
            breaker_backoff_max=cfg.get(
                "tpu_breaker_backoff_max_ms", 10_000) / 1e3,
            delta_warm_max=cfg.get("tpu_delta_warm_max", 128),
            initial_capacity=cfg.tpu_initial_capacity,
            mesh=self._mesh_from_config(),
            mesh_native=bool(cfg.get("tpu_mesh_native", True)),
            watchdog=(self.broker.watchdog
                      if cfg.get("watchdog_enabled", True) else None),
            rebuild_deadline_s=cfg.get("watchdog_rebuild_deadline_s",
                                       120.0),
        )

    def _mesh_from_config(self):
        """Build the serving mesh from the ``tpu_mesh`` knob ("BxS" or
        "S"); None (single-device matcher) when unset or unsatisfiable —
        a config asking for more devices than exist degrades LOUDLY to
        the single-chip path rather than refusing to boot."""
        from ..cluster.mesh_map import parse_mesh_spec

        spec = str(self.broker.config.get("tpu_mesh", "") or "").strip()
        parsed = parse_mesh_spec(spec)
        if parsed is None:
            if spec:
                log.error("invalid tpu_mesh %r; serving on the "
                          "single-device matcher", spec)
            return None
        batch, sub = parsed
        try:
            import jax

            from ..parallel.mesh import make_mesh

            need = batch * sub
            devs = jax.devices()
            if len(devs) < need:
                log.error(
                    "tpu_mesh=%s wants %d devices but only %d present; "
                    "serving on the single-device matcher", spec, need,
                    len(devs))
                return None
            return make_mesh(devs[:need], batch=batch)
        except Exception:
            log.exception("invalid tpu_mesh %r; serving on the "
                          "single-device matcher", spec)
            return None

    def _start_accel_probe(self) -> None:
        """Run the accelerator probe off-loop, once; on the verdict the
        next reg_view("tpu") call takes the real path."""
        if self._accel_probe_task is not None:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (unit tests poking reg_view directly): probe
            # synchronously — nothing to block
            _probe_accelerator()
            return
        fut = loop.run_in_executor(None, _probe_accelerator)
        self._accel_probe_task = fut

        def _done(f) -> None:
            ok = False
            try:
                ok = bool(f.result())
            except Exception:
                pass
            if not ok:
                # force the cached verdict so reg_view takes the loud
                # fallback + recovery path on its next call
                global _accel_probe_result
                _accel_probe_result = False
            log.info("accelerator probe finished: %s",
                     "available" if ok else "unavailable")

        fut.add_done_callback(_done)

    def _arm_accel_recovery(self, interval: float = 60.0) -> None:
        """Supervised re-probe loop: when the accelerator comes back, swap
        the real TPU view in (sessions notice via batched_view_active on
        their next publish)."""
        sup = getattr(self.broker, "supervisor", None)
        if sup is None or "accel-recovery" in sup._tasks:
            return

        async def recover():
            global _accel_probe_result
            loop = asyncio.get_event_loop()
            while True:
                await asyncio.sleep(interval)
                _accel_probe_result = None  # bypass the cache
                ok = await loop.run_in_executor(None, _probe_accelerator)
                if ok:
                    self.reg_views["tpu"] = self._make_tpu_view()
                    log.warning("accelerator recovered; TPU reg view "
                                "re-enabled")
                    return

        sup.spawn("accel-recovery", recover)

    def batched_view_active(self) -> bool:
        """True when sessions should publish through the BatchCollector —
        i.e. the configured view is the TPU engine AND it actually came up
        (the accelerator-down fallback swaps in the trie view, which has
        no batch interface)."""
        if self.broker.config.default_reg_view != "tpu":
            return False
        return hasattr(self.reg_view("tpu"), "fold_batch")

    # -- session registration ---------------------------------------------

    def register_subscriber(
        self, sid: SubscriberId, clean_start: bool, queue_opts: QueueOpts
    ) -> Tuple[SubscriberQueue, bool]:
        """Create/reuse the subscriber queue; returns (queue,
        session_present) (vmq_reg:register_subscriber, vmq_reg.erl:107-140).
        Session takeover of live sessions is handled by the session layer
        before calling this. A persistent subscriber whose record points at
        another node is remapped here (maybe_remap_subscriber,
        vmq_reg.erl:676-699) — the node change event triggers queue
        migration on the old owner."""
        cfg = self.broker.config
        if not self.broker.cluster_ready() and not cfg.allow_register_during_netsplit:
            raise RuntimeError("not_ready")
        existing = self.queues.get(sid)
        rec = self.db.read(sid)
        if clean_start:
            if existing is not None or rec is not None:
                self.cleanup_subscriber(sid)
            queue = self._start_queue(sid, queue_opts)
            return queue, False
        session_present = existing is not None or rec is not None
        if rec is not None and rec.node != self.node_name:
            # remap: rewrite the record to this node; every node's trie
            # re-points, the old owner starts draining its queue to us
            rec.node = self.node_name
            rec.clean_session = queue_opts.clean_session
            rec.queue_opts = _qopts_to_dict(queue_opts)
            self.db.store(sid, rec)
        elif rec is None:
            # persist an empty record immediately: every node must learn who
            # owns this ClientId's queue even before the first SUBSCRIBE
            # (maybe_remap_subscriber stores {Node, CleanSession, []},
            # vmq_reg.erl:676-699) — this is what a concurrent register on
            # another node races against
            from .subscriber_db import SubscriberRecord

            self.db.store(sid, SubscriberRecord(
                self.node_name, queue_opts.clean_session,
                queue_opts=_qopts_to_dict(queue_opts)))
        if existing is not None:
            existing.opts = queue_opts
            return existing, session_present
        queue = self._start_queue(sid, queue_opts)
        if session_present:
            # the reconnect path: a session is attaching right now, so
            # the replay may ride the batched ResumeCollector (one
            # off-loop read per storm window) — boot/remap recovery
            # stays synchronous
            self.broker.recover_offline(sid, queue, may_defer=True)
        return queue, session_present

    async def register_subscriber_synced(
        self, sid: SubscriberId, clean_start: bool, queue_opts: QueueOpts
    ) -> Tuple[SubscriberQueue, bool]:
        """Cluster-serialized registration: the whole register (incl. the
        record remap that triggers the old owner's drain) runs holding the
        cluster-wide per-SubscriberId lock (vmq_reg.erl:115-126 running
        register_subscriber_ via vmq_reg_sync:sync). Without it, two nodes
        registering the same ClientId concurrently race on the subscriber
        record. Raises RuntimeError('not_ready') like the direct path."""
        cluster = self.broker.cluster
        if cluster is None or not self.broker.config.coordinate_registrations:
            return self.register_subscriber(sid, clean_start, queue_opts)
        return await cluster.reg_sync.sync(
            sid,
            lambda: self.register_subscriber(sid, clean_start, queue_opts))

    async def cleanup_subscriber_synced(self, sid: SubscriberId) -> None:
        """Serialized cleanup (the vmq_reg_sync 'cleanup' action): session
        expiry racing a concurrent re-register on another node must not
        delete the record the other node just claimed."""
        cluster = self.broker.cluster
        if cluster is None or not self.broker.config.coordinate_registrations:
            self.cleanup_subscriber(sid)
            return

        def _do() -> None:
            rec = self.db.read(sid)
            if rec is not None and rec.node != self.node_name:
                return  # another node owns it now; nothing to clean here
            self.cleanup_subscriber(sid)

        await cluster.reg_sync.sync(sid, _do)

    def _start_queue(self, sid: SubscriberId, opts: QueueOpts) -> SubscriberQueue:
        queue = SubscriberQueue(self.broker, sid, opts)
        self.queues[sid] = queue
        self.broker.metrics.incr("queue_setup")
        return queue

    def get_queue(self, sid: SubscriberId) -> Optional[SubscriberQueue]:
        return self.queues.get(sid)

    def queue_terminated(self, sid: SubscriberId) -> None:
        """Callback from SubscriberQueue.terminate: drop registry state for
        clean sessions."""
        q = self.queues.pop(sid, None)
        if q is not None and q.opts.clean_session:
            rec = self.db.read(sid)
            if rec is not None:
                self.db.delete(sid)

    def cleanup_subscriber(self, sid: SubscriberId) -> None:
        """Full cleanup: subscriptions + queue + offline storage
        (vmq_reg cleanup via vmq_reg_sync, and client_expired path)."""
        if self.db.read(sid) is not None:
            self.db.delete(sid)
        q = self.queues.pop(sid, None)
        if q is not None:
            q.opts.clean_session = True  # prevent re-offline
            q.terminate("cleanup")
        self.broker.delete_offline(sid)

    # -- subscriber-db change events → trie (vmq_reg_trie event consumer) --

    def _on_subs_event(self, sid: SubscriberId, old, new,
                       origin: str = "") -> None:
        """Apply a subscriber-record change to this node's routing state:
        the diff of old vs new subscriptions (vmq_subscriber:get_changes,
        vmq_subscriber.erl:54-58) becomes trie/TPU-table deltas. Local
        subscribers become direct rows; remote plain subscriptions collapse
        into per-node pointer rows; shared-subscription rows keep the full
        (group, sid) identity with the owning node in the opts
        (the reference trie's {Node, Group, SubscriberId, SubInfo} rows)."""
        mountpoint = sid[0]
        old_subs = old.subs if old is not None else {}
        new_subs = new.subs if new is not None else {}
        old_node = old.node if old is not None else None
        new_node = new.node if new is not None else None
        for fw, opts in old_subs.items():
            if fw not in new_subs or new_node != old_node:
                self._trie_remove(mountpoint, fw, sid, old_node, opts)
        for fw, opts in new_subs.items():
            prev = old_subs.get(fw)
            if prev is None or old_node != new_node:
                self._trie_add(mountpoint, fw, sid, new_node, opts)
            elif opts_to_dict(prev) != opts_to_dict(opts):
                # opts-only change: local/group rows carry opts and must be
                # replaced; remote pointer rows don't (and must not have
                # their refcount bumped)
                group, _ = unshare(list(fw))
                if group is not None or new_node == self.node_name:
                    # in-place row replace: balance the filter-engine
                    # refcount (and free the old opts' windows) before
                    # the add bumps it — a re-subscribe changing the
                    # predicate must not leak a wants() ref or inherit
                    # a dead window's accumulator
                    self._filters_delta("remove", mountpoint, prev,
                                        fw, sid)
                    self._trie_add(mountpoint, fw, sid, new_node, opts)
        # a remote node took over a persistent subscriber we hold a queue
        # for → queue migration trigger (vmq_reg_mgr.erl:155-243, task:
        # drain handled by the migration protocol)
        if (new is not None and new_node != self.node_name
                and sid in self.queues and old_node == self.node_name):
            self.broker.on_subscriber_moved(sid, new_node)
        # a persistent subscriber was remapped TO this node by someone else
        # (queue migration / fix-dead-queues): create the offline queue
        # eagerly so publishes and drain frames land in it
        # (vmq_reg_mgr:handle_new_sub_event → setup_queue). A local-origin
        # remap is the register path, which creates its own queue.
        if (new is not None and new_node == self.node_name
                and origin != self.node_name
                and old_node != self.node_name):
            self.ensure_offline_queue(sid, new)

    def ensure_offline_queue(self, sid: SubscriberId, rec) -> None:
        """Create + recover the offline queue for a persistent subscriber
        homed here, if missing (vmq_reg_mgr setup_queue — used by the
        remote-remap event path and fix-dead-queues)."""
        if (rec is None or rec.clean_session or rec.node != self.node_name
                or sid in self.queues or sid in self.broker.sessions):
            return
        queue = self._start_queue(
            sid, _qopts_from_dict(rec.queue_opts, self.broker.config))
        self.broker.recover_offline(sid, queue, lazy=True)
        queue._arm_expiry()

    def _trie_add(self, mountpoint: str, fw: Tuple[str, ...],
                  sid: SubscriberId, node: str, opts: SubOpts) -> None:
        trie = self.trie(mountpoint)
        opts.node = node  # locality for shared-sub policy + introspection
        self._filters_delta("add", mountpoint, opts)
        group, rest = unshare(list(fw))
        if group is not None:
            key = ("$g", group, sid)
            trie.add(rest, key, opts)
            self._emit_delta("add", mountpoint, rest, key, opts)
        elif node == self.node_name:
            trie.add(list(fw), sid, opts)
            self._emit_delta("add", mountpoint, list(fw), sid, opts)
        else:
            ref = (mountpoint, fw, node)
            n = self._remote_refs.get(ref, 0)
            self._remote_refs[ref] = n + 1
            if n == 0:
                trie.add(list(fw), node, None)
                self._emit_delta("add", mountpoint, list(fw), node, None)

    def _trie_remove(self, mountpoint: str, fw: Tuple[str, ...],
                     sid: SubscriberId, node: str,
                     opts: Optional[SubOpts] = None) -> None:
        trie = self.trie(mountpoint)
        self._filters_delta("remove", mountpoint, opts, fw, sid)
        group, rest = unshare(list(fw))
        if group is not None:
            key = ("$g", group, sid)
            trie.remove(rest, key)
            self._emit_delta("remove", mountpoint, rest, key, None)
        elif node == self.node_name:
            trie.remove(list(fw), sid)
            self._emit_delta("remove", mountpoint, list(fw), sid, None)
        else:
            ref = (mountpoint, fw, node)
            n = self._remote_refs.get(ref, 0) - 1
            if n <= 0:
                self._remote_refs.pop(ref, None)
                trie.remove(list(fw), node)
                self._emit_delta("remove", mountpoint, list(fw), node, None)
            else:
                self._remote_refs[ref] = n

    def node_left(self, node: str) -> None:
        """A member left: its subscriber records are rewritten by migration
        (task of the leave path); nothing to do eagerly here — CAP flags
        gate routing while the cluster is inconsistent."""

    # -- subscribe / unsubscribe ------------------------------------------

    def subscribe(
        self, sid: SubscriberId, topics: List[Tuple[List[str], SubOpts]]
    ) -> List[int]:
        """Add subscriptions; returns granted qos per topic
        (vmq_reg:subscribe → subscribe_op, vmq_reg.erl:62-99,636-653)."""
        cfg = self.broker.config
        if not self.broker.cluster_ready() and not cfg.allow_subscribe_during_netsplit:
            raise RuntimeError("not_ready")
        rec = self.db.read(sid)
        if rec is None:
            q = self.queues.get(sid)
            clean = q.opts.clean_session if q is not None else True
            rec = SubscriberRecord(self.node_name, clean)
        rec.node = self.node_name
        q = self.queues.get(sid)
        if q is not None:
            rec.queue_opts = _qopts_to_dict(q.opts)
        existed_before = {tuple(w) for w, _ in topics if tuple(w) in rec.subs}
        granted = []
        for words, opts in topics:
            rec.subs[tuple(words)] = opts
            granted.append(opts.qos)
        self.db.store(sid, rec)  # events update the trie synchronously
        for words, opts in topics:
            group, _ = unshare(list(words))
            # retained replay (vmq_reg.erl:380-418); none for shared subs
            # (MQTT5: retained messages are not sent to shared subscriptions)
            if group is None and opts.retain_handling != 2:
                if not (opts.retain_handling == 1 and tuple(words) in existed_before):
                    self._deliver_retained(sid, words, opts)
        return granted

    def _emit_delta(self, op: str, mountpoint: str, filter_words, key, opts) -> None:
        """Subscription change event → TPU table delta stream (the analog of
        vmq_reg_trie consuming subscriber-db change events; BASELINE
        config 5 trie-delta streaming)."""
        view = self.reg_views.get("tpu")
        if view is not None and hasattr(view, "on_delta"):
            # (the accelerator-down fallback aliases "tpu" to the trie
            # view, which is fed through the trie events directly)
            view.on_delta(op, mountpoint, filter_words, key, opts)

    def _filters_delta(self, op: str, mountpoint: str, opts,
                       fw=None, sid=None) -> None:
        """Subscription change → payload-filter engine refcounts (the
        wants() gate of vernemq_tpu/filters/engine.py): predicate-
        carrying subscriptions register per mountpoint so unfiltered
        traffic skips the predicate phase at one dict probe. Removes
        carry the routing-row key so the engine frees the
        subscription's aggregation windows."""
        eng = getattr(self.broker, "filter_engine", None)
        if eng is None:
            return
        key = None
        if fw is not None and sid is not None:
            group, _ = unshare(list(fw))
            key = ("$g", group, sid) if group is not None else sid
        eng.on_sub_delta(op, mountpoint, opts, key)

    def unsubscribe(self, sid: SubscriberId, topics: List[List[str]]) -> List[bool]:
        cfg = self.broker.config
        if not self.broker.cluster_ready() and not cfg.allow_unsubscribe_during_netsplit:
            raise RuntimeError("not_ready")
        rec = self.db.read(sid)
        results = []
        if rec is None:
            return [False] * len(topics)
        for words in topics:
            results.append(rec.subs.pop(tuple(words), None) is not None)
        if rec.subs:
            self.db.store(sid, rec)
        elif self.queues.get(sid) is None or rec.clean_session:
            self.db.delete(sid)
        else:
            self.db.store(sid, rec)  # persistent session keeps its record
        return results

    def _deliver_retained(self, sid: SubscriberId, filter_words: List[str], opts: SubOpts) -> None:
        """Retained replay for one new subscription (vmq_reg.erl:380-418).
        With the device retained index active the filter rides the
        replay batch collector (concurrent SUBSCRIBEs coalesce into one
        reverse-match dispatch) and enqueues when the batch resolves;
        otherwise — collector off, accelerator down, or the device path
        degraded — the exact host walk serves synchronously."""
        if self.queues.get(sid) is None:
            return
        col = self.broker.retained_collector()
        if col is not None:
            fut = col.submit(sid[0], tuple(filter_words))

            def _done(f: "asyncio.Future") -> None:
                exc = f.exception()
                if exc is not None:
                    # unexpected collector error: the replay must still
                    # happen — exact host walk, loudly
                    log.exception("retained replay batch failed; serving "
                                  "the host walk", exc_info=exc)
                    matches = self.broker.retain.match_filter(
                        sid[0], list(filter_words))
                else:
                    matches = f.result()
                self._enqueue_retained(sid, opts, matches)

            fut.add_done_callback(_done)
            return
        self._enqueue_retained(
            sid, opts,
            self.broker.retain.match_filter(sid[0], list(filter_words)))

    def _enqueue_retained(self, sid: SubscriberId, opts: SubOpts,
                          matches) -> None:
        queue = self.queues.get(sid)
        if queue is None:
            return  # session ended between subscribe and batch resolve
        now = time.time()
        # payload-filter replay seam: a predicated subscription replays
        # only passing retained messages (exact host evaluator — the
        # payload is in hand); aggregation subs get no raw replay
        eng = (self.broker.filter_engine
               if getattr(opts, "filter_expr", None) else None)
        for topic, rmsg in matches:
            if rmsg.expiry_ts is not None and rmsg.expiry_ts < now:
                continue
            if eng is not None and eng.passes_single(
                    sid[0], topic, rmsg.payload, opts) is False:
                continue
            props = dict(rmsg.properties)
            expires_at = None
            if rmsg.expiry_ts is not None:
                # MQTT5 3.3.2.3.3: the replayed message carries the
                # REMAINING expiry, not the interval it was stored with
                # (re-stamped from expires_at by the send path); the
                # stored wall-clock deadline converts to the session's
                # monotonic domain here
                expires_at = time.monotonic() + (rmsg.expiry_ts - now)
                props.pop("message_expiry_interval", None)
            msg = Msg(
                topic=topic,
                payload=rmsg.payload,
                qos=min(opts.qos, rmsg.qos),
                retain=True,
                mountpoint=sid[0],
                properties=props,
                expires_at=expires_at,
            )
            queue.enqueue(msg)

    # -- publish fanout (HOT PATH) ----------------------------------------

    def publish(
        self,
        msg: Msg,
        from_sid: Optional[SubscriberId] = None,
        reg_view: Optional[str] = None,
        trace=None,
    ) -> int:
        """Retain handling + fold + enqueue; returns number of local matches
        (used for the v5 no-matching-subscribers reason code).
        vmq_reg:publish/4 (vmq_reg.erl:265-319)."""
        msg = self._pre_publish(msg)
        name = reg_view or self.broker.config.default_reg_view
        if name == "tpu" and reg_view is None:
            # synchronous callers (systree, wills, plugins) must never run
            # the device matcher on the event loop — the host trie is
            # maintained in parallel as the source of truth and gives
            # identical results; sessions reach the tpu view via
            # publish_async/BatchCollector
            name = "trie"
        rows = self.reg_view(name).fold(msg.mountpoint, msg.topic)
        rows = self._filter_rows_host(msg, rows)
        return self.route_rows(msg, rows, from_sid, trace=trace)

    def _filter_rows_host(self, msg: Msg, rows):
        """Payload-predicate phase for the synchronous fold paths (the
        exact host evaluator; the device phase rides the collector).
        One dict probe when no predicates exist on the mountpoint."""
        eng = getattr(self.broker, "filter_engine", None)
        if eng is None or not eng.wants(msg.mountpoint):
            return rows
        feat = eng.encode(msg.mountpoint, msg.topic, msg.payload)
        return eng.filter_single(msg.mountpoint, msg.topic, feat,
                                 list(rows))

    def _filters_feat(self, msg: Msg):
        """Feature row riding the collector submit (the K-batch staging
        of the device predicate phase); None when the phase won't run."""
        eng = getattr(self.broker, "filter_engine", None)
        if eng is None or not eng.wants(msg.mountpoint):
            return None
        return eng.encode(msg.mountpoint, msg.topic, msg.payload)

    async def publish_async(
        self, msg: Msg, from_sid: Optional[SubscriberId] = None,
        trace=None,
    ) -> int:
        """Batched publish path: retain handling is synchronous (local
        read-your-writes ordering like the reference's synchronous trie
        events), then the match rides the broker's BatchCollector — many
        concurrent publishes share one device call. ``trace`` (flight
        recorder) rides the collector item into the fold envelope."""
        msg = self._pre_publish(msg)
        rows = await self.broker.batch_collector().submit(
            msg.mountpoint, msg.topic, trace, feat=self._filters_feat(msg))
        return self.route_rows(msg, rows, from_sid, trace=trace)

    def publish_nowait(self, msg: Msg,
                       from_sid: Optional[SubscriberId] = None,
                       trace=None) -> int:
        """QoS0 fast path for the batched view: submit to the collector and
        route when the batch resolves, without blocking the session reader
        on the batch window (a single publisher would otherwise get exactly
        one message per window). Retain handling stays synchronous so local
        read-your-writes ordering holds. Per-publisher delivery order is
        preserved by collector submission order. A sampled publish's
        ``trace`` finishes here, after route_rows — the record's route
        stage covers the fanout work too."""
        msg = self._pre_publish(msg)
        fut = self.broker.batch_collector().submit(
            msg.mountpoint, msg.topic, trace, feat=self._filters_feat(msg))

        def _done(f: "asyncio.Future") -> None:
            exc = f.exception()
            if exc is not None:
                self.broker.metrics.incr("mqtt_publish_error")
                return
            self.route_rows(msg, f.result(), from_sid, trace=trace)
            if trace is not None:
                trace.stamp("route")
                self.broker.recorder.finish(trace)

        fut.add_done_callback(_done)
        return 0

    def publish_wire_qos0(self, mountpoint: str,
                          words: Tuple[str, ...], topic_str: str,
                          payload: Optional[bytes],
                          from_sid: Optional[SubscriberId],
                          wire_frame: Optional[bytes] = None,
                          payload_skip: int = 0,
                          trace=None) -> int:
        """The wire-plane QoS0 publish: route straight from frame-table
        spans — no Msg, no Publish frame — for fanouts whose every
        recipient is a plain local online lone-session v4 subscriber
        with no delivery transform. Anything else (shared groups,
        remote nodes, v5 receivers, offline queues, predicates,
        QoS-upgrade) materialises ONE Msg and takes the classic
        ``route_rows`` unchanged. With the batched view active the
        match rides the collector's staging exactly like
        ``publish_nowait`` (same submission-order guarantee, same
        device/host fold seam); the trie view folds synchronously.
        The session layer pre-gates retain/dup/auth/filters, so no
        retain handling happens here. ``payload`` may be None when
        ``wire_frame`` is given — it then lives at
        ``wire_frame[payload_skip:]`` and is sliced out lazily only by
        the branches that need it."""
        if self.batched_view_active():
            fut = self.broker.batch_collector().submit(
                mountpoint, words, trace, feat=None)

            def _done(f: "asyncio.Future") -> None:
                exc = f.exception()
                if exc is not None:
                    self.broker.metrics.incr("mqtt_publish_error")
                    return
                self._wire_route(mountpoint, words, topic_str, payload,
                                 f.result(), from_sid, wire_frame,
                                 payload_skip)
                if trace is not None:
                    trace.stamp("route")
                    self.broker.recorder.finish(trace)

            fut.add_done_callback(_done)
            return 0
        n = self._wire_route(mountpoint, words, topic_str, payload,
                             self.trie(mountpoint).match(list(words)),
                             from_sid, wire_frame, payload_skip)
        if trace is not None:
            trace.stamp("route")
            self.broker.recorder.finish(trace)
        return n

    def publish_wire(self, mountpoint: str, words: Tuple[str, ...],
                     topic_str: str, payload: bytes,
                     from_sid: Optional[SubscriberId], qos: int,
                     trace=None) -> int:
        """The wire-plane QoS1/2 publish: like
        :meth:`publish_wire_qos0` but the fanout stamps each QoS≥1
        recipient's packet id into its in-flight window and
        batch-encodes all recipients' headers in ONE native call
        (``fastpath.publish_headers_batch``). Synchronous only — the
        session needs the match count for the PUBACK/PUBREC reason
        code, so callers pre-gate ``batched_view_active()`` and keep
        the classic async path there."""
        n = self._wire_route(mountpoint, words, topic_str, payload,
                             self.trie(mountpoint).match(list(words)),
                             from_sid, qos=qos)
        if trace is not None:
            trace.stamp("route")
            self.broker.recorder.finish(trace)
        return n

    def _wire_route(self, mountpoint: str, words: Tuple[str, ...],
                    topic_str: str, payload: Optional[bytes], rows,
                    from_sid: Optional[SubscriberId],
                    wire_frame: Optional[bytes] = None,
                    payload_skip: int = 0, qos: int = 0) -> int:
        """Classify the fold result: if EVERY matched row is the plain
        fast shape, write the shared wire bytes to each recipient's
        transport (verbatim inbound span for v4 QoS0 publishers, one
        shared native-encoded header, or one batched per-recipient
        header arena for pid/alias-bearing groups — always with the
        shared payload riding the iovec uncopied) — the object-free
        half of the wire plane. One complex row routes the whole
        fanout through the classic Msg path for exact semantics.

        Fast rows now include v5 recipients (alias-aware headers from
        the per-connection LRU via ``wire_alias_for``) and QoS≥1
        deliveries (in-flight bookkeeping via ``wire_take_qos``); a
        qos-downgrade row (subscription qos below the publish qos but
        above 0) builds its own shared Msg per effective qos."""
        rows = list(rows)
        cfg = self.broker.config
        upgrade = cfg.upgrade_outgoing_qos
        recips: List[Tuple[Any, int]] = []
        fast = True
        frame_bound = 0
        for _f, key, opts in rows:
            if not (isinstance(key, tuple) and len(key) == 2):
                fast = False  # $g group row or remote node pointer
                break
            if opts.no_local and key == from_sid:
                continue
            if (getattr(opts, "filter_expr", None)
                    or getattr(opts, "subscription_id", None)
                    or (upgrade and opts.qos > 0)):
                fast = False
                break
            q = self.queues.get(key)
            if q is None:
                continue
            if q.state is not ONLINE or len(q.sessions) != 1:
                fast = False  # offline backlog / multi-session queue
                break
            sess = next(iter(q.sessions))
            # getattr defaults: non-Session consumers (bridge
            # endpoints) classify complex, same as the classic fan0
            # collection
            if getattr(sess, "closed", True):
                fast = False
                break
            if getattr(sess, "proto_ver", 0) == PROTO_5:
                ok5 = getattr(sess, "wire_v5_fast_ok", None)
                if ok5 is None:
                    fast = False
                    break
                if frame_bound == 0:
                    # conservative worst-case v5 frame size, computed
                    # once per fanout: full topic (no alias), pid,
                    # prop-len byte, and a 3-byte topic-alias property
                    # — every batch-encoded variant is <= this, so a
                    # cap check against it can never pass an oversize
                    # frame (MQTT-3.1.2-24: exceeding the client's
                    # maximum_packet_size is a protocol error)
                    plen = (len(payload) if payload is not None
                            else len(wire_frame) - payload_skip)
                    body = 2 + len(topic_str.encode("utf-8")) \
                        + 2 + 1 + 3 + plen
                    frame_bound = 1 + _varint_len(body) + body
                if not ok5(frame_bound):
                    fast = False  # frame may exceed the session's cap
                    break
            recips.append((sess, min(opts.qos, qos)))
        if fast:
            if recips:
                self._wire_fanout(mountpoint, words, topic_str, payload,
                                  wire_frame, payload_skip, recips)
            return len(recips)
        # complex fanout: ONE Msg, the exact classic path (host
        # predicate phase included — a racing filter subscription must
        # still filter). The payload materialises HERE, lazily, when
        # the fast fanout didn't need it as separate bytes.
        if payload is None:
            payload = wire_frame[payload_skip:]
        msg = Msg(topic=tuple(words), payload=payload, qos=qos,
                  mountpoint=mountpoint)
        return self.route_rows(msg, self._filter_rows_host(msg, rows),
                               from_sid)

    def _wire_fanout(self, mountpoint: str, words: Tuple[str, ...],
                     topic_str: str, payload: Optional[bytes],
                     wire_frame: Optional[bytes], payload_skip: int,
                     recips: List[Tuple[Any, int]]) -> None:
        """The object-free fast fanout write. Recipients group by
        (effective qos, protocol):

        - v4 effective-QoS0 recipients share ONE frame — the verbatim
          inbound span when the publisher gave us one, else one
          encoded header + payload iovec;
        - every pid- or alias-bearing group (QoS≥1 and/or v5) encodes
          ALL its per-recipient headers in ONE
          ``fastpath.publish_headers_batch`` call and writes
          memoryview slices of the arena, the shared payload riding
          each iovec uncopied;
        - QoS≥1 recipients register the (lazily built, shared) Msg in
          their in-flight window first (``wire_take_qos``); a full
          window parks the Msg in pending exactly like the classic
          deliver path — no wire write now, the ack-driven pump owns
          it."""
        m = self.broker.metrics
        t0 = time.monotonic()
        nbytes = 0
        sent = 0
        parked = 0
        v4_plain: List[Any] = []
        groups: Dict[Tuple[int, bool], List[Tuple[Any, Optional[int],
                                                  Optional[int]]]] = {}
        msg_by_eff: Dict[int, Msg] = {}
        for sess, eff in recips:
            is5 = getattr(sess, "proto_ver", 0) == PROTO_5
            if eff == 0:
                if not is5:
                    v4_plain.append(sess)
                else:
                    alias = sess.wire_alias_for(words)
                    groups.setdefault((0, True), []).append(
                        (sess, None, alias))
                continue
            msg = msg_by_eff.get(eff)
            if msg is None:
                if payload is None:
                    payload = wire_frame[payload_skip:]
                msg = Msg(topic=tuple(words), payload=payload, qos=eff,
                          mountpoint=mountpoint)
                msg_by_eff[eff] = msg
            pid = sess.wire_take_qos(msg)
            if not pid:
                if pid == 0:
                    parked += 1  # window full: pending pump owns it
                continue  # None: dropped (counted by wire_take_qos)
            if is5:
                alias = sess.wire_alias_for(words)
                groups.setdefault((eff, True), []).append(
                    (sess, pid, alias))
            else:
                groups.setdefault((eff, False), []).append(
                    (sess, pid, None))
        if v4_plain:
            if wire_frame is not None:
                fb = len(wire_frame)
                for sess in v4_plain:
                    sess.transport.write(wire_frame)
            else:
                hdr = fastpath.publish_header(
                    topic_str, 0, False, False, None, len(payload))
                iov = (hdr, payload)
                fb = len(hdr) + len(payload)
                for sess in v4_plain:
                    sess.transport.write_iov(iov)
            nbytes += fb * len(v4_plain)
            sent += len(v4_plain)
        if groups and payload is None:
            payload = wire_frame[payload_skip:]
        for (eff, is5), members in groups.items():
            pids = [p for _s, p, _a in members]
            aliases = [a for _s, _p, a in members] if is5 else None
            arena, offs = fastpath.publish_headers_batch(
                topic_str, eff, False, False, pids, len(payload),
                is5, aliases)
            fastpath.fanout_batches += 1
            plen = len(payload)
            for i, iov in enumerate(wire_batch_iovs(arena, offs,
                                                    payload)):
                members[i][0].transport.write_iov(iov)
                nbytes += (offs[i + 1] - offs[i]) + plen
            sent += len(members)
        if sent or parked:
            m.observe("stage_wire_encode_ms",
                      (time.monotonic() - t0) * 1e3)
            self.fanout_fast_pubs += 1
            m.incr("queue_message_in", sent + parked)
            m.incr("queue_message_out", sent)
            if nbytes:
                m.incr("bytes_sent", nbytes)
            m.incr("mqtt_publish_sent", sent)
            m.incr("router_matches_local", len(recips))

    def _pre_publish(self, msg: Msg) -> Msg:
        cfg = self.broker.config
        if not self.broker.cluster_ready() and not cfg.allow_publish_during_netsplit:
            raise RuntimeError("not_ready")
        if msg.retain:
            if not msg.payload:
                self.broker.retain.delete(msg.mountpoint, msg.topic)
                msg = msg_with_retain(msg, False)
            else:
                self.broker.retain.insert(
                    msg.mountpoint,
                    msg.topic,
                    RetainedMsg(
                        msg.payload,
                        dict(msg.properties),
                        msg.qos,
                        expiry_ts=_retain_expiry(msg),
                    ),
                )
                self.broker.metrics.incr("retain_messages_stored")
        return msg

    def route_rows(
        self,
        msg: Msg,
        rows: Iterable[Tuple[Tuple[str, ...], Any, SubOpts]],
        from_sid: Optional[SubscriberId],
        origin_local: bool = True,
        trace=None,
    ) -> int:
        """The fold body (vmq_reg:publish/3 fold fun, vmq_reg.erl:326-353):
        local rows enqueue, shared rows collect into groups, node rows
        forward. Shared groups then go through policy selection.
        ``origin_local=False`` (publish arriving over the cluster channel)
        serves local plain rows only — node and group rows were already
        covered by the origin node (vmq_cluster_com.erl:198-203).
        ``trace`` (a sampled publish's flight-recorder context) rides
        node-row forwards onto the cluster envelope so the receiving
        node resumes it (one cross-node Perfetto trace)."""
        matches = 0
        groups: Dict[str, List[Tuple[SubscriberId, SubOpts]]] = {}
        forwarded_nodes = set()  # one msg frame per remote node per publish
        # batched QoS0 fanout (the host hot path): recipients whose
        # delivery needs NO per-subscription transform and whose session
        # is a lone online v4 connection all receive the SAME wire
        # frame — collect them and write it once per socket, with
        # per-publish (not per-delivery) metric accounting. Everything
        # else takes the queue path unchanged.
        fan0: Optional[List[Any]] = \
            [] if (msg.qos == 0 and msg.expires_at is None
                   and self.broker.tracer is None) else None
        for _filter, key, opts in rows:
            if isinstance(key, tuple) and len(key) == 3 and key[0] == "$g":
                if not origin_local:
                    continue
                _, group, sid = key
                if opts.no_local and sid == from_sid:
                    continue
                groups.setdefault(group, []).append((sid, opts))
                continue
            if isinstance(key, str):  # remote node pointer
                if origin_local and key not in forwarded_nodes:
                    # overlapping filters yield multiple pointer rows to the
                    # same node; the receiving node re-folds its own view, so
                    # exactly one frame goes out (vmq_reg.erl:346-353).
                    # The forward QoS-splits at the cluster layer: QoS 0
                    # stays fire-and-forget (sheddable), QoS >= 1 rides
                    # the durable spool (cluster/spool.py) when the peer
                    # supports it — False back means dropped, visibly.
                    forwarded_nodes.add(key)
                    if self.remote_publish is not None:
                        # keyword only when a trace rides along: test
                        # stubs and older embeddings keep their 2-arg
                        # remote_publish signature
                        ok = (self.remote_publish(key, msg, trace=trace)
                              if trace is not None
                              else self.remote_publish(key, msg))
                        if ok:
                            self.broker.metrics.incr("router_matches_remote")
                        else:
                            self.broker.metrics.incr("cluster_publish_drop")
                    else:
                        # cluster channel stopped/detached: the forward is
                        # dropped VISIBLY (same counter as a down writer)
                        self.broker.metrics.incr("cluster_publish_no_channel")
                continue
            sid = key
            if opts.no_local and sid == from_sid:
                continue
            if self._enqueue_to(sid, msg, opts, fan0):
                matches += 1
        if fan0:
            self._fanout_qos0(msg, fan0)
        for group, members in groups.items():
            if self._publish_shared(msg, members):
                matches += 1
        if matches:
            self.broker.metrics.incr("router_matches_local", matches)
        return matches

    def publish_from_remote(self, msg: Msg, trace=None) -> int:
        """Entry for ``msg`` frames from the cluster channel: fold the local
        view, local subscribers only (vmq_cluster_com.erl:153-157).

        This is a flight-recorder ADMISSION point: a cluster-ingress
        publish without a propagated context competes in the same
        1-in-N sample count as local publishes (the recorder used to be
        blind to remote traffic — the one admission decision lived only
        in ``session._handle_publish``). A ``trace`` resumed from the
        origin node's envelope context takes precedence: its sample
        decision was already made at the origin, and the finished
        record carries both nodes' stamps."""
        if trace is None:
            trace = self.broker.recorder.admit(
                "(cluster)", "/".join(msg.topic), msg.qos)
            if trace is not None:
                trace.stamp("remote_recv")
        rows = self.reg_view("trie").fold(msg.mountpoint, msg.topic)
        rows = self._filter_rows_host(msg, rows)
        n = self.route_rows(msg, rows, None, origin_local=False)
        if trace is not None:
            trace.stamp("route")
            self.broker.recorder.finish(trace)
        return n

    def enqueue_remote(self, sid: SubscriberId, msgs: List[Msg],
                       migrate: bool = False) -> bool:
        """Entry for ``enq`` frames (remote shared-sub delivery and queue
        migration drain): enqueue into the local queue
        (vmq_cluster_com.erl:160-196). With ``migrate`` the sender is
        the record owner running a coordinated handoff: the drain lands
        BEFORE the fence repoints the record, so accept the queue even
        though the record still names the old owner."""
        queue = self.queues.get(sid)
        if queue is None:
            rec = self.db.read(sid)
            if rec is None:
                return False
            if rec.node != self.node_name and not (
                    migrate and not rec.clean_session):
                return False
            queue = self._start_queue(sid, QueueOpts(
                clean_session=rec.clean_session))
        for m in msgs:
            queue.enqueue(m)
        return True

    def _prep_out(self, msg: Msg, opts: SubOpts) -> Msg:
        """Per-subscription delivery transform: RAP flag, outgoing QoS
        (upgrade_outgoing_qos), subscription identifier — applied the same
        whether the member is local or remote."""
        out = msg if opts.rap else msg_with_retain(msg, False)
        qos = opts.qos if self.broker.config.upgrade_outgoing_qos else min(opts.qos, msg.qos)
        out = out.with_qos(qos)
        return _maybe_add_sub_id(out, opts)

    def _enqueue_to(self, sid: SubscriberId, msg: Msg, opts: SubOpts,
                    fan0: Optional[List[Any]] = None) -> bool:
        queue = self.queues.get(sid)
        if queue is None:
            return False
        out = self._prep_out(msg, opts)
        if fan0 is not None and out is msg and queue.state is ONLINE \
                and len(queue.sessions) == 1:
            # out IS msg → no rap/qos/sub-id transform applied, so this
            # recipient gets the identical wire frame; a lone online v4
            # session takes the shared-frame write in _fanout_qos0
            sess = next(iter(queue.sessions))
            if (not getattr(sess, "closed", True)
                    and getattr(sess, "proto_ver", PROTO_5) != PROTO_5):
                fan0.append(sess)
                return True
        queue.enqueue(out)
        return True

    def _fanout_qos0(self, msg: Msg, sessions: List[Any]) -> None:
        """Shared-frame QoS0 fanout: one serialisation, one buffered
        socket write per recipient, metric increments once per PUBLISH
        instead of 4x per delivery (the dominant cost of the Python
        delivery path at fanout — profiled at 36%). Semantics match the
        queue path exactly for the collected class of recipients
        (online, lone session, v4, no transform, no tracing)."""
        t0 = time.monotonic()
        iov = wire_v4_iov_qos0(msg)
        nbytes = sum(len(c) for c in iov)
        handlers = self.broker.hooks.handlers("on_deliver")
        delivered = 0
        for sess in sessions:
            if sess.closed:  # closed between collect and write
                q = self.queues.get(sess.sid)
                if q is not None:
                    q.enqueue(msg)
                continue
            for fn in handlers:  # prefetched once per publish
                try:
                    res = fn(sess.username, sess.sid, msg.topic,
                             msg.payload)
                    if asyncio.iscoroutine(res):
                        # async hooks schedule, same as hooks_fire_all
                        asyncio.ensure_future(res)
                except Exception:
                    log.exception("on_deliver hook failed")
            sess.transport.write_iov(iov)
            delivered += 1
        if delivered:
            self.broker.metrics.observe(
                "stage_wire_encode_ms", (time.monotonic() - t0) * 1e3)
            self.fanout_fast_pubs += 1
            m = self.broker.metrics
            m.incr("queue_message_in", delivered)
            m.incr("queue_message_out", delivered)
            m.incr("bytes_sent", delivered * nbytes)
            m.incr("mqtt_publish_sent", delivered)

    def _publish_shared(
        self, msg: Msg, members: List[Tuple[SubscriberId, SubOpts]]
    ) -> bool:
        """Pick one group member by policy, online members first
        (vmq_shared_subscriptions.erl:26-63,90-106): ``prefer_local`` tries
        local members before remote ones, ``local_only`` never leaves the
        node, ``random`` mixes both. Remote member delivery rides the
        cluster ``enq`` channel (vmq_shared_subscriptions.erl:86-88)."""
        policy = self.broker.config.shared_subscription_policy
        local, remote = [], []
        for sid, opts in members:
            node = getattr(opts, "node", self.node_name)
            (local if node == self.node_name else remote).append((sid, opts, node))
        random.shuffle(local)
        random.shuffle(remote)
        local_online = [m for m in local
                        if (q := self.queues.get(m[0])) is not None
                        and q.state == ONLINE]
        if policy == "local_only":
            candidates = local_online + [m for m in local if m not in local_online]
        elif policy == "random":
            mixed = local_online + remote
            random.shuffle(mixed)
            candidates = mixed + [m for m in local if m not in local_online]
        else:  # prefer_local
            candidates = (local_online + remote
                          + [m for m in local if m not in local_online])
        for sid, opts, node in candidates:
            if node == self.node_name:
                if self._enqueue_to(sid, msg, opts):
                    return True
            elif self.remote_enqueue_nowait is not None:
                if self.remote_enqueue_nowait(node, sid, [self._prep_out(msg, opts)]):
                    self.broker.metrics.incr("router_matches_remote")
                    return True
        return False

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, float]:
        total = sum(len(t) for t in self._tries.values())
        mem = sum(t.stats()["memory"] for t in self._tries.values())
        out = {
            "router_subscriptions": total,
            "router_memory": mem,
            "queue_processes": len(self.queues),
            # publishes whose whole local fanout took the shared-frame
            # QoS0 fast path (vs the per-recipient queue path)
            "router_fanout_fast_pubs": self.fanout_fast_pubs,
        }
        # device-matcher gauges when the TPU reg view is live (the
        # router_subscriptions/router_memory pair extended with the HBM
        # table's health — fallbacks rising means fanouts exceed
        # tpu_max_fanout and the exact host path is absorbing them)
        tpu = self.reg_views.get("tpu")
        if tpu is not None:
            for mp, m in getattr(tpu, "_matchers", {}).items():
                ts = m.table.stats()
                out["tpu_table_rows"] = out.get("tpu_table_rows", 0) + \
                    ts["subscriptions"]
                out["tpu_table_bytes"] = out.get("tpu_table_bytes", 0) + \
                    ts["table_bytes"]
                out["tpu_match_batches"] = out.get("tpu_match_batches", 0) \
                    + m.match_batches
                out["tpu_match_publishes"] = \
                    out.get("tpu_match_publishes", 0) + m.match_publishes
                out["tpu_host_fallbacks"] = \
                    out.get("tpu_host_fallbacks", 0) + m.host_fallbacks
                out["tpu_warmup_batches"] = \
                    out.get("tpu_warmup_batches", 0) + m.warmup_batches
                out["tpu_async_rebuilds"] = \
                    out.get("tpu_async_rebuilds", 0) + m.rebuilds_async
                out["tpu_device_failures"] = \
                    out.get("tpu_device_failures", 0) + m.device_failures
                out["tpu_degraded_sheds"] = \
                    out.get("tpu_degraded_sheds", 0) + m.degraded_sheds
                out["tpu_delta_shapes_warmed"] = \
                    out.get("tpu_delta_shapes_warmed", 0) \
                    + m.delta_shapes_warmed
                # stall-watchdog fallout (abandoned dispatches fed to
                # the breaker, wedged rebuilds reaped)
                out["tpu_dispatch_stalls"] = \
                    out.get("tpu_dispatch_stalls", 0) + m.dispatch_stalls
                out["tpu_rebuild_abandons"] = \
                    out.get("tpu_rebuild_abandons", 0) + m.rebuild_abandons
                br = getattr(m, "breaker", None)
                if br is not None:
                    # state: worst across mountpoints (0 closed, 1
                    # half-open, 2 open) — any open matcher means the
                    # node is in degraded matching mode
                    out["tpu_breaker_state"] = max(
                        out.get("tpu_breaker_state", 0), br.state)
                    out["tpu_breaker_opens"] = \
                        out.get("tpu_breaker_opens", 0) + br.opens
                    out["tpu_breaker_closes"] = \
                        out.get("tpu_breaker_closes", 0) + br.closes
                    out["tpu_breaker_time_degraded_seconds"] = round(
                        out.get("tpu_breaker_time_degraded_seconds", 0.0)
                        + br.time_degraded(), 3)
        col = getattr(self.broker, "_collector", None)
        if col is not None:
            # small flushes served host-side by hybrid dispatch
            out["tpu_hybrid_host_pubs"] = col.host_hybrid_pubs
            out["tpu_overload_shed_pubs"] = col.overload_host_pubs
            out["tpu_saturated_merges"] = col.saturated_merges
            # pubs the trie served while the device table rebuilt
            out["tpu_rebuild_shed_pubs"] = col.rebuild_host_pubs
            # pubs the trie served past the matcher-lock busy bound
            out["tpu_busy_shed_pubs"] = col.busy_host_pubs
            # pubs the trie served while the device breaker was open
            out["tpu_degraded_host_pubs"] = col.degraded_host_pubs
            # pubs the trie served after a dispatch-deadline abandon /
            # past their queued-item expiry (stall watchdog bounds)
            out["tpu_stalled_host_pubs"] = col.stalled_host_pubs
            out["tpu_expired_host_pubs"] = col.expired_host_pubs
        # deterministic fault-injection harness (robustness/faults.py)
        from ..robustness import faults as _faults

        out.update(_faults.stats())
        # wire plane (protocol/fastpath.py): native-vs-pure batch split,
        # codec breaker state, object-free admissions
        out.update(fastpath.stats())
        return out

    def fold_subscriptions(self, mountpoint: str = ""):
        """Iterate every (filter, key, opts) — warm-load feed for the TPU
        table (mirrors vmq_reg:fold_subscriptions, vmq_reg_trie warm load)."""
        return self.trie(mountpoint).entries()


def _qopts_to_dict(opts: "QueueOpts") -> Dict[str, Any]:
    """Durable queue parameters carried in the subscriber record so boot
    re-creation keeps them (session expiry above all — MQTT5 semantics)."""
    return {
        "session_expiry": opts.session_expiry,
        "max_offline_messages": opts.max_offline_messages,
        "max_online_messages": opts.max_online_messages,
        "queue_type": opts.queue_type,
        "deliver_mode": opts.deliver_mode,
    }


def _qopts_from_dict(d: Dict[str, Any], config) -> "QueueOpts":
    from .queue import QueueOpts

    return QueueOpts(
        clean_session=False,
        session_expiry=d.get("session_expiry", 0),
        max_offline_messages=d.get("max_offline_messages",
                                   config.max_offline_messages),
        max_online_messages=d.get("max_online_messages",
                                  config.max_online_messages),
        queue_type=d.get("queue_type", config.queue_type),
        deliver_mode=d.get("deliver_mode", config.queue_deliver_mode),
    )


def msg_with_retain(msg: Msg, retain: bool) -> Msg:
    if msg.retain == retain:
        return msg
    return dataclasses.replace(msg, retain=retain)


def _maybe_add_sub_id(msg: Msg, opts: SubOpts) -> Msg:
    sub_id = getattr(opts, "subscription_id", None)
    if not sub_id:
        return msg
    props = dict(msg.properties)
    props.setdefault("subscription_identifier", []).append(sub_id)
    return dataclasses.replace(msg, properties=props)


def _retain_expiry(msg: Msg) -> Optional[float]:
    interval = msg.properties.get("message_expiry_interval")
    if interval:
        return time.time() + interval
    return None
