"""Registry: subscribe/unsubscribe/register ops and the publish fanout.

Mirrors ``apps/vmq_server/src/vmq_reg.erl``:

- the **reg-view seam** (``vmq_reg_view.erl:20-27``): a RegView exposes
  ``fold(topic) -> match rows``; ``TrieRegView`` (host trie) and the TPU
  engine's view are interchangeable via config ``default_reg_view``;
- ``publish``: retain set/delete first, then fold the view; per matched row
  enqueue locally, collect shared-subscription group members for policy
  selection, forward remote-node pointers to the cluster channel
  (``vmq_reg.erl:265-353``);
- RAP flag: live-routed deliveries clear the retain flag unless the v5
  retain-as-published option is set (``vmq_reg.erl:355-360``);
- ``no_local``: a subscriber never receives its own publishes on a no-local
  subscription (``vmq_reg.erl:330-341``);
- subscribe triggers retained replay per filter (``vmq_reg.erl:380-418``)
  honoring v5 retain-handling;
- shared-subscription member selection by policy with online members
  preferred (``vmq_shared_subscriptions.erl:26-63,90-106``).

Single-node in round 1: remote-node entries and the is_ready CAP gate are
wired (cluster layer fills them in), with local behavior already faithful.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import time
from typing import TYPE_CHECKING, Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..models.trie import SubscriptionTrie
from ..protocol.topic import is_shared, unshare
from ..protocol.types import SubOpts
from .message import Msg, SubscriberId
from .queue import OFFLINE, ONLINE, QueueOpts, SubscriberQueue

if TYPE_CHECKING:
    from .broker import Broker


class RetainedMsg:
    """Stored retained message (#retain_msg{}, vmq_reg.erl:281-287)."""

    __slots__ = ("payload", "properties", "expiry_ts", "qos")

    def __init__(self, payload: bytes, properties: Dict[str, Any], qos: int,
                 expiry_ts: Optional[float] = None):
        self.payload = payload
        self.properties = properties
        self.qos = qos
        self.expiry_ts = expiry_ts


class TrieRegView:
    """Default reg view: fold over the host subscription trie
    (vmq_reg_trie:fold/4)."""

    name = "trie"

    def __init__(self, registry: "Registry"):
        self._registry = registry

    def fold(self, mountpoint: str, topic: Sequence[str]):
        """Yield match rows: (filter, key, subopts). Keys are SubscriberId
        for plain subs or ("$g", group, SubscriberId) for shared subs."""
        return self._registry.trie(mountpoint).match(topic)


class Registry:
    def __init__(self, broker: "Broker"):
        self.broker = broker
        self._tries: Dict[str, SubscriptionTrie] = {}  # per-mountpoint
        # subscriber DB: sid -> {filter_words_tuple: SubOpts}
        # (vmq_subscriber_db over metadata; local dict in round 1)
        self.subscriptions: Dict[SubscriberId, Dict[Tuple[str, ...], SubOpts]] = {}
        self.queues: Dict[SubscriberId, SubscriberQueue] = {}
        self.reg_views: Dict[str, Any] = {"trie": TrieRegView(self)}
        # remote-node fanout hook, filled by the cluster layer:
        # fn(node, msg) -> None (vmq_cluster:publish/2)
        self.remote_publish = None

    def trie(self, mountpoint: str = "") -> SubscriptionTrie:
        t = self._tries.get(mountpoint)
        if t is None:
            t = self._tries[mountpoint] = SubscriptionTrie()
        return t

    def reg_view(self, name: Optional[str] = None):
        name = name or self.broker.config.default_reg_view
        view = self.reg_views.get(name)
        if view is None and name == "tpu":
            from ..models.tpu_matcher import TpuRegView

            view = self.reg_views["tpu"] = TpuRegView(
                self, max_fanout=self.broker.config.tpu_max_fanout
            )
        if view is None:
            raise KeyError(f"unknown reg view {name!r}")
        return view

    # -- session registration ---------------------------------------------

    def register_subscriber(
        self, sid: SubscriberId, clean_start: bool, queue_opts: QueueOpts
    ) -> Tuple[SubscriberQueue, bool]:
        """Create/reuse the subscriber queue; returns (queue,
        session_present) (vmq_reg:register_subscriber, vmq_reg.erl:107-140).
        Session takeover of live sessions is handled by the session layer
        before calling this."""
        existing = self.queues.get(sid)
        if clean_start:
            if existing is not None:
                self.cleanup_subscriber(sid)
            queue = self._start_queue(sid, queue_opts)
            return queue, False
        session_present = existing is not None or sid in self.subscriptions
        if existing is not None:
            existing.opts = queue_opts
            return existing, session_present
        queue = self._start_queue(sid, queue_opts)
        if session_present:
            self.broker.recover_offline(sid, queue)
        return queue, session_present

    def _start_queue(self, sid: SubscriberId, opts: QueueOpts) -> SubscriberQueue:
        queue = SubscriberQueue(self.broker, sid, opts)
        self.queues[sid] = queue
        self.broker.metrics.incr("queue_setup")
        return queue

    def get_queue(self, sid: SubscriberId) -> Optional[SubscriberQueue]:
        return self.queues.get(sid)

    def queue_terminated(self, sid: SubscriberId) -> None:
        """Callback from SubscriberQueue.terminate: drop registry state for
        clean sessions."""
        q = self.queues.pop(sid, None)
        if q is not None and q.opts.clean_session:
            self._remove_all_subscriptions(sid)

    def cleanup_subscriber(self, sid: SubscriberId) -> None:
        """Full cleanup: subscriptions + queue + offline storage
        (vmq_reg cleanup via vmq_reg_sync, and client_expired path)."""
        self._remove_all_subscriptions(sid)
        q = self.queues.pop(sid, None)
        if q is not None:
            q.opts.clean_session = True  # prevent re-offline
            q.terminate("cleanup")
        self.broker.delete_offline(sid)

    def _remove_all_subscriptions(self, sid: SubscriberId) -> None:
        subs = self.subscriptions.pop(sid, None)
        if not subs:
            return
        trie = self.trie(sid[0])
        for filter_words in subs:
            group, rest = unshare(list(filter_words))
            if group is None:
                trie.remove(filter_words, sid)
                self._emit_delta("remove", sid[0], filter_words, sid, None)
            else:
                trie.remove(rest, ("$g", group, sid))
                self._emit_delta("remove", sid[0], rest, ("$g", group, sid), None)

    # -- subscribe / unsubscribe ------------------------------------------

    def subscribe(
        self, sid: SubscriberId, topics: List[Tuple[List[str], SubOpts]]
    ) -> List[int]:
        """Add subscriptions; returns granted qos per topic
        (vmq_reg:subscribe → subscribe_op, vmq_reg.erl:62-99,636-653)."""
        mountpoint = sid[0]
        trie = self.trie(mountpoint)
        subs = self.subscriptions.setdefault(sid, {})
        granted = []
        for words, opts in topics:
            key = tuple(words)
            existed = key in subs
            subs[key] = opts
            group, rest = unshare(list(words))
            if group is None:
                trie.add(words, sid, opts)
                self._emit_delta("add", sid[0], words, sid, opts)
            else:
                trie.add(rest, ("$g", group, sid), opts)
                self._emit_delta("add", sid[0], rest, ("$g", group, sid), opts)
            granted.append(opts.qos)
            # retained replay (vmq_reg.erl:380-418); none for shared subs
            # (MQTT5: retained messages are not sent to shared subscriptions)
            if group is None and opts.retain_handling != 2:
                if not (opts.retain_handling == 1 and existed):
                    self._deliver_retained(sid, words, opts)
        return granted

    def _emit_delta(self, op: str, mountpoint: str, filter_words, key, opts) -> None:
        """Subscription change event → TPU table delta stream (the analog of
        vmq_reg_trie consuming subscriber-db change events; BASELINE
        config 5 trie-delta streaming)."""
        view = self.reg_views.get("tpu")
        if view is not None:
            view.on_delta(op, mountpoint, filter_words, key, opts)

    def unsubscribe(self, sid: SubscriberId, topics: List[List[str]]) -> List[bool]:
        mountpoint = sid[0]
        trie = self.trie(mountpoint)
        subs = self.subscriptions.get(sid, {})
        results = []
        for words in topics:
            key = tuple(words)
            existed = subs.pop(key, None) is not None
            group, rest = unshare(list(words))
            if group is None:
                trie.remove(words, sid)
                self._emit_delta("remove", mountpoint, words, sid, None)
            else:
                trie.remove(rest, ("$g", group, sid))
                self._emit_delta("remove", mountpoint, rest, ("$g", group, sid), None)
            results.append(existed)
        if not subs:
            self.subscriptions.pop(sid, None)
        return results

    def _deliver_retained(self, sid: SubscriberId, filter_words: List[str], opts: SubOpts) -> None:
        queue = self.queues.get(sid)
        if queue is None:
            return
        now = time.time()
        for topic, rmsg in self.broker.retain.match_filter(sid[0], filter_words):
            if rmsg.expiry_ts is not None and rmsg.expiry_ts < now:
                continue
            msg = Msg(
                topic=topic,
                payload=rmsg.payload,
                qos=min(opts.qos, rmsg.qos),
                retain=True,
                mountpoint=sid[0],
                properties=dict(rmsg.properties),
            )
            queue.enqueue(msg)

    # -- publish fanout (HOT PATH) ----------------------------------------

    def publish(
        self,
        msg: Msg,
        from_sid: Optional[SubscriberId] = None,
        reg_view: Optional[str] = None,
    ) -> int:
        """Retain handling + fold + enqueue; returns number of local matches
        (used for the v5 no-matching-subscribers reason code).
        vmq_reg:publish/4 (vmq_reg.erl:265-319)."""
        msg = self._pre_publish(msg)
        name = reg_view or self.broker.config.default_reg_view
        if name == "tpu" and reg_view is None:
            # synchronous callers (systree, wills, plugins) must never run
            # the device matcher on the event loop — the host trie is
            # maintained in parallel as the source of truth and gives
            # identical results; sessions reach the tpu view via
            # publish_async/BatchCollector
            name = "trie"
        rows = self.reg_view(name).fold(msg.mountpoint, msg.topic)
        return self.route_rows(msg, rows, from_sid)

    async def publish_async(
        self, msg: Msg, from_sid: Optional[SubscriberId] = None
    ) -> int:
        """Batched publish path: retain handling is synchronous (local
        read-your-writes ordering like the reference's synchronous trie
        events), then the match rides the broker's BatchCollector — many
        concurrent publishes share one device call."""
        msg = self._pre_publish(msg)
        rows = await self.broker.batch_collector().submit(msg.mountpoint, msg.topic)
        return self.route_rows(msg, rows, from_sid)

    def publish_nowait(self, msg: Msg, from_sid: Optional[SubscriberId] = None) -> int:
        """QoS0 fast path for the batched view: submit to the collector and
        route when the batch resolves, without blocking the session reader
        on the batch window (a single publisher would otherwise get exactly
        one message per window). Retain handling stays synchronous so local
        read-your-writes ordering holds. Per-publisher delivery order is
        preserved by collector submission order."""
        msg = self._pre_publish(msg)
        fut = self.broker.batch_collector().submit(msg.mountpoint, msg.topic)

        def _done(f: "asyncio.Future") -> None:
            exc = f.exception()
            if exc is not None:
                self.broker.metrics.incr("mqtt_publish_error")
                return
            self.route_rows(msg, f.result(), from_sid)

        fut.add_done_callback(_done)
        return 0

    def _pre_publish(self, msg: Msg) -> Msg:
        cfg = self.broker.config
        if not self.broker.cluster_ready() and not cfg.allow_publish_during_netsplit:
            raise RuntimeError("not_ready")
        if msg.retain:
            if not msg.payload:
                self.broker.retain.delete(msg.mountpoint, msg.topic)
                msg = msg_with_retain(msg, False)
            else:
                self.broker.retain.insert(
                    msg.mountpoint,
                    msg.topic,
                    RetainedMsg(
                        msg.payload,
                        dict(msg.properties),
                        msg.qos,
                        expiry_ts=_retain_expiry(msg),
                    ),
                )
                self.broker.metrics.incr("retain_messages_stored")
        return msg

    def route_rows(
        self,
        msg: Msg,
        rows: Iterable[Tuple[Tuple[str, ...], Any, SubOpts]],
        from_sid: Optional[SubscriberId],
    ) -> int:
        """The fold body (vmq_reg:publish/3 fold fun, vmq_reg.erl:326-353):
        local rows enqueue, shared rows collect into groups, node rows
        forward. Shared groups then go through policy selection."""
        matches = 0
        groups: Dict[str, List[Tuple[SubscriberId, SubOpts]]] = {}
        for _filter, key, opts in rows:
            if isinstance(key, tuple) and len(key) == 3 and key[0] == "$g":
                _, group, sid = key
                if opts.no_local and sid == from_sid:
                    continue
                groups.setdefault(group, []).append((sid, opts))
                continue
            if isinstance(key, str):  # remote node pointer
                if self.remote_publish is not None:
                    self.remote_publish(key, msg)
                    self.broker.metrics.incr("router_matches_remote")
                continue
            sid = key
            if opts.no_local and sid == from_sid:
                continue
            if self._enqueue_to(sid, msg, opts):
                matches += 1
        for group, members in groups.items():
            if self._publish_shared(msg, members):
                matches += 1
        if matches:
            self.broker.metrics.incr("router_matches_local", matches)
        return matches

    def _enqueue_to(self, sid: SubscriberId, msg: Msg, opts: SubOpts) -> bool:
        queue = self.queues.get(sid)
        if queue is None:
            return False
        out = msg if opts.rap else msg_with_retain(msg, False)
        qos = opts.qos if self.broker.config.upgrade_outgoing_qos else min(opts.qos, msg.qos)
        out = out.with_qos(qos)
        out = _maybe_add_sub_id(out, opts)
        queue.enqueue(out)
        return True

    def _publish_shared(
        self, msg: Msg, members: List[Tuple[SubscriberId, SubOpts]]
    ) -> bool:
        """Pick one group member: randomized, online-first
        (vmq_shared_subscriptions.erl:26-63). Policies prefer_local /
        local_only / random coincide on a single node; the cluster layer
        extends member lists with remote entries."""
        shuffled = members[:]
        random.shuffle(shuffled)
        online = [
            (sid, opts)
            for sid, opts in shuffled
            if (q := self.queues.get(sid)) is not None and q.state == ONLINE
        ]
        for sid, opts in online + shuffled:
            if self._enqueue_to(sid, msg, opts):
                return True
        return False

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, float]:
        total = sum(len(t) for t in self._tries.values())
        mem = sum(t.stats()["memory"] for t in self._tries.values())
        return {
            "router_subscriptions": total,
            "router_memory": mem,
            "queue_processes": len(self.queues),
        }

    def fold_subscriptions(self, mountpoint: str = ""):
        """Iterate every (filter, key, opts) — warm-load feed for the TPU
        table (mirrors vmq_reg:fold_subscriptions, vmq_reg_trie warm load)."""
        return self.trie(mountpoint).entries()


def msg_with_retain(msg: Msg, retain: bool) -> Msg:
    if msg.retain == retain:
        return msg
    return dataclasses.replace(msg, retain=retain)


def _maybe_add_sub_id(msg: Msg, opts: SubOpts) -> Msg:
    sub_id = getattr(opts, "subscription_id", None)
    if not sub_id:
        return msg
    props = dict(msg.properties)
    props.setdefault("subscription_identifier", []).append(sub_id)
    return dataclasses.replace(msg, properties=props)


def _retain_expiry(msg: Msg) -> Optional[float]:
    interval = msg.properties.get("message_expiry_interval")
    if interval:
        return time.time() + interval
    return None
