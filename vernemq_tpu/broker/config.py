"""Layered broker configuration.

Mirrors the reference's config system shape (``vmq_config.erl``: file <
app-default < stored-global < stored-per-node, cached lookups;
``priv/vmq_server.schema`` for the knob names) without cuttlefish — plain
defaults dict + override layers. Knob names keep the reference's schema
names so an operator coming from the reference finds the same switches.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

DEFAULTS: Dict[str, Any] = {
    # connection / session (vmq_server.schema)
    # off by default like the reference: with no auth plugin answering the
    # auth_on_register chain, connects are denied (vmq_auth.erl:3-8
    # registers deny-all fallback hooks when allow_anonymous=off)
    "allow_anonymous": False,
    "max_client_id_size": 100,
    "persistent_client_expiration": 0,  # seconds; 0 = never expire
    "max_inflight_messages": 20,
    "max_online_messages": 1000,
    "max_offline_messages": 1000,
    "queue_deliver_mode": "fanout",  # fanout | balance (vmq_queue.erl:826-835)
    "queue_type": "fifo",  # fifo | lifo offline drop policy (vmq_queue.erl:845-865)
    "upgrade_outgoing_qos": False,
    "allow_multiple_sessions": False,
    "retry_interval": 20,
    "max_message_rate": 0,  # msgs/sec per session; 0 = unlimited
    "max_message_size": 0,  # bytes; 0 = unlimited
    "m5_max_packet_size": 0,  # broker->v5-client frame cap; 0 = client's say
    "max_last_will_delay": 0,  # v5 will-delay cap, seconds
    "receive_max_broker": 10,
    "receive_max_client": 65535,
    "suppress_lwt_on_session_takeover": False,
    "coordinate_registrations": True,
    # netsplit CAP flags (vmq_server.schema:13-35, vmq_reg.erl:65-70)
    "allow_register_during_netsplit": False,
    "allow_publish_during_netsplit": False,
    "allow_subscribe_during_netsplit": False,
    "allow_unsubscribe_during_netsplit": False,
    # shared subscriptions (vmq_shared_subscriptions.erl:90-106)
    "shared_subscription_policy": "prefer_local",  # prefer_local|local_only|random
    # cluster (vmq_cluster_node.erl buffering; vmq_queue drain batching)
    "outgoing_clustering_buffer_size": 10_000_000,  # bytes
    "max_msgs_per_drain_step": 100,
    "max_drain_time": 500,  # ms cap per migration drain step
    "remote_enqueue_timeout": 5000,  # ms ack timeout for remote enqueues
    # store-and-forward spool for QoS>=1 cluster frames (cluster/spool.py):
    # journaled before the writer, seq-tagged on the wire (msq), deleted
    # on cumulative acks, replayed on channel re-establishment — the
    # cross-node delivery guarantee through partitions and peer restarts
    "cluster_spool_enabled": True,
    # journal directory; empty = in-memory journal (replay across
    # partitions and buffer overflow, no crash durability); set a path
    # (resolved under data_dir) for crash-restart replay from disk
    "cluster_spool_dir": "",
    "cluster_spool_max_bytes": 128 * 1024 * 1024,
    # cumulative-ack pacing on the receiver (ms between acks per origin)
    "cluster_spool_ack_interval": 50,
    # ack watchdog: unacked frames older than this replay over the live
    # channel (recovers in-channel loss where no reconnect fires replay)
    "cluster_spool_retransmit_ms": 1000,
    # frames the watchdog replays per tick, with a persistent per-peer
    # cursor resuming where the last tick stopped — a long partition at
    # high publish rates no longer re-ships the whole journal every
    # tick (the quadratic wire cost flagged in ROADMAP). 0 = unbudgeted
    # (full replay per tick, the old behaviour).
    "cluster_spool_replay_burst": 512,
    # compat no-op (see schema.COMPAT_NOOPS): queues are dict-sharded
    "queue_sup_sup_children": 50,
    # reg views started at boot; entries from schema.REG_VIEW_ALIASES
    "reg_views": ["trie"],
    # bounded migration-drain retry (max_drain_time apart) before the
    # backlog is restored locally and the migration is marked failed
    "migrate_drain_retries": 60,
    # live handoff (cluster/handoff.py): per-phase deadlines of the
    # freeze→drain→fence→adopt state machine. The freeze deadline
    # bounds the pause a moving unit's clients can observe (freeze,
    # fence and adopt each run under it); the drain deadline bounds
    # the backlog flush — past either the handoff rolls back and the
    # OLD owner keeps serving (degraded, never stuck).
    "handoff_freeze_deadline_ms": 500,
    "handoff_drain_deadline_s": 10.0,
    # live v5 handoff: moved sessions get DISCONNECT 0x9D (Server
    # moved, with the Server Reference property) after fence+adopt
    # instead of a takeover kick — the client reconnects straight to
    # the new owner. v3/4 sessions always keep the takeover path.
    "handoff_v5_redirect": True,
    # sessions per batched drain handoff: each batch bound for one
    # target shares ONE fence write (store_many) instead of a
    # per-session record rewrite
    "handoff_batch_max_sessions": 64,
    # membership health plane (cluster/health.py): phi-accrual failure
    # detection over the existing cluster traffic. Every delivered
    # inbound batch is a heartbeat (the 1s idle ping guarantees a
    # floor); phi scores the current silence in units of the observed
    # cadence — suspect at ~3.5 missed intervals, down at ~18. The
    # exit_ratio/hold pair is the governor's flap-suppression
    # hysteresis: re-entering alive needs phi below
    # phi_suspect*exit_ratio for hold_s straight.
    "health_enabled": True,
    "health_tick_ms": 500,
    "health_window": 64,
    "health_phi_suspect": 1.5,
    "health_phi_down": 8.0,
    "health_exit_ratio": 0.5,
    "health_hold_s": 3.0,
    # automatic rebalance planner: fires on join/leave/down/alive,
    # debounced. The debounce doubles as the correlated-failure
    # confirmation window: when this node is being isolated, its links
    # die together but the DOWN verdicts skew by up to the 1s idle-ping
    # phase, so the window must exceed that cadence for both verdicts
    # to land in one batch and the quorum gate to see them together.
    # Per-peer cooldown is the anti-ping-pong rail (at most
    # one cycle per peer per window); the quorum gate refuses automatic
    # action while this node cannot see a membership majority (a
    # netsplit minority sits still — CAP machinery owns partitions);
    # max_concurrent caps in-flight handoffs node-wide (automation must
    # not freeze half the node at once).
    "rebalance_enabled": True,
    "rebalance_require_quorum": True,
    "rebalance_debounce_s": 1.5,
    "rebalance_cooldown_s": 10.0,
    "rebalance_max_concurrent": 4,
    # client-facing address gossiped to peers (hlo/ping "caddr"): what
    # a v5 server-redirect DISCONNECT hands out as the Server Reference
    # for sessions moved HERE. Empty = peers fall back to the node name.
    "cluster_advertised_address": "",
    # QoS2 exactly-once dedup bound: max awaiting-release pids held
    # per session before oldest-first eviction (qos2_dedup_evictions);
    # 0 = unbounded (the pre-cap behaviour)
    "qos2_dedup_max": 4096,
    # v5
    "topic_alias_max_client": 0,
    "topic_alias_max_broker": 0,
    "max_session_expiry_interval": 0,  # 0 → no cap (v5 session_expiry_interval)
    # matcher
    "default_reg_view": "trie",  # trie | tpu — the reg-view seam (vmq_mqtt_fsm.erl:105)
    "tpu_batch_window_us": 200,
    # per-part device fanout cap (k): beyond it the pub falls back to the
    # exact host match — 256 balances extraction cost vs fallback rate
    "tpu_max_fanout": 256,
    # flat result-buffer slots per pub, batch-averaged (C = Bpad * this)
    "tpu_flat_avg": 128,
    # pre-size the device table for a known subscriber scale: growth
    # rebuilds (repartition + full re-upload) happen at doublings, so an
    # operator expecting 1M subscriptions boots with the bucketed layout
    # already in place instead of rebuilding through the ladder
    "tpu_initial_capacity": 1024,
    # scripting: SQL function wrapping the password in the bundled
    # mysql auth-script query — password | md5 | sha1 | sha256
    # (vmq_diversity_mysql.erl:119-129 hash_method)
    "mysql_password_hash_method": "password",
    # Lua interpreter states per script (the balancing pool of
    # vmq_diversity_script_sup_sup.erl): concurrent auth hooks each
    # check a state out instead of serialising on one interpreter
    "diversity_num_states": 4,
    # fused Pallas tile matcher for the probe phases (ops/pallas_match.py);
    # off by default until the on-chip A/B (tools/tune_windowed.py
    # --pallas) shows a win — self-disables if Mosaic lowering fails
    "tpu_use_pallas": False,
    # packed transport for the windowed kernel: ONE int32 upload vector
    # and ONE result vector per batch instead of 12 args + 4 pulls —
    # per-argument dispatch latency dominates on tunnel-attached
    # accelerators (tools/probe_tunnel.py)
    "tpu_packed_io": True,
    # flushes this small are matched on the host trie instead of paying a
    # device round trip (hybrid dispatch, SURVEY.md §7.2); 0 disables
    "tpu_host_batch_threshold": 8,
    # multi-device serving mesh "BxS" (batch x sub axes, e.g. "1x8") or
    # "S" (sub-only) — when set, the tpu reg view shards the subscription
    # table over the 'sub' axis and the publish batch over 'batch'
    # (SURVEY §5.7: the per-node trie replica sharded across chips,
    # vmq_reg_trie.erl:503-520). Empty = single-device matcher.
    "tpu_mesh": "",
    # mesh implementation: the mesh-native matcher (persistent
    # NamedSharding/pjit arrays placed via partition rules, slice-routed
    # delta scatter, multi-process capable — parallel/mesh_match.py) is
    # the default when tpu_mesh is set; false keeps the legacy per-call
    # shard_map seat
    "tpu_mesh_native": True,
    # device flush waits at most this long for the matcher lock before
    # the whole flush serves from the host trie (0 = unbounded wait)
    "tpu_lock_busy_shed_ms": 500,
    # wire plane (protocol/fastpath.py + native/codec.cc): the QoS0
    # object-free fast path over the batched frame table. Off = every
    # frame materialises and takes the classic session handler (the
    # pre-wire-plane behaviour); the batch parser itself stays on
    # either way (it is byte-identical). The NATIVE codec has its own
    # escape hatch: the VMQ_NATIVE_CODEC=0 environment variable.
    "wire_fastpath_enabled": True,
    # under load, up to this many full batch windows coalesce into ONE
    # device dispatch (match_many super-batches: K round trips -> 1,
    # the continuous-batching posture); 1 disables
    "tpu_super_batch_k": 8,
    # device-path circuit breaker (robustness/breaker.py): N consecutive
    # dispatch failures open it — ALL matching serves from the exact
    # host trie until a half-open probe (exponential backoff + jitter
    # between attempts, bounded by the max) succeeds and the matcher
    # re-warms. Disabled = raw device errors propagate to publishers.
    "tpu_breaker_enabled": True,
    "tpu_breaker_failure_threshold": 3,
    "tpu_breaker_backoff_initial_ms": 200,
    "tpu_breaker_backoff_max_ms": 10_000,
    # pre-compile the delta-scatter shape ladder (Dpad 2..this) at
    # matcher startup so the first post-subscribe flush pays a scatter,
    # not a compile (the sub_to_matchable_ms_max tail); 0 disables
    "tpu_delta_warm_max": 128,
    # device-resident retained-message index (vernemq_tpu/retained/):
    # SUBSCRIBE retained replay reverse-matches filter batches against
    # the retained-topic table on the device instead of the serial host
    # walk. Active only when default_reg_view=tpu AND the accelerator
    # actually came up; any degraded signal (breaker open, rebuild,
    # per-filter escape) serves the exact host walk.
    "tpu_retained_enabled": True,
    # replay coalescing window (µs) and max filters per dispatch
    "tpu_retained_window_us": 500,
    "tpu_retained_max_batch": 1024,
    # flushes this small are served by the host walk on the event loop
    # (a lone subscribe must not pay a device round trip); 0 disables
    "tpu_retained_host_threshold": 4,
    # per-filter device match cap: a filter matching more retained
    # topics than this resolves against the host store instead
    "tpu_retained_max_fanout": 256,
    # pre-size the retained device table (growth rebuilds at doublings)
    "tpu_retained_initial_capacity": 2048,
    # payload filtering & windowed aggregation (vernemq_tpu/filters/,
    # MQTT+): subscriptions may carry a ?$-suffix predicate/aggregation
    # over fields named in the per-mountpoint schema registry
    # (`vmq-admin schema set`). Disabled = the '?' stays part of the
    # topic and no engine is built — byte-identical to the pre-filter
    # broker. Enabled with no schemas/predicates registered costs one
    # dict probe per publish.
    "payload_filters_enabled": True,
    # boot-installed schemas: [{mountpoint, topic, fields}] dicts, e.g.
    # {"mountpoint": "", "topic": "sensors/+/temp",
    #  "fields": "value:number,unit:enum(c|f)"}
    "payload_schemas": [],
    # (matched-subscriber x predicate) pairs below this are evaluated
    # by the exact host evaluator instead of paying a device round trip
    # (the predicate analog of tpu_host_batch_threshold)
    "predicate_host_threshold": 16,
    # device pair cap per predicate dispatch; larger batches host-serve
    "predicate_max_pairs": 65536,
    # aggregation accumulator table: initial slots (grows in doublings)
    # and the hard cap — past it aggregation subs degrade to raw
    # per-message delivery, visibly (aggregate_window_overflows)
    "aggregate_initial_windows": 256,
    "aggregate_max_windows": 4096,
    # time-window close scan interval (ms)
    "aggregate_tick_ms": 250,
    # multi-process session front end (broker/workers.py +
    # broker/match_service.py): N worker processes share the MQTT port
    # via SO_REUSEPORT, each running parse/auth/session/queue locally;
    # matching optionally centralizes in ONE device-match service
    # process reached over shared-memory rings. workers=1 (the default,
    # and what every test boots) runs byte-identical to the classic
    # single-process broker — none of the keys below change any code
    # path until the WorkerGroup parent sets them.
    # vmqlint: allow(knob-registry): consumed by the worker CLI via the
    # RAW parsed conf (workers.py probes parse_conf output, deliberately
    # not a Config — DEFAULTS merging would make the cpu_count/2
    # fallback unreachable), a read the config-shaped taint cannot see
    "workers": 1,
    # shared-memory stats table name (parallel/shm_ring.py
    # WorkerStatsBlock): per-worker health/pressure slots the governors
    # fuse and `vmq-admin workers show` reads. Empty = not a worker.
    "worker_stats_block": "",
    "worker_index": 0,
    "workers_total": 1,
    # request/response ring names for the match-service channel; empty =
    # no service (each process matches in-process, the classic path)
    "match_service_req_ring": "",
    "match_service_resp_ring": "",
    # worker-side fold reply deadline: past it the fold degrades to the
    # worker's local trie through the client breaker
    "match_service_timeout_ms": 2000,
    # deterministic fault injection (robustness/faults.py): a list of
    # rule dicts ({point, kind, probability, after, count, latency_ms})
    # installed at boot; also live-toggleable via `vmq-admin fault ...`.
    # Empty = no plan, zero overhead.
    "fault_injection": [],
    "fault_injection_seed": 0,
    # supervisor restart budget: more than max_restarts CONSECUTIVE
    # crashy restarts of one child escalates (listener teardown — the
    # node fails health checks instead of crash-looping forever); a
    # stint healthier than the current backoff, or longer than
    # restart_window seconds, resets the count. 0 = unlimited.
    "supervisor_max_restarts": 20,
    "supervisor_restart_window": 60.0,
    # systree / metrics
    "systree_enabled": True,
    "systree_interval": 20,
    "systree_mountpoint": "",
    "systree_qos": 0,
    "systree_retain": False,
    "systree_reg_view": "",  # compat no-op (schema.COMPAT_NOOPS)
    "graphite_enabled": False,
    "graphite_host": "localhost",
    "graphite_port": 2003,
    "graphite_interval": 20,
    "graphite_prefix": "",
    "graphite_api_key": "",  # hosted-graphite key, prepended to the path
    "graphite_connect_timeout": 5.0,   # seconds
    "graphite_reconnect_timeout": 10.0,  # seconds between retries
    "graphite_include_labels": False,  # compat no-op (unlabeled metrics)
    # http endpoints (vmq_http_config.erl http_modules)
    "http_enabled": False,
    "http_host": "127.0.0.1",
    "http_port": 8888,
    "http_modules": ["metrics", "health", "status", "mgmt"],
    "http_mgmt_api_auth": True,
    # storage
    "message_store": "memory",  # memory | file | native (C++ engine)
    "message_store_dir": "./data/msgstore",
    # opt-in fsync per message-store write: the stores flush to the OS
    # on every write either way; fsync makes each write power-loss
    # durable at a large throughput cost (the reference's sync knob)
    "msg_store_fsync": False,
    # with fsync on, coalesce to ONE fsync per write burst at the
    # flush-tick boundary (msg_store_fsync_coalesced counts the saved
    # syncs); off = the legacy per-record fsync
    "msg_store_group_commit": True,
    # engines hashed by msg-ref; reference runs 12 (vmq_lvldb_store_sup.erl)
    "msg_store_instances": 12,
    # unified segment engine (storage/segment.py): seal size of the
    # append segment, checkpoint cadence (bytes appended between index
    # checkpoints — recovery replays only what landed after one), and
    # the budgeted off-loop compaction driver (bytes copied per engine
    # per tick; 0 interval disables the driver)
    "store_segment_max_bytes": 8 * 1024 * 1024,
    "store_checkpoint_every_bytes": 32 * 1024 * 1024,
    "store_compact_interval_ms": 1000,
    "store_compact_budget_bytes": 4 * 1024 * 1024,
    # expired parked offline messages classified per maintenance tick
    # (refs examined, not bytes; the sweep rides the compaction tick)
    "store_expire_sweep_budget": 256,
    # batched reconnect-storm resumption (storage/resume.py): coalesce
    # concurrent offline replays into one off-loop read per window
    "resume_batched": True,
    "resume_window_us": 500,
    "resume_max_batch": 512,
    "resume_host_threshold": 4,
    # queued-resume deadline before the exact per-session fallback
    # serves on the loop (a 100k-session storm legitimately queues for
    # seconds — this is a wedge bound, not a latency target)
    "resume_expiry_ms": 30_000,
    "metadata_dir": "./data/meta",
    "metadata_persistence": False,  # durable subscriber-db/retain via kvstore
    # metadata backend: "lww" (plumtree-flavored) | "swc" (server-wide
    # clocks, vmq_swc) — the metadata_impl knob (vmq_metadata.erl:24-28)
    "metadata_plugin": "lww",
    # MQTT bridges (vmq_bridge): list of {host, port, topics:[{pattern,
    # direction, qos, local_prefix, remote_prefix}], ...} dicts — the
    # vmq_bridge.tcp.* config tree flattened
    "bridges": [],
    # scripting plugin (vmq_diversity): operator script files exposing the
    # hook surface; Python here where the reference embeds Lua
    "diversity_scripts": [],
    # sysmon / overload protection (vmq_sysmon; riak_sysmon knobs)
    "sysmon_enabled": True,
    "sysmon_lag_threshold": 0.25,  # seconds of event-loop lag = long_schedule
    "sysmon_memory_high_watermark": 0,  # bytes RSS; 0 = off (large_heap)
    # overload exits only after lag stays below threshold * this ratio
    # for a full cooldown (hysteresis — no shed/unshed flap at the edge)
    "sysmon_lag_exit_ratio": 0.5,
    # adaptive overload governor (robustness/overload.py): fuses loop-lag
    # EWMA + RSS watermark, collector pending-depth/dispatch-latency,
    # breaker state and cluster buffer/spool depth into a pressure level
    # 0-3 with per-level hysteresis. Staged cheapest-first responses:
    # L1 proportional per-session read throttle, L2 per-client token
    # buckets + QoS0 fanout shedding + retained-replay deferral, L3
    # connect refusal (CONNACK 0x97 / server unavailable) + top-talker
    # disconnects (Server busy). "binary" keeps the legacy posture (the
    # sysmon flag + fixed 0.1s sleep) for A/B runs — bench config 9.
    "overload_mode": "governor",  # governor | binary
    "overload_tick_ms": 250,
    "overload_hold_s": 5.0,       # per-level hysteresis hold window
    "overload_exit_ratio": 0.5,   # exit below enter_threshold * this
    "overload_l1_enter": 0.25,    # pressure gates per level
    "overload_l2_enter": 0.5,
    "overload_l3_enter": 0.8,
    "overload_l1_throttle_ms": 100,  # base read-throttle, scaled by
                                     # level and the session's talker
                                     # share (heaviest wait longest)
    "overload_l2_client_rate": 50,   # token-bucket refill, msgs/s/client
    "overload_l2_burst": 100,
    "overload_l3_disconnect_top": 5,  # heaviest talkers shed at L3 entry
    # dispatch-latency EWMA budget for the collector pressure signal
    "overload_dispatch_budget_ms": 50.0,
    # stall watchdog (robustness/watchdog.py): monitored-operation
    # registry + deadline abandonment for SILENT failures — a device
    # dispatch that never returns, a wedged rebuild thread, a half-open
    # cluster peer whose acks stop. Off = stalls wedge exactly as far
    # as their own seams (lock timeouts, injection caps) allow.
    "watchdog_enabled": True,
    "watchdog_tick_ms": 100,      # overdue-op scan interval
    # device dispatch deadline: a collector flush whose device call has
    # not returned by then is ABANDONED — the waiters are served by the
    # exact host trie, the stall feeds the breaker, the wedged executor
    # thread is sacrificed and its late result discarded. 0 disables
    # (the pre-watchdog unbounded wait).
    "watchdog_dispatch_deadline_ms": 5000,
    # background device-table (re)build deadline: past it the build is
    # abandoned like a failed one (breaker fed, host path serves, late
    # install discarded). Generous — full builds at millions of rows
    # legitimately take seconds; this catches WEDGES, not slowness.
    "watchdog_rebuild_deadline_s": 120.0,
    # queued-item expiry, in multiples of overload_dispatch_budget_ms:
    # a publish/replay still queued in a collector after this many
    # dispatch budgets is served by the host oracle even if every
    # pipeline slot is wedged — the bounded-tail guarantee. 0 disables.
    "watchdog_collector_expiry_budgets": 4,
    # cluster connection-level stall detection: unacked spooled bytes
    # with no cumulative-ack progress for this long cycle the channel
    # (drop + reconnect + spool replay — loss-free by PR 3); catches
    # half-open peers whose writes succeed but whose acks never arrive.
    # 0 disables.
    "cluster_stall_timeout_s": 10.0,
    # observability (vernemq_tpu/observability/): stage latency
    # histograms + publish-path flight recorder + device dispatch
    # profiler. Off reduces every instrumented seam to one boolean test
    # (the bench overhead guard measures the difference).
    "observability_enabled": True,
    # flight recorder: every Nth admitted publish carries a stage-
    # stamped trace through the whole path (0 disables sampling)
    "flight_recorder_sample_n": 32,
    "flight_recorder_capacity": 4096,
    # device dispatch profiler ring (records kept for `vmq-admin
    # profile device` / `timeline dump`)
    "profiler_capacity": 2048,
    # control-plane event journal ring (observability/events.py):
    # breaker/governor/watchdog/supervisor/mesh/spool/wire transitions
    # kept for `vmq-admin events show|dump` and trace interleaving
    "events_capacity": 2048,
    # canary SLO probe (observability/canary.py): a loopback subscriber
    # + a periodic synthetic publish through the FULL path feeding the
    # e2e_canary_ms histogram and the canary_slo_breaches burn counter.
    # Off by default: the probe adds one routing-table row and a
    # publish per interval — opt in per deployment.
    "canary_enabled": False,
    "canary_interval_ms": 1000,
    "canary_slo_ms": 250.0,
    "crl_refresh_interval": 60.0,  # seconds (vmq_crl_srv schema knob)
    "swc_replication_groups": 8,  # reference runs 10 (vmq_swc_plugin.erl:36-44)
    "swc_sync_interval": 2.0,  # seconds between AE rounds (sync_interval)
    # storage engine behind the vmq_swc_db seam (cluster/swc_db.py):
    # kvstore (one native engine) | bucketed (N engines by key hash) —
    # the reference's leveldb/rocksdb/leveled choice (vmq_swc_db.erl)
    "swc_db_backend": "kvstore",
    # plumtree EBT safety valves (plumtree.* schema tree): cap on
    # announced-but-unreceived ids awaiting GRAFT, and the backlog size
    # past which new IHAVE announcements are dropped (digest AE repairs)
    "plumtree_outstanding_limit": 10_000,
    "plumtree_drop_ihave_threshold": 0,  # 0 = never drop
    # shared-subscription delivery on remote-ack timeout: queue retry
    # gives requeue semantics either way (schema.COMPAT_NOOPS)
    "shared_subscription_timeout_action": "ignore",
    # raw tcp listen options string (reference erlang proplist); nodelay
    # is parsed and applied, the rest is accepted for compatibility
    "tcp_listen_options":
        "[{nodelay, true}, {linger, {true, 0}}, {send_timeout, 30000}, "
        "{send_timeout_close, true}]",
    # release-layout base directories (setup.* schema tree): when set,
    # relative message_store_dir/metadata_dir/log_file resolve under them
    "data_dir": "",
    "log_dir": "",
    # logging sinks (the lager console/file/syslog triple of the
    # reference's release config; syslog uses the OS socket via the
    # stdlib handler — the reference's C port driver seat)
    "log_level": "info",
    "log_file": "",          # path; empty = no file sink
    "log_syslog": False,
    "log_syslog_address": "/dev/log",
    # structured keys filled by the conf-file loader (broker/conf.py):
    # listeners started at boot (vmq_ranch_config listener tree) and
    # plugins enabled at boot (plugins.<name> = on)
    "listeners": [],  # [{kind, name, addr, port, opts}]
    "plugins": [],    # [{name, opts}]
}


class Config:
    """Override layers: constructor kwargs > set() calls > DEFAULTS."""

    def __init__(self, **overrides: Any):
        import copy

        # deep copy: DEFAULTS holds mutable values (http_modules list) that
        # must not be shared across Config instances
        self._values: Dict[str, Any] = copy.deepcopy(DEFAULTS)
        for k, v in overrides.items():
            if k not in DEFAULTS:
                raise KeyError(f"unknown config key: {k}")
            self._values[k] = v
        self._listeners: List[Callable[[str, Any], None]] = []

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._values:
            return self._values[key]
        if default is not None:
            return default
        raise KeyError(key)

    def __getattr__(self, key: str) -> Any:
        try:
            return self._values[key]
        except KeyError:
            raise AttributeError(key) from None

    def set(self, key: str, value: Any) -> None:
        """Runtime config change with change-event fan-out
        (vmq_config.erl:220-246 change_config)."""
        if key not in DEFAULTS:
            raise KeyError(f"unknown config key: {key}")
        self._values[key] = value
        for fn in self._listeners:
            fn(key, value)

    @classmethod
    def from_file(cls, path: str) -> "Config":
        """Boot-from-conf-file entry point (the vernemq.conf layer)."""
        from .conf import load_conf_file

        return load_conf_file(path)

    def on_change(self, fn: Callable[[str, Any], None]) -> None:
        self._listeners.append(fn)

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._values)
