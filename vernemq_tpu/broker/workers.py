"""Multi-process host scale-out: N broker workers sharing one MQTT port.

The reference runs one lightweight Erlang process per socket scheduled
across all BEAM schedulers (``vmq_ranch.erl:41-43``) — per-connection
parallelism inside one OS process. A GIL-bound asyncio broker can't do
that, so the same capability is delivered the OS way: **N worker
processes**, each a full broker (sessions, queues, matcher, storage
views), accepting on ONE shared MQTT port via ``SO_REUSEPORT`` (the
kernel balances accepts), and meshed over the existing cluster-node
machinery on loopback — a worker IS a lightweight local node, so
cross-worker delivery, subscriber replication, session takeover and
shared subscriptions all reuse the cluster data/metadata plane
(``cluster/``), exactly as they work between real nodes.

Usage::

    python -m vernemq_tpu.broker.workers --workers 4 --port 1883 \
        [--conf vernemq.conf] [--allow-anonymous]

or programmatically :class:`WorkerGroup` (used by ``tools/loadtest.py
--workers N``).

The parent supervises: a dead worker is relaunched with its same
identity (worker index, cluster port), mirroring the restart discipline
of ``broker/supervisor.py`` one level up.
"""

from __future__ import annotations

import argparse
import atexit
import multiprocessing as mp
import os
import secrets
import signal
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

#: cluster channel of worker i listens on loopback at base + i (kept BELOW the kernel ephemeral port range 32768+, or client sockets collide with it under load)
DEFAULT_CLUSTER_BASE = 24100


def _run_worker(idx: int, n_workers: int, host: str, port: int,
                cluster_base: int, overrides: Dict[str, Any],
                conf_path: Optional[str],
                direct_base: Optional[int] = None) -> None:
    """Worker-process entry point (spawn-safe, top-level)."""
    import asyncio
    import faulthandler

    dump_s = int(os.environ.get("TIER1_FAULTHANDLER_S") or 0)
    if dump_s > 0:
        # hung-child forensics (tests/conftest.py arms the parent the
        # same way): a wedged worker prints WHERE it hung before the
        # outer timeout kills the test run
        faulthandler.enable()
        faulthandler.dump_traceback_later(dump_s, repeat=True, exit=False)

    async def amain() -> None:
        import os

        plats = os.environ.get("JAX_PLATFORMS")
        if plats and plats != "axon":
            # this image's jax ignores the env var; translate it so the
            # worker's in-process backend matches the probe's verdict
            import jax

            jax.config.update("jax_platforms", plats)

        from .config import Config
        from .server import start_broker

        if conf_path:
            from .conf import load_conf_file

            cfg = load_conf_file(conf_path)
            for k, v in overrides.items():
                cfg.set(k, v)
            # conf-declared listeners must not EADDRINUSE across the
            # group: MQTT/WS listeners join the SO_REUSEPORT set on
            # every worker; singleton kinds (admin HTTP, explicit
            # cluster listeners) run on worker 0 only
            shared_kinds = ("mqtt", "mqtts", "ws", "wss")
            rewritten = []
            for ent in cfg.get("listeners", []):
                if ent["kind"] in shared_kinds:
                    ent = {**ent,
                           "opts": {**ent.get("opts", {}),
                                    "reuse_port": True}}
                elif idx > 0:
                    continue
                rewritten.append(ent)
            cfg.set("listeners", rewritten)
        else:
            cfg = Config(**overrides)
        if idx > 0 and cfg.get("http_enabled", False):
            # the admin HTTP endpoint is a fixed-port singleton
            cfg.set("http_enabled", False)
        broker, server = await start_broker(
            cfg, host=host, port=port,
            node_name=f"worker{idx}",
            cluster_listen=("127.0.0.1", cluster_base + idx),
            join=("127.0.0.1", cluster_base) if idx > 0 else None,
            reuse_port=True)
        if direct_base:
            # per-worker direct MQTT port (base + idx): lets operators
            # and the efficiency harness address ONE worker instead of
            # taking the kernel's SO_REUSEPORT pick — the analog of
            # dialing a specific node of a cluster. Through the
            # ListenerManager so it shows in `listener show` and stops
            # with the broker like every other listener.
            from .listeners import ListenerManager

            lm = broker.listeners or ListenerManager(broker)
            await lm.start_listener("mqtt", "127.0.0.1",
                                    direct_base + idx)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await broker.stop()
        await server.stop()

    asyncio.run(amain())


class WorkerGroup:
    """Spawn + supervise N broker worker processes on one shared port.

    With ``match_service=True`` the group additionally owns ONE
    device-match service process and the shared-memory plumbing
    (broker/match_service.py): per-worker request/response rings plus
    the worker stats block. Workers then boot with
    ``default_reg_view=tpu`` served by the ring stub — their parse/
    auth/session/queue work stays local, matching is centralized. A
    stats block is created regardless of match_service (it carries the
    fused overload pressure and ``vmq-admin workers show`` health rows
    and never touches the match path), so ``workers=1`` without a
    service runs byte-identical to the single-process broker."""

    def __init__(self, n_workers: int, host: str = "127.0.0.1",
                 port: int = 1883,
                 cluster_base: int = DEFAULT_CLUSTER_BASE,
                 conf_path: Optional[str] = None,
                 direct_base: Optional[int] = None,
                 match_service: bool = False,
                 match_view: str = "trie",
                 ring_bytes: int = 1 << 22,
                 **config_overrides: Any):
        if n_workers < 1:
            raise ValueError("need at least one worker")
        self.n_workers = n_workers
        self.host = host
        self.port = port
        self.cluster_base = cluster_base
        self.conf_path = conf_path
        self.direct_base = direct_base
        self.match_service = match_service
        self.match_view = match_view
        self.ring_bytes = ring_bytes
        self.overrides = config_overrides
        self._ctx = mp.get_context("spawn")
        self._procs: List[Any] = []
        self._service_proc: Optional[Any] = None
        self._service_epoch = 0
        self.service_restarts = 0
        self._stopping = False
        self._shm_tag = f"vmqw{os.getpid() & 0xFFFF:x}{secrets.token_hex(3)}"
        self.stats_name = f"{self._shm_tag}s"
        self._stats = None
        self._rings: List[Tuple[Any, Any]] = []  # parent-held (req, resp)

    # ------------------------------------------------- cluster port block

    def _probe_cluster_base(self) -> int:
        """Find a bindable loopback port block for the workers' cluster
        channels. The configured base is a *preference*: this host's
        ephemeral range (``ip_local_port_range``) may cover it, so any
        client socket can squat ``base + i`` between runs — probe the
        whole block and slide past squatters instead of letting worker
        ``i`` crash-loop on EADDRINUSE at boot."""
        import socket

        base = self.cluster_base
        for _ in range(64):
            socks = []
            try:
                for i in range(self.n_workers):
                    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                    s.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_REUSEADDR, 1)
                    try:
                        s.bind(("127.0.0.1", base + i))
                    except OSError:
                        s.close()
                        break
                    socks.append(s)
                else:
                    return base
            finally:
                for s in socks:
                    s.close()
            base += max(16, self.n_workers)
        raise RuntimeError(
            f"no free cluster port block of {self.n_workers} near "
            f"{self.cluster_base}")

    # --------------------------------------------------- shm plumbing

    def _ring_names(self, idx: int) -> Tuple[str, str]:
        return (f"{self._shm_tag}q{idx}", f"{self._shm_tag}r{idx}")

    def _create_shm(self) -> None:
        from ..parallel.shm_ring import ShmRing, WorkerStatsBlock

        self._stats = WorkerStatsBlock.create(self.stats_name,
                                              self.n_workers)
        if self.match_service:
            for i in range(self.n_workers):
                rq, rs = self._ring_names(i)
                self._rings.append((ShmRing.create(rq, self.ring_bytes),
                                    ShmRing.create(rs, self.ring_bytes)))

    def _destroy_shm(self) -> None:
        for rq, rs in self._rings:
            rq.close()
            rq.unlink()
            rs.close()
            rs.unlink()
        self._rings = []
        if self._stats is not None:
            self._stats.close()
            self._stats.unlink()
            self._stats = None

    def stats_block(self):
        """The parent's handle on the shared stats table (bench /
        supervision reads)."""
        return self._stats

    def _worker_overrides(self, idx: int) -> Dict[str, Any]:
        ov = dict(self.overrides)
        # a dead PEER WORKER is not a netsplit: it shares this host, the
        # supervisor respawns it within seconds, and its sessions are
        # dropped with DISCONNECT semantics — surviving workers must
        # keep admitting work through the respawn window instead of
        # refusing every publish behind the cluster-consistency gate.
        # Explicit operator settings still win.
        for flag in ("allow_publish_during_netsplit",
                     "allow_subscribe_during_netsplit",
                     "allow_unsubscribe_during_netsplit",
                     "allow_register_during_netsplit"):
            ov.setdefault(flag, True)
        ov.update(worker_stats_block=self.stats_name, worker_index=idx,
                  workers_total=self.n_workers)
        if self.match_service:
            rq, rs = self._ring_names(idx)
            # default_reg_view=tpu mounts the ring stub; the retained
            # device index stays OFF in workers — they own no device
            # (the service does), so subscribe replay host-walks locally
            ov.update(match_service_req_ring=rq,
                      match_service_resp_ring=rs,
                      default_reg_view="tpu",
                      tpu_retained_enabled=False)
        return ov

    # ----------------------------------------------------- supervision

    def _spawn(self, idx: int):
        p = self._ctx.Process(
            target=_run_worker,
            args=(idx, self.n_workers, self.host, self.port,
                  self.cluster_base, self._worker_overrides(idx),
                  self.conf_path, self.direct_base),
            name=f"vmq-worker{idx}", daemon=True)
        p.start()
        return p

    def _spawn_service(self):
        from .match_service import _service_main

        self._service_epoch += 1
        p = self._ctx.Process(
            target=_service_main,
            args=(self.stats_name,
                  [self._ring_names(i) for i in range(self.n_workers)],
                  self.match_view, self._service_epoch),
            name="vmq-match-service", daemon=True)
        p.start()
        return p

    def start(self) -> None:
        self._stopping = False
        # publish-ordering fence check before any ring exists: one
        # warning when the TSO fallback runs on a weakly-ordered host
        from ..parallel.shm_ring import fence_startup_check

        fence_startup_check()
        self.cluster_base = self._probe_cluster_base()
        self._create_shm()
        atexit.register(self.stop)  # leaked groups must not pin the
        # reuseport socket / shm segments past the parent (test reaper)
        if self.match_service:
            self._service_proc = self._spawn_service()
        # worker 0 is the cluster seed: it must be listening before the
        # rest dial in, so stagger it first
        self._procs = [self._spawn(0)]
        time.sleep(0.3)
        for i in range(1, self.n_workers):
            self._procs.append(self._spawn(i))

    def poll_restart(self) -> int:
        """Supervision tick: relaunch dead workers (same identity —
        worker index, cluster port, ring pair) and a dead match service
        (new epoch: workers notice the bump in the stats block and
        resync their owned rows). Returns the number restarted."""
        if self._stopping:
            return 0
        restarted = 0
        for i, p in enumerate(self._procs):
            if not p.is_alive():
                self._procs[i] = self._spawn(i)
                restarted += 1
        if (self.match_service and self._service_proc is not None
                and not self._service_proc.is_alive()):
            self._service_proc = self._spawn_service()
            self.service_restarts += 1
            restarted += 1
        return restarted

    def alive_count(self) -> int:
        return sum(1 for p in self._procs if p.is_alive())

    def service_alive(self) -> bool:
        return (self._service_proc is not None
                and self._service_proc.is_alive())

    def stop(self, timeout: float = 10.0) -> None:
        if self._stopping:
            return
        self._stopping = True
        procs = list(self._procs)
        if self._service_proc is not None:
            procs.append(self._service_proc)
        for p in procs:
            if p.is_alive():
                p.terminate()
        deadline = time.time() + timeout
        for p in procs:
            p.join(max(0.1, deadline - time.time()))
            if p.is_alive():
                p.kill()
                p.join(1.0)
        self._procs = []
        self._service_proc = None
        self._destroy_shm()


def main(argv: Optional[List[str]] = None) -> None:  # pragma: no cover
    ap = argparse.ArgumentParser(
        description="vernemq_tpu multi-process broker")
    ap.add_argument("--workers", type=int, default=None,
                    help="worker process count (default: the conf "
                         "file's `workers` knob, else cpu_count/2)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=1883)
    ap.add_argument("--cluster-base", type=int,
                    default=DEFAULT_CLUSTER_BASE)
    ap.add_argument("--direct-base", type=int, default=None,
                    help="also open a per-worker MQTT port at "
                         "direct_base+idx (address ONE worker)")
    ap.add_argument("--conf", default=None)
    ap.add_argument("--allow-anonymous", action="store_true")
    ap.add_argument("--match-service", action="store_true",
                    help="centralize matching in ONE device-match "
                         "service process fed over shared-memory rings "
                         "(workers keep parse/auth/session/queue local)")
    ap.add_argument("--match-view", default="trie",
                    choices=["trie", "tpu"],
                    help="what the match service folds on: the host "
                         "trie or the TPU batch pipeline")
    args = ap.parse_args(argv)
    n_workers = args.workers
    if n_workers is None and args.conf:
        from .conf import parse_conf

        # probe the RAW parsed file, not a Config: Config merges
        # DEFAULTS (workers=1), so .get() can never distinguish "knob
        # absent" from "knob set to 1" and the cpu_count/2 fallback
        # below would be unreachable for every conf-file launch
        with open(args.conf, "r", encoding="utf-8") as fh:
            raw = parse_conf(fh.read())
        if "workers" in raw:
            n_workers = int(raw["workers"])
    if n_workers is None:
        n_workers = max(2, (os.cpu_count() or 2) // 2)
    args.workers = n_workers
    overrides: Dict[str, Any] = {}
    if args.allow_anonymous:
        overrides["allow_anonymous"] = True
    group = WorkerGroup(args.workers, args.host, args.port,
                        cluster_base=args.cluster_base,
                        conf_path=args.conf,
                        direct_base=args.direct_base,
                        match_service=args.match_service,
                        match_view=args.match_view, **overrides)
    group.start()
    print(f"started {args.workers} workers on {args.host}:{args.port}",
          file=sys.stderr, flush=True)
    try:
        while True:
            time.sleep(1.0)
            n = group.poll_restart()
            if n:
                print(f"restarted {n} dead worker(s)", file=sys.stderr,
                      flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        group.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
