"""Multi-process host scale-out: N broker workers sharing one MQTT port.

The reference runs one lightweight Erlang process per socket scheduled
across all BEAM schedulers (``vmq_ranch.erl:41-43``) — per-connection
parallelism inside one OS process. A GIL-bound asyncio broker can't do
that, so the same capability is delivered the OS way: **N worker
processes**, each a full broker (sessions, queues, matcher, storage
views), accepting on ONE shared MQTT port via ``SO_REUSEPORT`` (the
kernel balances accepts), and meshed over the existing cluster-node
machinery on loopback — a worker IS a lightweight local node, so
cross-worker delivery, subscriber replication, session takeover and
shared subscriptions all reuse the cluster data/metadata plane
(``cluster/``), exactly as they work between real nodes.

Usage::

    python -m vernemq_tpu.broker.workers --workers 4 --port 1883 \
        [--conf vernemq.conf] [--allow-anonymous]

or programmatically :class:`WorkerGroup` (used by ``tools/loadtest.py
--workers N``).

The parent supervises: a dead worker is relaunched with its same
identity (worker index, cluster port), mirroring the restart discipline
of ``broker/supervisor.py`` one level up.
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import signal
import sys
import time
from typing import Any, Dict, List, Optional

#: cluster channel of worker i listens on loopback at base + i (kept BELOW the kernel ephemeral port range 32768+, or client sockets collide with it under load)
DEFAULT_CLUSTER_BASE = 24100


def _run_worker(idx: int, n_workers: int, host: str, port: int,
                cluster_base: int, overrides: Dict[str, Any],
                conf_path: Optional[str],
                direct_base: Optional[int] = None) -> None:
    """Worker-process entry point (spawn-safe, top-level)."""
    import asyncio

    async def amain() -> None:
        import os

        plats = os.environ.get("JAX_PLATFORMS")
        if plats and plats != "axon":
            # this image's jax ignores the env var; translate it so the
            # worker's in-process backend matches the probe's verdict
            import jax

            jax.config.update("jax_platforms", plats)

        from .config import Config
        from .server import start_broker

        if conf_path:
            from .conf import load_conf_file

            cfg = load_conf_file(conf_path)
            for k, v in overrides.items():
                cfg.set(k, v)
            # conf-declared listeners must not EADDRINUSE across the
            # group: MQTT/WS listeners join the SO_REUSEPORT set on
            # every worker; singleton kinds (admin HTTP, explicit
            # cluster listeners) run on worker 0 only
            shared_kinds = ("mqtt", "mqtts", "ws", "wss")
            rewritten = []
            for ent in cfg.get("listeners", []):
                if ent["kind"] in shared_kinds:
                    ent = {**ent,
                           "opts": {**ent.get("opts", {}),
                                    "reuse_port": True}}
                elif idx > 0:
                    continue
                rewritten.append(ent)
            cfg.set("listeners", rewritten)
        else:
            cfg = Config(**overrides)
        if idx > 0 and cfg.get("http_enabled", False):
            # the admin HTTP endpoint is a fixed-port singleton
            cfg.set("http_enabled", False)
        broker, server = await start_broker(
            cfg, host=host, port=port,
            node_name=f"worker{idx}",
            cluster_listen=("127.0.0.1", cluster_base + idx),
            join=("127.0.0.1", cluster_base) if idx > 0 else None,
            reuse_port=True)
        if direct_base:
            # per-worker direct MQTT port (base + idx): lets operators
            # and the efficiency harness address ONE worker instead of
            # taking the kernel's SO_REUSEPORT pick — the analog of
            # dialing a specific node of a cluster. Through the
            # ListenerManager so it shows in `listener show` and stops
            # with the broker like every other listener.
            from .listeners import ListenerManager

            lm = broker.listeners or ListenerManager(broker)
            await lm.start_listener("mqtt", "127.0.0.1",
                                    direct_base + idx)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await broker.stop()
        await server.stop()

    asyncio.run(amain())


class WorkerGroup:
    """Spawn + supervise N broker worker processes on one shared port."""

    def __init__(self, n_workers: int, host: str = "127.0.0.1",
                 port: int = 1883,
                 cluster_base: int = DEFAULT_CLUSTER_BASE,
                 conf_path: Optional[str] = None,
                 direct_base: Optional[int] = None,
                 **config_overrides: Any):
        if n_workers < 1:
            raise ValueError("need at least one worker")
        self.n_workers = n_workers
        self.host = host
        self.port = port
        self.cluster_base = cluster_base
        self.conf_path = conf_path
        self.direct_base = direct_base
        self.overrides = config_overrides
        self._ctx = mp.get_context("spawn")
        self._procs: List[Any] = []
        self._stopping = False

    def _spawn(self, idx: int):
        p = self._ctx.Process(
            target=_run_worker,
            args=(idx, self.n_workers, self.host, self.port,
                  self.cluster_base, self.overrides, self.conf_path,
                  self.direct_base),
            name=f"vmq-worker{idx}", daemon=True)
        p.start()
        return p

    def start(self) -> None:
        # worker 0 is the cluster seed: it must be listening before the
        # rest dial in, so stagger it first
        self._procs = [self._spawn(0)]
        time.sleep(0.3)
        for i in range(1, self.n_workers):
            self._procs.append(self._spawn(i))

    def poll_restart(self) -> int:
        """Supervision tick: relaunch dead workers with their identity.
        Returns the number restarted."""
        if self._stopping:
            return 0
        restarted = 0
        for i, p in enumerate(self._procs):
            if not p.is_alive():
                self._procs[i] = self._spawn(i)
                restarted += 1
        return restarted

    def alive_count(self) -> int:
        return sum(1 for p in self._procs if p.is_alive())

    def stop(self, timeout: float = 10.0) -> None:
        self._stopping = True
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        deadline = time.time() + timeout
        for p in self._procs:
            p.join(max(0.1, deadline - time.time()))
            if p.is_alive():
                p.kill()
                p.join(1.0)
        self._procs = []


def main(argv: Optional[List[str]] = None) -> None:  # pragma: no cover
    ap = argparse.ArgumentParser(
        description="vernemq_tpu multi-process broker")
    ap.add_argument("--workers", type=int,
                    default=max(2, (os.cpu_count() or 2) // 2))
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=1883)
    ap.add_argument("--cluster-base", type=int,
                    default=DEFAULT_CLUSTER_BASE)
    ap.add_argument("--direct-base", type=int, default=None,
                    help="also open a per-worker MQTT port at "
                         "direct_base+idx (address ONE worker)")
    ap.add_argument("--conf", default=None)
    ap.add_argument("--allow-anonymous", action="store_true")
    args = ap.parse_args(argv)
    overrides: Dict[str, Any] = {}
    if args.allow_anonymous:
        overrides["allow_anonymous"] = True
    group = WorkerGroup(args.workers, args.host, args.port,
                        cluster_base=args.cluster_base,
                        conf_path=args.conf,
                        direct_base=args.direct_base, **overrides)
    group.start()
    print(f"started {args.workers} workers on {args.host}:{args.port}",
          file=sys.stderr, flush=True)
    try:
        while True:
            time.sleep(1.0)
            n = group.poll_restart()
            if n:
                print(f"restarted {n} dead worker(s)", file=sys.stderr,
                      flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        group.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
