"""``vernemq.conf``-style configuration file loader.

The reference translates a flat ``key = value`` file through cuttlefish
schemas (``apps/vmq_server/priv/vmq_server.schema``, 217 mappings) into app
envs. This loader keeps the same operator surface — the same knob names,
``on``/``off`` flags, ``listener.<kind>.<name>`` tree, ``plugins.<name>``
switches — mapped onto :class:`~vernemq_tpu.broker.config.Config` without
the schema-compiler machinery: values are coerced to the type of the
matching ``DEFAULTS`` entry.

Grammar (one setting per line)::

    # comment                     (also '%%' like the reference's erlang-isms)
    allow_anonymous = off
    listener.tcp.default = 127.0.0.1:1883
    listener.tcp.default.proxy_protocol = on
    listener.ssl.default = 0.0.0.0:8883
    listener.ssl.default.certfile = /etc/ssl/cert.pem
    plugins.vmq_passwd = on
    vmq_passwd.password_file = /etc/vmq.passwd

Listener kinds follow ``vmq_ranch_config.erl:224-227``: ``tcp``/``ssl``
(MQTT), ``ws``/``wss`` (WebSocket), ``http``/``https`` (admin), ``vmq``/
``vmqs`` (cluster data plane).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from .config import DEFAULTS, Config

# conf-file listener kind -> ListenerManager kind
LISTENER_KINDS = {
    "tcp": "mqtt", "ssl": "mqtts", "ws": "ws", "wss": "wss",
    "http": "http", "https": "https", "vmq": "vmq", "vmqs": "vmqs",
}

# plugin-opt spellings from the reference schemas -> our enable() kwargs
_PLUGIN_OPT_ALIASES = {
    ("vmq_passwd", "password_file"): "passwd_file",
    ("vmq_acl", "acl_file"): "acl_file",
    ("vmq_diversity", "script_dir"): "script_dir",
}

# reference metadata_plugin values -> our backend names
_METADATA_IMPLS = {"vmq_plumtree": "lww", "vmq_swc": "swc",
                   "lww": "lww", "swc": "swc"}

# reference vernemq.conf spellings -> our DEFAULTS names
_KEY_ALIASES = {
    "message_size_limit": "max_message_size",  # vmq_server.schema:62
}


class ConfError(ValueError):
    def __init__(self, lineno: int, line: str, why: str):
        super().__init__(f"conf line {lineno}: {why}: {line!r}")
        self.lineno = lineno


def _coerce(key: str, raw: str, lineno: int, line: str) -> Any:
    """Coerce ``raw`` to the type of ``DEFAULTS[key]`` (cuttlefish's
    datatype step)."""
    proto = DEFAULTS[key]
    if isinstance(proto, bool):
        low = raw.lower()
        if low in ("on", "true", "1", "yes"):
            return True
        if low in ("off", "false", "0", "no"):
            return False
        raise ConfError(lineno, line, f"expected on/off for {key}")
    if isinstance(proto, int) and not isinstance(proto, bool):
        try:
            return int(raw)
        except ValueError:
            raise ConfError(lineno, line, f"expected integer for {key}") from None
    if isinstance(proto, float):
        try:
            return float(raw)
        except ValueError:
            raise ConfError(lineno, line, f"expected number for {key}") from None
    if isinstance(proto, list):
        return [p.strip() for p in raw.split(",") if p.strip()]
    return raw


def _host_port(raw: str, lineno: int, line: str) -> Tuple[str, int]:
    host, sep, port = raw.rpartition(":")
    if not sep:
        raise ConfError(lineno, line, "expected host:port")
    try:
        return host, int(port)
    except ValueError:
        raise ConfError(lineno, line, "bad port") from None


def parse_conf(text: str) -> Dict[str, Any]:
    """Parse conf text into Config kwargs (including the ``listeners`` and
    ``plugins`` structured keys)."""
    settings: Dict[str, Any] = {}
    listeners: Dict[Tuple[str, str], Dict[str, Any]] = {}
    plugins: Dict[str, Dict[str, Any]] = {}
    plugin_opts: Dict[str, Dict[str, Any]] = {}

    # first pass: collect declared plugin names so a typo'd option tree
    # (vmq_paswd.password_file) fails loudly instead of being stashed for a
    # plugin that will never exist
    declared_plugins = set()
    for rawline in text.splitlines():
        line = rawline.strip()
        if line.startswith("plugins.") and "=" in line:
            declared_plugins.add(line.split("=")[0].strip().split(".", 1)[1])

    for lineno, rawline in enumerate(text.splitlines(), 1):
        line = rawline.strip()
        if not line or line.startswith("#") or line.startswith("%%"):
            continue
        key, sep, value = line.partition("=")
        if not sep:
            raise ConfError(lineno, line, "expected key = value")
        key = key.strip()
        value = value.strip()
        # strip a trailing comment ('cert.pem  # prod cert')
        if " #" in value:
            value = value.split(" #", 1)[0].strip()

        if key.startswith("listener."):
            parts = key.split(".")
            if len(parts) < 3 or parts[1] not in LISTENER_KINDS:
                raise ConfError(lineno, line,
                                f"unknown listener kind {parts[1] if len(parts) > 1 else '?'}")
            kind, name = parts[1], parts[2]
            ent = listeners.setdefault((kind, name), {"opts": {}})
            if len(parts) == 3:
                ent["addr"], ent["port"] = _host_port(value, lineno, line)
            else:
                opt = ".".join(parts[3:])
                ov: Any = value
                if value.lower() in ("on", "true"):
                    ov = True
                elif value.lower() in ("off", "false"):
                    ov = False
                else:
                    try:
                        ov = int(value)
                    except ValueError:
                        pass
                ent["opts"][opt] = ov
            continue

        if key.startswith("plugins."):
            name = key.split(".", 1)[1]
            low = value.lower()
            if low in ("on", "true"):
                plugins[name] = plugin_opts.setdefault(name, {})
            elif low in ("off", "false"):
                plugins.pop(name, None)
            else:
                raise ConfError(lineno, line, "expected on/off")
            continue

        head = key.split(".", 1)[0]
        if head.startswith("vmq_") and head not in DEFAULTS:
            # plugin option tree (vmq_passwd.password_file = ...)
            if head not in declared_plugins:
                raise ConfError(lineno, line,
                                f"options for undeclared plugin {head} "
                                f"(missing plugins.{head} = on?)")
            opt = key.split(".", 1)[1]
            opt = _PLUGIN_OPT_ALIASES.get((head, opt), opt)
            plugin_opts.setdefault(head, {})[opt] = value
            if head in plugins:
                plugins[head] = plugin_opts[head]
            continue

        if key == "metadata_plugin":
            impl = _METADATA_IMPLS.get(value)
            if impl is None:
                raise ConfError(lineno, line, "unknown metadata_plugin")
            settings[key] = impl
            continue

        if key in ("plugins", "listeners"):
            # loader-internal structured keys — only the dotted forms
            # (plugins.<name>, listener.<kind>.<name>) are valid conf lines
            raise ConfError(lineno, line,
                            f"'{key}' is not settable directly; use "
                            f"{'plugins.<name> = on' if key == 'plugins' else 'listener.<kind>.<name> = ip:port'}")
        key = _KEY_ALIASES.get(key, key)
        if key not in DEFAULTS:
            raise ConfError(lineno, line, f"unknown config key {key}")
        settings[key] = _coerce(key, value, lineno, line)

    if listeners:
        for (kind, name), ent in listeners.items():
            if "port" not in ent:
                # opts-only listener = typo'd name or missing address line;
                # refuse rather than bind an unconfigured ephemeral socket
                raise ConfError(
                    0, f"listener.{kind}.{name}",
                    "listener has options but no address line")
        settings["listeners"] = [
            {"kind": LISTENER_KINDS[kind], "name": name,
             "addr": ent.get("addr", "127.0.0.1"),
             "port": ent["port"], "opts": ent["opts"]}
            for (kind, name), ent in listeners.items()
        ]
    if plugins:
        settings["plugins"] = [
            {"name": n, "opts": o} for n, o in plugins.items()
        ]
    return settings


def load_conf_file(path: str) -> Config:
    with open(path, "r", encoding="utf-8") as fh:
        return Config(**parse_conf(fh.read()))
