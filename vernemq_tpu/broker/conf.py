"""``vernemq.conf``-style configuration file loader.

The reference translates a flat ``key = value`` file through cuttlefish
schemas (``apps/vmq_server/priv/vmq_server.schema``, 217 mappings) into
app envs. This loader keeps the same operator surface — the same knob
names, ``on``/``off`` flags, the full ``listener.*`` tree (global, kind
and per-name option scopes), ``plugins.<name>`` switches, duration
strings (``1w``), millisecond-typed intervals — mapped onto
:class:`~vernemq_tpu.broker.config.Config` without the schema-compiler
machinery. The mapping classification (aliases, unit conversions,
deliberate gaps, compat no-ops) lives in
:mod:`vernemq_tpu.broker.schema`; every documented reference conf line
either works or errors with a reason.

Grammar (one setting per line)::

    # comment                     (also '%%' like the reference's erlang-isms)
    allow_anonymous = off
    listener.max_connections = 10000          # global default
    listener.tcp.proxy_protocol = on          # kind-level default
    listener.tcp.default = 127.0.0.1:1883     # instance address
    listener.tcp.default.allowed_protocol_versions = 3,4,5
    listener.ssl.default.certfile = /etc/ssl/cert.pem
    plugins.vmq_passwd = on
    vmq_passwd.password_file = /etc/vmq.passwd

Listener kinds follow ``vmq_ranch_config.erl:224-227``: ``tcp``/``ssl``
(MQTT), ``ws``/``wss`` (WebSocket), ``http``/``https`` (admin), ``vmq``/
``vmqs`` (cluster data plane).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from . import schema
from .config import DEFAULTS, Config

log = logging.getLogger(__name__)

# conf-file listener kind -> ListenerManager kind (single source:
# schema.INTERNAL_KINDS, shared with the key classifier)
LISTENER_KINDS = schema.INTERNAL_KINDS

# plugin-opt spellings from the reference schemas -> our enable() kwargs
_PLUGIN_OPT_ALIASES = {
    ("vmq_passwd", "password_file"): "passwd_file",
    ("vmq_acl", "acl_file"): "acl_file",
    ("vmq_diversity", "script_dir"): "script_dir",
}

# reference metadata_plugin values -> our backend names
_METADATA_IMPLS = {"vmq_plumtree": "lww", "vmq_swc": "swc",
                   "lww": "lww", "swc": "swc"}


class ConfError(ValueError):
    def __init__(self, lineno: int, line: str, why: str):
        super().__init__(f"conf line {lineno}: {why}: {line!r}")
        self.lineno = lineno


def _strip_listish(raw: str) -> str:
    """The reference writes list values as erlang lists
    (``[vmq_metrics_http, vmq_status_http]``); tolerate the brackets."""
    s = raw.strip()
    if s.startswith("[") and s.endswith("]"):
        s = s[1:-1]
    return s


def _coerce(key: str, raw: str, lineno: int, line: str) -> Any:
    """Coerce ``raw`` to the type of ``DEFAULTS[key]`` (cuttlefish's
    datatype step), honoring the schema layer's unit conversions."""
    if key in schema.DURATION_KEYS:
        try:
            return schema.parse_duration(raw)
        except ValueError as e:
            raise ConfError(lineno, line, str(e)) from None
    proto = DEFAULTS[key]
    if isinstance(proto, bool):
        low = raw.lower()
        if low in ("on", "true", "1", "yes"):
            return True
        if low in ("off", "false", "0", "no"):
            return False
        raise ConfError(lineno, line, f"expected on/off for {key}")
    if isinstance(proto, int) and not isinstance(proto, bool):
        try:
            return int(raw)
        except ValueError:
            raise ConfError(lineno, line,
                            f"expected integer for {key}") from None
    if isinstance(proto, float):
        try:
            return float(raw)
        except ValueError:
            raise ConfError(lineno, line,
                            f"expected number for {key}") from None
    if isinstance(proto, list):
        items = [p.strip() for p in _strip_listish(raw).split(",")
                 if p.strip()]
        if key == "http_modules":
            items = [schema.HTTP_MODULE_ALIASES.get(m, m) for m in items]
        elif key == "reg_views":
            out = []
            for m in items:
                v = schema.REG_VIEW_ALIASES.get(m)
                if v is None:
                    raise ConfError(
                        lineno, line,
                        f"unknown reg view {m!r} (valid: "
                        f"{', '.join(sorted(schema.REG_VIEW_ALIASES))})")
                out.append(v)
            items = out
        return items
    return raw


def _host_port(raw: str, lineno: int, line: str) -> Tuple[str, int]:
    host, sep, port = raw.rpartition(":")
    if not sep:
        raise ConfError(lineno, line, "expected host:port")
    try:
        return host, int(port)
    except ValueError:
        raise ConfError(lineno, line, "bad port") from None


def _listener_opt_value(opt: str, value: str) -> Any:
    if opt == "allowed_protocol_versions":
        return [int(v) for v in _strip_listish(value).split(",")
                if v.strip()]
    if opt in schema.INT_LISTENER_OPTS:
        return int(value)  # ValueError -> ConfError in the caller
    if value.lower() in ("on", "true"):
        return True
    if value.lower() in ("off", "false"):
        return False
    try:
        return int(value)
    except ValueError:
        return value


def parse_conf(text: str) -> Dict[str, Any]:
    """Parse conf text into Config kwargs (including the ``listeners`` and
    ``plugins`` structured keys)."""
    settings: Dict[str, Any] = {}
    listeners: Dict[Tuple[str, str], Dict[str, Any]] = {}
    global_opts: Dict[str, Any] = {}
    kind_opts: Dict[str, Dict[str, Any]] = {}
    plugins: Dict[str, Dict[str, Any]] = {}
    plugin_opts: Dict[str, Dict[str, Any]] = {}

    # first pass: collect declared plugin names so a typo'd option tree
    # (vmq_paswd.password_file) fails loudly instead of being stashed for a
    # plugin that will never exist
    declared_plugins = set()
    for rawline in text.splitlines():
        line = rawline.strip()
        if line.startswith("plugins.") and "=" in line:
            declared_plugins.add(line.split("=")[0].strip().split(".", 1)[1])

    for lineno, rawline in enumerate(text.splitlines(), 1):
        line = rawline.strip()
        if not line or line.startswith("#") or line.startswith("%%"):
            continue
        key, sep, value = line.partition("=")
        if not sep:
            raise ConfError(lineno, line, "expected key = value")
        key = key.strip()
        value = value.strip()
        # strip a trailing comment ('cert.pem  # prod cert')
        if " #" in value:
            value = value.split(" #", 1)[0].strip()

        if key.startswith("listener."):
            try:
                scope, kind, name, opt = schema.classify_listener_key(key)
            except KeyError as e:
                raise ConfError(lineno, line, e.args[0]) from None
            try:
                if scope == "global-opt":
                    global_opts[opt] = _listener_opt_value(opt, value)
                elif scope == "kind-opt":
                    kind_opts.setdefault(kind, {})[opt] = \
                        _listener_opt_value(opt, value)
                elif scope == "addr":
                    ent = listeners.setdefault((kind, name), {"opts": {}})
                    ent["addr"], ent["port"] = _host_port(value, lineno,
                                                          line)
                else:  # name-opt
                    ent = listeners.setdefault((kind, name), {"opts": {}})
                    ent["opts"][opt] = _listener_opt_value(opt, value)
            except ConfError:
                raise
            except ValueError:
                raise ConfError(lineno, line,
                                f"bad value for listener option {opt}") \
                    from None
            continue

        if key.startswith("plugins."):
            rest = key.split(".", 1)[1]
            if "." in rest:
                # plugins.<name>.path / plugins.<name>.priority
                # (vmq_plugin.schema tree): external-plugin load options
                name, popt = rest.split(".", 1)
                if popt not in ("path", "priority"):
                    raise ConfError(lineno, line,
                                    f"unknown plugin option {popt!r} "
                                    "(valid: path, priority)")
                pv: Any = value
                if popt == "priority":
                    try:
                        pv = int(value)
                    except ValueError:
                        raise ConfError(lineno, line,
                                        "expected integer priority") \
                            from None
                plugin_opts.setdefault(name, {})[popt] = pv
                if name in plugins:
                    plugins[name] = plugin_opts[name]
                continue
            name = rest
            low = value.lower()
            if low in ("on", "true"):
                plugins[name] = plugin_opts.setdefault(name, {})
            elif low in ("off", "false"):
                plugins.pop(name, None)
            else:
                raise ConfError(lineno, line, "expected on/off")
            continue

        head = key.split(".", 1)[0]
        if (head.startswith("vmq_") and head not in DEFAULTS
                and key not in schema.FLAT_ALIASES):
            # plugin option tree (vmq_passwd.password_file = ...)
            if head not in declared_plugins:
                raise ConfError(lineno, line,
                                f"options for undeclared plugin {head} "
                                f"(missing plugins.{head} = on?)")
            opt = key.split(".", 1)[1]
            opt = _PLUGIN_OPT_ALIASES.get((head, opt), opt)
            plugin_opts.setdefault(head, {})[opt] = value
            if head in plugins:
                plugins[head] = plugin_opts[head]
            continue

        if key == "vmq_swc.db_backend" or key == "swc_db_backend":
            # reference engine names map onto the default native engine;
            # kvstore/bucketed select ours explicitly (cluster/swc_db.py)
            val = {"leveldb": "kvstore", "rocksdb": "kvstore",
                   "leveled": "kvstore", "kvstore": "kvstore",
                   "bucketed": "bucketed"}.get(value)
            if val is None:
                raise ConfError(lineno, line, "unknown swc db backend")
            settings["swc_db_backend"] = val
            continue

        if key == "metadata_plugin":
            impl = _METADATA_IMPLS.get(value)
            if impl is None:
                raise ConfError(lineno, line, "unknown metadata_plugin")
            settings[key] = impl
            continue

        if key in ("plugins", "listeners"):
            # loader-internal structured keys — only the dotted forms
            # (plugins.<name>, listener.<kind>.<name>) are valid conf lines
            raise ConfError(lineno, line,
                            f"'{key}' is not settable directly; use "
                            f"{'plugins.<name> = on' if key == 'plugins' else 'listener.<kind>.<name> = ip:port'}")
        gap = schema.GAPS.get(key)
        if gap is not None:
            raise ConfError(lineno, line, f"deliberate gap: {gap}")
        key = schema.FLAT_ALIASES.get(key, key)
        if key not in DEFAULTS:
            raise ConfError(lineno, line, f"unknown config key {key}")
        if key in schema.COMPAT_NOOPS:
            log.info("conf: %s accepted for compatibility: %s",
                     key, schema.COMPAT_NOOPS[key])
        coerced = _coerce(key, value, lineno, line)
        if key in schema.MS_TO_SECONDS:
            # reference datatype is milliseconds; internal knob is
            # seconds. 0 stays 0 (= disabled in the reference schema);
            # any non-zero value rounds to at least 1s
            if isinstance(DEFAULTS[key], float):
                coerced = coerced / 1000.0
            elif coerced <= 0:
                coerced = 0
            else:
                coerced = max(1, int(round(coerced / 1000.0)))
        settings[key] = coerced

    if (global_opts or kind_opts) and not listeners:
        # option defaults with no listener address line are legal
        # cuttlefish (they set app envs), but here nothing will consume
        # them — warn loudly instead of leaving the operator's cap inert
        orphan = list(global_opts) + [k for d in kind_opts.values()
                                      for k in d]
        log.warning("conf: listener option default(s) %s given but no "
                    "listener address line (listener.<kind>.<name> = "
                    "ip:port) — they apply to no listener",
                    ", ".join(sorted(set(orphan))))
    if listeners or global_opts or kind_opts:
        for (kind, name), ent in listeners.items():
            if "port" not in ent:
                # opts-only listener = typo'd name or missing address line;
                # refuse rather than bind an unconfigured ephemeral socket
                raise ConfError(
                    0, f"listener.{kind}.{name}",
                    "listener has options but no address line")
        settings["listeners"] = [
            {"kind": LISTENER_KINDS[kind], "name": name,
             "addr": ent.get("addr", "127.0.0.1"), "port": ent["port"],
             # option precedence: instance > kind default > global default
             "opts": {**global_opts, **kind_opts.get(kind, {}),
                      **ent["opts"]}}
            for (kind, name), ent in listeners.items()
        ]
    if plugins:
        settings["plugins"] = [
            {"name": n, "opts": o} for n, o in plugins.items()
        ]
    return settings


def load_conf_file(path: str) -> Config:
    with open(path, "r", encoding="utf-8") as fh:
        return Config(**parse_conf(fh.read()))
