"""System monitor: event-loop health + memory watermark + overload signal.

Plays the role of ``vmq_sysmon`` (224 LoC, riak_sysmon-based): the
reference watches the BEAM for long_gc / long_schedule / busy_port events
and forces a GC on large_heap (``vmq_sysmon_handler.erl:221``). The
asyncio equivalents:

- **loop lag**: a periodic sleep measures scheduling drift — the analog of
  long_schedule. Sustained lag beyond the threshold sets the broker's
  ``overloaded`` flag, which the session layer turns into read throttling
  (the load-shedding role of the reference's throttle return,
  ``vmq_ranch.erl:198-203``).
- **memory watermark**: RSS read from ``/proc/self/statm``; crossing the
  high watermark triggers ``gc.collect()`` (the forced-GC response to
  large_heap) and counts a metric.

CRL refresh (``vmq_crl_srv.erl``): TLS listeners configured with a CRL
file get it re-read periodically so revocations take effect without a
restart; each refresh rebuilds the listener's SSLContext verify store.
"""

from __future__ import annotations

import asyncio
import gc
import logging
import os
import time
from typing import Any, Dict, Optional

log = logging.getLogger("vernemq_tpu.sysmon")

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


class Sysmon:
    def __init__(self, broker, interval: float = 1.0,
                 lag_threshold: float = 0.25,
                 memory_high_watermark: int = 0,
                 overload_cooldown: float = 5.0,
                 lag_exit_ratio: float = 0.5):
        self.broker = broker
        self.interval = interval
        self.lag_threshold = lag_threshold
        # bytes; 0 = no watermark (the reference defaults large_heap off
        # too unless configured)
        self.memory_high_watermark = memory_high_watermark
        self.overload_cooldown = overload_cooldown
        # hysteresis: overload ENTERS at lag_threshold but only EXITS
        # once lag stays below lag_threshold * lag_exit_ratio for a full
        # cooldown — lag hovering at the boundary (the common overload
        # shape: shedding lowers lag just below the threshold, which
        # unsheds, which raises lag ...) must not flap the flag
        self.lag_exit_ratio = lag_exit_ratio
        self.lag_events = 0
        self.overload_extends = 0  # cooldowns re-armed by boundary lag
        self.gc_forced = 0
        self.last_lag = 0.0
        self.overloaded_until = 0.0
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    @property
    def overloaded(self) -> bool:
        return time.monotonic() < self.overloaded_until

    def observe_lag(self, lag: float) -> None:
        """Fold one loop-lag sample into the overload state (split out
        of the sampling loop so tests drive the hysteresis directly)."""
        self.last_lag = lag
        now = time.monotonic()
        if lag > self.lag_threshold:
            self.lag_events += 1
            self.overloaded_until = now + self.overload_cooldown
            self.broker.metrics.incr("sysmon_long_schedule")
            log.warning("event loop lag %.3fs over threshold %.3fs — "
                        "shedding load for %.1fs",
                        lag, self.lag_threshold, self.overload_cooldown)
        elif (self.overloaded
              and lag > self.lag_threshold * self.lag_exit_ratio):
            # boundary lag while shedding: keep the window armed (no
            # log/metric spam — it's the same overload episode)
            self.overload_extends += 1
            self.overloaded_until = max(self.overloaded_until,
                                        now + self.overload_cooldown)

    async def _run(self) -> None:
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.interval)
            lag = time.monotonic() - t0 - self.interval
            self.observe_lag(lag)
            gov = getattr(self.broker, "overload", None)
            if gov is not None:
                # feed the governor's lag-EWMA signal (it recomputes the
                # level inline so the L1 response lands this sample)
                gov.observe_lag(lag)
            ws = getattr(self.broker, "worker_stats", None)
            if ws is not None:
                # multi-process front end: every lag sample also lands
                # in this worker's shared slot — the per-worker
                # loop-lag p99 bench config 11 and `workers show` read
                try:
                    ws.push_lag(self.broker.worker_index, lag)
                except Exception:
                    pass
            if self.memory_high_watermark:
                rss = rss_bytes()
                if gov is not None:
                    gov.observe_rss(rss, self.memory_high_watermark)
                if rss > self.memory_high_watermark:
                    self.gc_forced += 1
                    self.broker.metrics.incr("sysmon_large_heap")
                    gc.collect()  # forced GC (vmq_sysmon_handler.erl:221)

    def status(self) -> Dict[str, Any]:
        return {
            "last_loop_lag_s": round(self.last_lag, 4),
            "lag_events": self.lag_events,
            "overload_extends": self.overload_extends,
            "gc_forced": self.gc_forced,
            "overloaded": self.overloaded,
            "rss_bytes": rss_bytes(),
        }


class CrlRefresher:
    """Periodic CRL re-load for TLS listeners (vmq_crl_srv.erl: periodic
    fetch keyed by ``crl_refresh_interval``). File-based: operators drop an
    updated CRL PEM in place; we rebuild each listener's verify store."""

    def __init__(self, broker, interval: float = 60.0):
        self.broker = broker
        self.interval = interval
        self.refreshes = 0
        self._task: Optional[asyncio.Task] = None
        self._mtimes: Dict[str, float] = {}

    def start(self) -> None:
        try:
            self.refresh()  # pick up listeners that pre-date the refresher
        except Exception:
            log.exception("initial CRL refresh failed")
        self._task = asyncio.get_event_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                self.refresh()
            except Exception:
                log.exception("CRL refresh failed")

    def refresh(self) -> int:
        """Re-load changed CRL files into their listeners' SSL contexts;
        returns how many listeners were refreshed."""
        manager = self.broker.listeners
        if manager is None:
            return 0
        n = 0
        for rec in manager.listener_records():
            crl_file = rec.get("opts", {}).get("crl_file")
            ctx = rec.get("ssl_context")
            if not crl_file or ctx is None:
                continue
            try:
                mtime = os.stat(crl_file).st_mtime
            except OSError:
                continue
            if self._mtimes.get(crl_file) == mtime:
                continue
            try:
                import ssl

                ctx.load_verify_locations(cafile=crl_file)
                ctx.verify_flags |= ssl.VERIFY_CRL_CHECK_LEAF
                self._mtimes[crl_file] = mtime
                self.refreshes += 1
                n += 1
                log.info("reloaded CRL %s", crl_file)
            except Exception:
                log.exception("loading CRL %s failed", crl_file)
        return n
