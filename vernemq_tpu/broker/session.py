"""MQTT session FSM — one implementation parameterized by protocol level.

Mirrors the reference session FSMs (``vmq_mqtt_fsm.erl`` for 3.1/3.1.1,
``vmq_mqtt5_fsm.erl`` for 5.0). Like the reference, the FSM has no process
of its own — it runs inside the connection's socket loop (here: the asyncio
connection task), with queue deliveries arriving as callbacks:

- CONNECT pipeline ``check_connect → check_client_id → check_user →
  check_will`` (vmq_mqtt_fsm.erl:487-604), auth via the
  ``auth_on_register(_m5)`` all_till_ok chain with modifier support;
- PUBLISH dispatch by QoS (vmq_mqtt_fsm.erl:748-866): QoS1 route+PUBACK,
  QoS2 route-on-first-PUBLISH, PUBREC, dedup until PUBREL, PUBCOMP;
- outgoing QoS1/2 tracked in ``waiting_acks`` with retry w/ DUP
  (vmq_mqtt_fsm.erl:294-355,1077-1101) and a ``max_inflight_messages``
  window (vmq_mqtt_fsm.erl:65);
- keepalive enforcement at 1.5× (vmq_mqtt_fsm.erl:422-432);
- session takeover (dup CONNECT) disconnects the old session, v5 with
  reason 0x8E;
- MQTT5: topic aliases both directions (vmq_mqtt5_fsm.erl:90-93), flow
  control receive-maximum (:97-100), session/message expiry (:69),
  enhanced AUTH via the on_auth_m5 hook (:78,330-353), will delay.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Tuple

log = logging.getLogger("vernemq_tpu.session")

from ..filters.predicate import FilterError, parse_filter, split_filter_suffix
from ..protocol import codec_v4, codec_v5, fastpath
from ..protocol import topic as T
from ..protocol.types import (
    PROTO_5,
    PUBACK as PUBACK_T,
    PUBCOMP as PUBCOMP_T,
    PUBREC as PUBREC_T,
    PUBREL as PUBREL_T,
    RC_GRANTED_QOS0,
    RC_NOT_AUTHORIZED,
    RC_SERVER_UNAVAILABLE,
    RC_NO_MATCHING_SUBSCRIBERS,
    RC_NO_SUBSCRIPTION_EXISTED,
    RC_PACKET_ID_NOT_FOUND,
    RC_SERVER_BUSY,
    RC_SERVER_MOVED,
    RC_SESSION_TAKEN_OVER,
    RC_SUCCESS,
    RC_USE_ANOTHER_SERVER,
    RC_RECEIVE_MAX_EXCEEDED,
    RC_TOPIC_ALIAS_INVALID,
    RC_UNSPECIFIED_ERROR,
    Auth,
    Connack,
    Connect,
    Disconnect,
    Frame,
    ParseError,
    Pingreq,
    Pingresp,
    Puback,
    Pubcomp,
    Publish,
    Pubrec,
    Pubrel,
    SubOpts,
    Suback,
    Subscribe,
    Unsuback,
    Unsubscribe,
    Will,
    reason_name,
)
from .message import Msg, SubscriberId
from .plugins import HookError
from .queue import QueueOpts

if TYPE_CHECKING:
    from .broker import Broker

CONNACK_V4_FROM_RC = {
    # map v5-style internal reasons onto v4 return codes
    RC_UNSPECIFIED_ERROR: 3,
    RC_NOT_AUTHORIZED: 5,
}


class SessionError(Exception):
    pass


class Session:
    """One per live client connection."""

    def __init__(self, broker: "Broker", transport: "Transport", proto_ver: int,
                 peer: Tuple[str, int] = ("", 0), mountpoint: str = ""):
        self.broker = broker
        self.transport = transport
        self.proto_ver = proto_ver
        self.codec = codec_v5 if proto_ver == PROTO_5 else codec_v4
        self.peer = peer
        self.mountpoint = mountpoint
        self.client_id: str = ""
        self.sid: Optional[SubscriberId] = None
        self.username: Optional[str] = None
        self.connected = False
        self.clean_start = True
        self.keepalive = 0
        self.will: Optional[Will] = None
        self.queue = None
        # outgoing qos1/2: pid -> [kind, msg, ts, dup_sent]; kind: 'puback'|'pubrec'|'pubcomp'
        self.waiting_acks: Dict[int, List[Any]] = {}
        self.pending: List[Msg] = []  # deliveries waiting for an inflight slot
        self._next_pid = 0
        self.awaiting_rel: Dict[int, float] = {}  # incoming qos2 pids
        self.last_activity = time.monotonic()
        # wakes a rate-throttled reader early: set on the notify_ready /
        # window-freed edge (_pump_pending) and at close
        self._throttle_wake = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        self.closed = False
        self.close_reason = "normal"
        # v5 state
        self.session_expiry = 0
        # inbound alias -> (words, topic_str): the wire fast path needs
        # the validated string without re-unwording per publish
        self.topic_alias_in: Dict[int, Tuple[Tuple[str, ...], str]] = {}
        # outbound words -> alias, LRU-ordered (oldest first): a full
        # table evicts the least-recently-SENT topic and re-establishes
        # its alias number for the new hot topic (MQTT5 3.3.2.3.4 lets
        # the sender remap an alias mid-connection)
        self.topic_alias_out: "OrderedDict[Tuple[str, ...], int]" = \
            OrderedDict()
        self.topic_alias_max_out = 0  # client's limit for broker→client aliases
        self.receive_max_out = 65535  # client's receive maximum (broker→client inflight cap)
        self.max_packet_out = 0  # client's maximum_packet_size; 0 = unlimited
        self.max_frame_in = 0    # the listener's enforced inbound frame cap
        self._recv_max_announced = 0  # receive_maximum sent in OUR CONNACK
        self.request_problem_info = True
        self.auth_method: Optional[str] = None
        self._in_enhanced_auth = False
        self._pending_connect: Optional[Connect] = None
        # wire fast path (protocol/fastpath.py): per-connection topic
        # admission cache — raw topic bytes -> (words, topic_str), so a
        # telemetry stream repeating a handful of topics validates each
        # once and admits the rest with zero frame/Msg objects
        self._wire_topic_cache: Dict[bytes, Tuple[Tuple[str, ...], str]] = {}

    # ------------------------------------------------------------------ IO

    def send(self, frame: Frame) -> None:
        if self.closed:
            return
        if self.broker.tracer is not None:  # session tracer tap (vmq_tracer)
            self.broker.trace_frame("out", self.mountpoint, self.client_id, frame)
        data = self.codec.serialise(frame)
        self.transport.write(data)
        self.broker.metrics.incr("bytes_sent", len(data))

    def _metric_in(self, frame: Frame) -> None:
        m = _IN_METRIC.get(type(frame))
        if m:
            self.broker.metrics.incr(m)
        if type(frame) is Disconnect and self.proto_ver == PROTO_5:
            # per-reason family (vmq_metrics mqtt5_disconnect_recv_def)
            self.broker.metrics.incr_labeled(
                "mqtt_disconnect_received", mqtt_version="5",
                reason_code=reason_name(frame.reason_code,
                                        zero="normal_disconnect"))

    # ---------------------------------------------------------- CONNECT

    async def handle_connect(self, f: Connect) -> bool:
        """CONNECT pipeline; returns True if session established."""
        self.broker.metrics.incr("mqtt_connect_received")
        cfg = self.broker.config
        self.keepalive = f.keepalive
        self.clean_start = f.clean_start
        self.will = f.will
        self.username = f.username

        # check_client_id (vmq_mqtt_fsm.erl:514-560)
        client_id = f.client_id
        if not client_id:
            if not f.clean_start and self.proto_ver != PROTO_5:
                await self._connack_fail(2, RC_CLIENT_ID_NOT_VALID)
                return False
            client_id = f"auto-{id(self):x}-{int(time.time() * 1000) & 0xFFFFFF:x}"
            self._assigned_client_id = client_id
        else:
            self._assigned_client_id = None
        if len(client_id) > cfg.max_client_id_size:
            await self._connack_fail(2, RC_CLIENT_ID_NOT_VALID)
            return False
        self.client_id = client_id
        self.sid = (self.mountpoint, client_id)
        if self.broker.tracer is not None:
            # trace the CONNECT of a newly-arriving traced client (the
            # trace_fun injected into FSM init, vmq_mqtt_fsm.erl:116-118)
            self.broker.trace_frame("in", self.mountpoint, client_id, f,
                                    session_start=True)

        if self.proto_ver == PROTO_5:
            self.session_expiry = f.properties.get("session_expiry_interval", 0)
            cap = cfg.max_session_expiry_interval
            if cap and self.session_expiry > cap:
                self.session_expiry = cap
            self.topic_alias_max_out = f.properties.get("topic_alias_maximum", 0)
            if cfg.topic_alias_max_broker:
                self.topic_alias_max_out = min(self.topic_alias_max_out,
                                               cfg.topic_alias_max_broker)
            # default when the client announces none: the reference's
            # receive_max_client knob (vmq_server.schema), not a
            # hardcoded 65535 — an operator capping broker->client
            # inflight for quiet v5 clients gets the cap they set
            self.receive_max_out = f.properties.get(
                "receive_maximum", cfg.receive_max_client)
            # client's packet-size ceiling for broker->client frames
            # (vmq_mqtt5_fsm.erl:159-161 maybe_get_maximum_packet_size,
            # min'd with the broker's own configured cap)
            self.max_packet_out = f.properties.get("maximum_packet_size", 0)
            cfg_mps = cfg.get("m5_max_packet_size", 0)
            if cfg_mps:
                self.max_packet_out = (min(self.max_packet_out, cfg_mps)
                                       if self.max_packet_out else cfg_mps)
            self.request_problem_info = bool(f.properties.get("request_problem_information", 1))
            self.auth_method = f.properties.get("authentication_method")

        # enhanced auth (MQTT5 AUTH exchange, vmq_mqtt5_fsm.erl:330-353)
        if self.auth_method is not None:
            if not self.broker.hooks.has("on_auth_m5"):
                # a method the broker does not support must be rejected
                # with 0x8C, not silently ignored (MQTT5 4.12)
                self.broker.metrics.incr("mqtt_connect_error")
                self.send(Connack(session_present=False, rc=0x8C))
                self._count_connack(0x8C)
                await self.close("bad_authentication_method")
                return False
            self._pending_connect = f
            res = await self._run_enhanced_auth(f.properties.get("authentication_data"))
            if res == "continue":
                return True  # wait for client AUTH frames
            if res != "ok":
                return False
            # fallthrough: auth completed in one round

        return await self._finish_connect(f)

    async def _finish_connect(self, f: Connect) -> bool:
        cfg = self.broker.config
        # check_user → auth_on_register chain (vmq_mqtt_fsm.erl:606-650)
        hook = "auth_on_register_m5" if self.proto_ver == PROTO_5 else "auth_on_register"
        modifiers: Dict[str, Any] = {}
        try:
            res = await self.broker.hooks.all_till_ok(
                hook, self.peer, self.sid, f.username, f.password, f.clean_start
            )
            if isinstance(res, tuple):
                modifiers = res[1]
        except HookError as e:
            if e.reason == "no_matching_hook_found":
                if not cfg.allow_anonymous:
                    await self._connack_fail(5, RC_NOT_AUTHORIZED)
                    return False
            else:
                self.broker.metrics.incr("mqtt_connect_error")
                rc = 4 if e.reason == "invalid_credentials" else 5
                await self._connack_fail(rc, RC_NOT_AUTHORIZED)
                return False
        # apply modifiers (per-session overrides, vmq_mqtt_fsm.erl:606-650)
        if "mountpoint" in modifiers:
            self.mountpoint = modifiers["mountpoint"]
            self.sid = (self.mountpoint, self.client_id)
        if "clean_session" in modifiers:
            self.clean_start = modifiers["clean_session"]

        # check_will (vmq_mqtt_fsm.erl:581-604)
        if self.will is not None:
            try:
                wt = T.validate_topic("publish", self.will.topic)
                self.will_topic_words = tuple(wt)
            except T.TopicError:
                await self._connack_fail(2, RC_TOPIC_NAME_INVALID)
                return False
            try:
                await self.broker.auth_publish(
                    self.sid, self.username, self.will_topic_words,
                    self.will.payload, self.will.qos, self.will.retain,
                    self.proto_ver,
                )
            except HookError:
                await self._connack_fail(5, RC_NOT_AUTHORIZED)
                return False

        # session takeover (vmq_mqtt_fsm check_client_id dup connect) —
        # unless multiple sessions per ClientId are allowed, in which case
        # the new session joins the existing queue (vmq_queue multi-session
        # fanout/balance, vmq_queue.erl:826-835)
        multi = (cfg.allow_multiple_sessions
                 and self.broker.registry.get_queue(self.sid) is not None)
        if not multi:
            await self.broker.takeover(self.sid, self)
        self.broker.cancel_delayed_will(self.sid)

        # register queue
        persistent = (
            (self.proto_ver == PROTO_5 and self.session_expiry > 0)
            or (self.proto_ver != PROTO_5 and not self.clean_start)
        )
        qopts = QueueOpts(
            clean_session=not persistent,
            max_offline_messages=cfg.max_offline_messages,
            max_online_messages=cfg.max_online_messages,
            deliver_mode=cfg.queue_deliver_mode,
            queue_type=cfg.queue_type,
            session_expiry=self.session_expiry,
        )
        if multi:
            # a joining extra session must not clean-start the shared queue
            # NOR flip it volatile: the queue stays persistent while ANY of
            # its sessions is persistent (register_subscriber overwrites
            # existing.opts with what we pass)
            shared = self.broker.registry.get_queue(self.sid)
            if shared is not None:
                qopts.clean_session = (qopts.clean_session
                                       and shared.opts.clean_session)
                qopts.session_expiry = max(qopts.session_expiry,
                                           shared.opts.session_expiry)
        try:
            # cluster-serialized per-SubscriberId (vmq_reg.erl:115-126 via
            # vmq_reg_sync); degrades to the direct call single-node
            self.queue, session_present = \
                await self.broker.registry.register_subscriber_synced(
                    self.sid, self.clean_start and not multi, qopts
                )
        except RuntimeError:
            # netsplit CAP gate (vmq_reg.erl:65-70): CONNACK server
            # unavailable instead of dropping the socket
            await self._connack_fail(3, RC_SERVER_UNAVAILABLE)
            return False
        self.connected = True
        self.broker.sessions[self.sid] = self

        # CONNACK
        props: Dict[str, Any] = {}
        if self.proto_ver == PROTO_5:
            if self._assigned_client_id:
                props["assigned_client_identifier"] = self._assigned_client_id
            if cfg.receive_max_broker:
                props["receive_maximum"] = cfg.receive_max_broker
                # enforce what THIS session announced, not the live cfg:
                # a runtime `config set receive_max_broker` must not turn
                # compliant in-flight clients into 0x93 disconnects (same
                # announced-vs-enforced discipline as max_frame_in above)
                self._recv_max_announced = cfg.receive_max_broker
            if cfg.topic_alias_max_client:
                props["topic_alias_maximum"] = cfg.topic_alias_max_client
            if self.max_frame_in:
                # announce the inbound ceiling the listener is ACTUALLY
                # parsing with (MQTT5 3.2.2.3.6) — not the live config
                # value, which can drift from the listener's snapshot
                # (runtime config set, per-listener override). The
                # parser caps remaining length, so total accepted bytes
                # run up to ~5B over: the lenient direction — nothing
                # the broker promised to accept is ever rejected
                props["maximum_packet_size"] = self.max_frame_in
            if cfg.max_session_expiry_interval and self.session_expiry != \
                    (self._pending_connect or f).properties.get("session_expiry_interval", 0):
                props["session_expiry_interval"] = self.session_expiry
            if self.auth_method is not None and \
                    getattr(self, "_enhanced_done", False):
                # enhanced auth RAN: CONNACK echoes the method and the
                # final server data (MQTT5 3.2.2.3.17; vmq_mqtt5_fsm AUTH)
                props["authentication_method"] = self.auth_method
                if getattr(self, "_auth_success_data", None):
                    props["authentication_data"] = self._auth_success_data
        self.send(Connack(session_present=session_present, rc=0, properties=props))
        self._count_connack(0)
        # attach AFTER the CONNACK so offline-backlog flush serialises behind
        # it on the wire (the reference's queue wakeup happens post-CONNACK)
        self.queue.add_session(self, self._queue_deliver)
        self.broker.hooks_fire_all(
            "on_register", self.peer, self.sid, self.username
        )
        self._start_timers()
        return True

    async def _run_enhanced_auth(self, data: Optional[bytes]) -> str:
        """on_auth_m5 hook round (vmq_mqtt5_fsm enhanced auth)."""
        try:
            res = await self.broker.hooks.all_till_ok(
                "on_auth_m5", self.sid, self.auth_method, data
            )
        except HookError:
            self.broker.metrics.incr("mqtt_connect_error")
            if self.connected:
                # re-auth on an established session: DISCONNECT, never a
                # second CONNACK (MQTT5 4.12.1)
                self.send(Disconnect(reason_code=0x8C))
                self._count_disconnect_sent(0x8C)
            else:
                self.send(Connack(session_present=False, rc=0x8C))
                self._count_connack(0x8C)
            await self.close("bad_authentication_method")
            return "error"
        if isinstance(res, tuple):
            mods = res[1]
            out_data = mods.get("authentication_data")
            if mods.get("continue_auth"):
                self._in_enhanced_auth = True
                self.send(Auth(reason_code=0x18, properties={
                    "authentication_method": self.auth_method,
                    **({"authentication_data": out_data} if out_data else {}),
                }))
                self.broker.metrics.incr("mqtt_auth_sent")
                return "continue"
            self._auth_success_data = out_data
        self._enhanced_done = True
        return "ok"

    #: v4 CONNACK return code → per-reason counter (vmq_metrics.erl:655-660)
    _V4_CONNACK_COUNTER = {
        0: "mqtt_connack_accepted_sent",
        1: "mqtt_connack_unacceptable_protocol_sent",
        2: "mqtt_connack_identifier_rejected_sent",
        3: "mqtt_connack_server_unavailable_sent",
        4: "mqtt_connack_bad_credentials_sent",
        5: "mqtt_connack_not_authorized_sent",
    }
    #: and the reference's v4 return_code label strings (m4_connack_labels)
    _V4_CONNACK_LABEL = {
        0: "success", 1: "unsupported_protocol_version",
        2: "client_identifier_not_valid", 3: "server_unavailable",
        4: "bad_username_or_password", 5: "not_authorized",
    }

    def _count_connack(self, rc: int) -> None:
        """Flat family counter + per-reason accounting for one CONNACK
        (the reference keeps both: the v4 per-reason counters AND the
        reason-labeled family, vmq_metrics.erl:655-660 + :787-813)."""
        m = self.broker.metrics
        m.incr("mqtt_connack_sent")
        if self.proto_ver == PROTO_5:
            m.incr_labeled("mqtt_connack_sent", mqtt_version="5",
                           reason_code=reason_name(rc))
        else:
            flat = self._V4_CONNACK_COUNTER.get(rc)
            if flat:
                m.incr(flat)
            m.incr_labeled("mqtt_connack_sent", mqtt_version="4",
                           return_code=self._V4_CONNACK_LABEL.get(
                               rc, f"rc_{rc}"))

    async def _connack_fail(self, v4_rc: int, v5_rc: int) -> None:
        self.broker.metrics.incr("mqtt_connect_error")
        rc = v5_rc if self.proto_ver == PROTO_5 else v4_rc
        self.send(Connack(session_present=False, rc=rc))
        self._count_connack(rc)
        await self.close("connack_fail", send_will=False)

    # ------------------------------------------------------- frame dispatch

    async def handle_frame(self, frame: Frame) -> None:
        self.last_activity = time.monotonic()
        self._metric_in(frame)
        if self.broker.tracer is not None:
            self.broker.trace_frame("in", self.mountpoint, self.client_id, frame)
        t = type(frame)
        if t is Publish:
            await self._handle_publish(frame)
        elif t is Puback:
            self._handle_puback(frame)
        elif t is Pubrec:
            self._handle_pubrec(frame)
        elif t is Pubrel:
            self._handle_pubrel(frame)
        elif t is Pubcomp:
            self._handle_pubcomp(frame)
        elif t is Subscribe:
            await self._handle_subscribe(frame)
        elif t is Unsubscribe:
            await self._handle_unsubscribe(frame)
        elif t is Pingreq:
            self.send(Pingresp())
            self.broker.metrics.incr("mqtt_pingresp_sent")
        elif t is Disconnect:
            # v5 rc 0x04 = disconnect with will
            send_will = self.proto_ver == PROTO_5 and frame.reason_code == 0x04
            if self.proto_ver == PROTO_5:
                sei = frame.properties.get("session_expiry_interval")
                if sei is not None:
                    cap = self.broker.config.max_session_expiry_interval
                    if cap and sei > cap:
                        sei = cap
                    self.session_expiry = sei
                    if self.queue is not None:
                        self.queue.opts.session_expiry = sei
                        # sei == 0 ends the session when the network
                        # connection closes (MQTT5 3.14.2.2.2)
                        self.queue.opts.clean_session = sei == 0
            await self.close("client_disconnect", send_will=send_will)
        elif t is Auth:
            await self._handle_auth(frame)
        elif t is Connect:
            await self.close("protocol_violation_dup_connect")
        else:
            await self.close("unexpected_frame")

    # ---------------------------------------------------------- PUBLISH in

    async def _handle_publish(self, f: Publish) -> None:
        cfg = self.broker.config
        # flight recorder: the ONE 1-in-N sample decision, made here at
        # admission; the trace context rides the whole routing path
        # (including the match-service fold envelope) and yields ONE
        # record with per-stage deltas (observability/recorder.py)
        trace = self.broker.recorder.admit(self.client_id or "",
                                           f.topic, f.qos)
        # NOTE max_message_size is enforced at the PARSER as a frame cap
        # for every packet type (vmq_parser.erl semantics; server.py
        # steady-state loop incrs mqtt_invalid_msg_size_error and sends
        # v5 DISCONNECT 0x95) — an oversize PUBLISH never reaches here
        if not self.broker.metrics.check_rate(self.sid, cfg.max_message_rate):
            # the reference THROTTLES rather than kills the session: the
            # socket loop pauses reads (vmq_mqtt_fsm.erl:243-262 →
            # vmq_ranch.erl:198-203); awaiting here backpressures the
            # reader loop the same way. Instead of the old blind 1.0s
            # sleep regardless of how much window remained, wait only the
            # REMAINDER of the rate window — waking early when session
            # capacity frees (the notify_ready edge via _pump_pending) or
            # the session closes — and re-check the budget on wake.
            self.broker.metrics.incr("mqtt_publish_throttled")
            while not self.closed:
                self._throttle_wake.clear()
                try:
                    await asyncio.wait_for(
                        self._throttle_wake.wait(),
                        self.broker.metrics.rate_wait_s(self.sid))
                except asyncio.TimeoutError:
                    pass
                if self.broker.metrics.check_rate(self.sid,
                                                  cfg.max_message_rate):
                    break
            if self.closed:
                return  # closed while parked: don't route a dead session
        gov = self.broker.overload
        if gov is not None:
            # graded overload shedding (robustness/overload.py): L1
            # proportional read throttle + L2 token bucket, replacing
            # the old fixed 0.1s sleep for every producer; in binary
            # mode this applies the legacy fixed pause. The governor
            # counts parked sessions while they sleep (its demand
            # signal for graceful de-escalation).
            if await gov.throttle_publish(self.sid) > 0:
                self.broker.metrics.incr("mqtt_publish_throttled")
            if self.closed:
                return  # closed (takeover/disconnect) while parked
            if f.qos == 0 and gov.shed_qos0():
                # L2+: QoS0 fanout shed at the admission gate — no ack
                # owed, the cheapest work in the broker to drop
                return
        elif self.broker.sysmon is not None and self.broker.sysmon.overloaded:
            # no governor wired (embedding/tests): legacy binary shed
            self.broker.metrics.incr("mqtt_publish_throttled")
            await asyncio.sleep(0.1)
        # incoming flow control: QoS2 publishes hold a receive credit
        # until their PUBREL (awaiting_rel IS fc_receive_cnt); at the
        # announced receive_maximum the next QoS>0 publish is a protocol
        # error (vmq_mqtt5_fsm.erl:1215-1218 fc_incr_cnt -> error ->
        # recv_max_exceeded). A retransmitted QoS2 pid already holding a
        # credit does not count twice.
        if (self.proto_ver == PROTO_5 and f.qos > 0
                and self._recv_max_announced
                and len(self.awaiting_rel) >= self._recv_max_announced
                and not (f.qos == 2
                         and f.packet_id in self.awaiting_rel)):
            self.broker.metrics.incr("mqtt_publish_error")
            await self._disconnect_v5(RC_RECEIVE_MAX_EXCEEDED)
            return
        # v5 topic alias resolution (vmq_mqtt5_fsm.erl:90-93)
        topic_str = f.topic
        words: Optional[Tuple[str, ...]] = None
        if self.proto_ver == PROTO_5:
            alias = f.properties.get("topic_alias")
            if alias is not None:
                if alias == 0 or (cfg.topic_alias_max_client and
                                  alias > cfg.topic_alias_max_client):
                    await self._disconnect_v5(RC_TOPIC_ALIAS_INVALID)
                    return
                if topic_str:
                    try:
                        words = tuple(T.validate_topic("publish", topic_str))
                    except T.TopicError:
                        await self._pub_nack(f, RC_TOPIC_NAME_INVALID)
                        return
                    self.topic_alias_in[alias] = (words, topic_str)
                else:
                    ent = self.topic_alias_in.get(alias)
                    if ent is None:
                        await self._disconnect_v5(RC_TOPIC_ALIAS_INVALID)
                        return
                    words = ent[0]
        if words is None:
            try:
                words = tuple(T.validate_topic("publish", topic_str))
            except T.TopicError:
                self.broker.metrics.incr("mqtt_publish_error")
                if self.proto_ver == PROTO_5 and f.qos > 0:
                    await self._pub_nack(f, RC_TOPIC_NAME_INVALID)
                else:
                    await self.close("invalid_topic")
                return

        # auth_on_publish chain; modifiers may rewrite topic/payload/qos
        try:
            mods = await self.broker.auth_publish(
                self.sid, self.username, words, f.payload, f.qos, f.retain,
                self.proto_ver, f.properties,
            )
        except HookError:
            self.broker.metrics.incr("mqtt_publish_auth_error")
            if self.proto_ver == PROTO_5 and f.qos > 0:
                await self._pub_nack(f, RC_NOT_AUTHORIZED)
            elif self.proto_ver == PROTO_5:
                await self._disconnect_v5(RC_NOT_AUTHORIZED)
            else:
                # v4 has no nack: drop (QoS1 acked to avoid retry storms,
                # mirroring the reference's behaviour of acking then dropping)
                if f.qos == 1 and f.packet_id:
                    self.send(Puback(packet_id=f.packet_id))
                elif f.qos == 2 and f.packet_id:
                    self.send(Pubrec(packet_id=f.packet_id))
                    self._qos2_hold(f.packet_id)
            return
        payload = f.payload
        if mods:
            if "topic" in mods:
                words = tuple(mods["topic"])
            if "payload" in mods:
                payload = mods["payload"]
            if "retain" in mods:
                f.retain = mods["retain"]

        props = {
            k: v for k, v in f.properties.items()
            if k in ("payload_format_indicator", "message_expiry_interval",
                     "content_type", "response_topic", "correlation_data",
                     "user_property")
        }
        msg = Msg(
            topic=words, payload=payload, qos=f.qos, retain=f.retain,
            mountpoint=self.mountpoint, properties=props,
        )
        expiry = props.get("message_expiry_interval")
        if expiry:
            msg.expires_at = time.monotonic() + expiry
        if trace is not None:
            # gates passed, topic validated, auth done: admitted
            trace.stamp("admit")

        if f.qos == 0:
            await self._route(msg, nowait=True, trace=trace)
        elif f.qos == 1:
            matches = await self._route(msg, trace=trace)
            if matches < 0:
                # internal routing failure: withhold the PUBACK so the
                # client's DUP retry re-routes (same contract as QoS2 below)
                return
            rc = RC_SUCCESS if matches else RC_NO_MATCHING_SUBSCRIBERS
            ack = Puback(packet_id=f.packet_id)
            if self.proto_ver == PROTO_5 and rc:
                ack.reason_code = rc
            self.send(ack)
            self.broker.metrics.incr("mqtt_puback_sent")
        else:  # qos 2: route on first arrival, dedup until PUBREL
            if f.packet_id not in self.awaiting_rel:
                self._qos2_hold(f.packet_id)
                n = await self._route(msg, trace=trace)
                if n < 0:
                    # internal routing failure: forget the packet id so the
                    # client's DUP retry re-routes instead of being deduped
                    self.awaiting_rel.pop(f.packet_id, None)
                    return
            self.send(Pubrec(packet_id=f.packet_id))
            self.broker.metrics.incr("mqtt_pubrec_sent")

    def _qos2_hold(self, pid: int) -> None:
        """Park ``pid`` in the QoS2 dedup window (awaiting PUBREL),
        bounded at qos2_dedup_max: a client that never releases must
        not grow the dict without limit, so the OLDEST held pid is
        evicted (insertion order = arrival order) and counted. An
        evicted pid's DUP retransmission re-routes — the documented
        at-least-once degradation at window overflow."""
        rel = self.awaiting_rel
        if pid in rel:
            rel[pid] = time.monotonic()
            return
        cap = int(self.broker.config.get("qos2_dedup_max", 4096))
        if cap > 0:
            m = self.broker.metrics
            while len(rel) >= cap:
                rel.pop(next(iter(rel)))
                m.incr("qos2_dedup_evictions")
        rel[pid] = time.monotonic()

    # ------------------------------------------------- wire fast path

    def wire_fast_ready(self) -> bool:
        """Batch-level gate for the wire fast path (QoS0 AND QoS1/2
        publishes, plus the 2-byte ack family): True only when NO
        per-publish Python edge applies — no tracer, no per-publish
        auth/deliver hooks, no rate limit, governor idle, cluster
        ready, no payload predicates on this mountpoint. Checked once
        per parsed batch (and re-checked after cooperative yields);
        anything that needs per-frame policy falls back to the classic
        handler frame by frame."""
        if not self.connected or self.closed:
            return False
        b = self.broker
        cfg = b.config
        if not cfg.get("wire_fastpath_enabled", True):
            return False
        if b.tracer is not None or cfg.max_message_rate:
            return False
        gov = b.overload
        if gov is not None:
            if gov.level > 0:
                return False
        elif b.sysmon is not None and b.sysmon.overloaded:
            return False
        h = b.hooks
        if (h.has("auth_on_publish") or h.has("auth_on_publish_m5")
                or h.has("on_publish") or h.has("on_deliver")):
            return False
        if not b.cluster_ready() \
                and not cfg.allow_publish_during_netsplit:
            return False
        eng = getattr(b, "filter_engine", None)
        if eng is not None and eng.wants(self.mountpoint):
            return False
        return True

    def _wire_cache_topic(self, buf, t_off: int, t_len: int):
        """Resolve ``(words, topic_str)`` through the per-connection
        topic cache, or None when the topic is invalid (the classic
        path raises the canonical error)."""
        cache = self._wire_topic_cache
        key = bytes(buf[t_off:t_off + t_len])
        ent = cache.get(key)
        if ent is None:
            try:
                topic_str = key.decode("utf-8")
            except UnicodeDecodeError:
                return None  # codec raises the canonical invalid_utf8
            if "\x00" in topic_str:
                return None  # canonical no_null_allowed
            try:
                words = tuple(T.validate_topic("publish", topic_str))
            except T.TopicError:
                return None  # classic close("invalid_topic")
            ent = (words, topic_str)
            # bounded by entries AND entry size: topics run up to 64KB
            # and each entry holds ~3 copies — a publisher minting
            # large distinct topics must not pin O(100MB) per
            # connection. Long topics still fast-path, just uncached
            # (the cache pays off for short repeated telemetry names).
            if len(key) <= 1024:
                if len(cache) >= 512:
                    cache.clear()
                cache[key] = ent
        return ent

    def _wire_topic(self, buf, rec):
        """``(words, topic_str)`` for a frame-table publish record —
        the topic cache plus, for v5, the inbound topic-alias table
        (the frame table classifies an alias-ONLY property block as
        hot and leaves the 4-byte span for us to read). None = the
        classic path must serve: invalid topic, alias 0 / over the
        announced cap / unknown — each raises or disconnects with the
        canonical reason there."""
        _k, b0, _pid, f_off, f_end, t_off, t_len, p_off = rec
        if self.proto_ver == PROTO_5:
            qos = (b0 >> 1) & 0x03
            pstart = t_off + t_len + (2 if qos else 0)
            if p_off - pstart == 4:  # topic-alias-only property block
                alias = (buf[p_off - 2] << 8) | buf[p_off - 1]
                cfg = self.broker.config
                if alias == 0 or (cfg.topic_alias_max_client
                                  and alias > cfg.topic_alias_max_client):
                    return None  # classic: TOPIC_ALIAS_INVALID
                if t_len == 0:
                    return self.topic_alias_in.get(alias)
                ent = self._wire_cache_topic(buf, t_off, t_len)
                if ent is not None:
                    self.topic_alias_in[alias] = ent
                return ent
        return self._wire_cache_topic(buf, t_off, t_len)

    def wire_publish_qos0(self, buf, rec) -> bool:
        """Admit one QoS0 PUBLISH straight from the frame table:
        topic resolved through the per-connection cache (and, v5, the
        inbound alias table), payload sliced once, fanout written as
        shared wire bytes — no Publish frame, no Msg, no property dict
        on this path. Returns False when the frame needs classic
        handling (uncached-invalid topic, alias error, codec edge);
        the caller materialises it then."""
        _k, b0, _pid, f_off, f_end, t_off, t_len, p_off = rec
        b = self.broker
        ent = self._wire_topic(buf, rec)
        if ent is None:
            return False
        words, topic_str = ent
        trace = b.recorder.admit(self.client_id, topic_str, 0)
        if trace is not None:
            trace.stamp("admit")
        # a v4 QoS0 frame with flags 0 forwards VERBATIM: the inbound
        # span IS the outbound frame for every fast recipient — the
        # payload is NOT copied separately (the dominant cost this
        # path removes); the route slices it out of the span lazily
        # only on the complex-row fallback. A v5 inbound frame carries
        # the extra property-length byte, so those pass the payload
        # and re-encode a header instead.
        if self.proto_ver != PROTO_5:
            span = bytes(buf[f_off:f_end])
            payload = None
            pskip = p_off - f_off
        else:
            span = None
            payload = bytes(buf[p_off:f_end])
            pskip = 0
        try:
            b.registry.publish_wire_qos0(
                self.mountpoint, words, topic_str, payload, self.sid,
                wire_frame=span, payload_skip=pskip, trace=trace)
        except RuntimeError as e:
            b.metrics.incr("mqtt_publish_error")
            if e.args != ("not_ready",):
                log.exception("wire publish routing failed for %s",
                              self.sid)
            return True  # handled: QoS0 owes no ack (classic parity)
        except Exception:
            b.metrics.incr("mqtt_publish_error")
            log.exception("wire publish routing failed for %s", self.sid)
            return True
        return True

    def wire_publish_qos(self, buf, rec) -> bool:
        """Admit one QoS1/2 PUBLISH straight from the frame table: the
        pid is stamped into the store/ack state machine from the span
        and the PUBACK/PUBREC reply is sent without materialising a
        Publish or Msg on the inbound side (the fanout builds ONE Msg
        lazily only for QoS≥1 recipients that must track it in
        waiting_acks). Returns False when the frame needs the exact
        classic path: receive-max exceeded, invalid topic/alias — each
        raises or disconnects with the canonical reason there."""
        _k, b0, pid, f_off, f_end, t_off, t_len, p_off = rec
        qos = (b0 >> 1) & 0x03
        b = self.broker
        # QoS≥1 acks need the synchronous match count for the reason
        # code; the batched (collector) view routes asynchronously, so
        # the classic await path serves it
        if b.registry.batched_view_active():
            return False
        # v5 incoming flow control: at the announced receive maximum
        # the next QoS>0 publish is a protocol error — the classic
        # path serves the RECEIVE_MAX_EXCEEDED disconnect canonically
        if (self.proto_ver == PROTO_5 and self._recv_max_announced
                and len(self.awaiting_rel) >= self._recv_max_announced
                and not (qos == 2 and pid in self.awaiting_rel)):
            return False
        ent = self._wire_topic(buf, rec)
        if ent is None:
            return False
        words, topic_str = ent
        trace = b.recorder.admit(self.client_id, topic_str, qos)
        if trace is not None:
            trace.stamp("admit")
        if qos == 2 and pid in self.awaiting_rel:
            # duplicate arrival of an unreleased pid: dedup (no
            # re-route), refresh the PUBREC (classic parity)
            self.send(Pubrec(packet_id=pid))
            b.metrics.incr("mqtt_pubrec_sent")
            return True
        payload = bytes(buf[p_off:f_end])
        if qos == 2:
            self._qos2_hold(pid)
        try:
            matches = b.registry.publish_wire(
                self.mountpoint, words, topic_str, payload, self.sid,
                qos, trace=trace)
        except RuntimeError as e:
            b.metrics.incr("mqtt_publish_error")
            if e.args != ("not_ready",):
                log.exception("wire publish routing failed for %s",
                              self.sid)
            # withhold the ack so the client's DUP retry re-routes;
            # the QoS2 receive credit must not leak meanwhile
            if qos == 2:
                self.awaiting_rel.pop(pid, None)
            return True
        except Exception:
            b.metrics.incr("mqtt_publish_error")
            log.exception("wire publish routing failed for %s", self.sid)
            if qos == 2:
                self.awaiting_rel.pop(pid, None)
            return True
        if qos == 1:
            ack = Puback(packet_id=pid)
            if self.proto_ver == PROTO_5 and not matches:
                ack.reason_code = RC_NO_MATCHING_SUBSCRIBERS
            self.send(ack)
            b.metrics.incr("mqtt_puback_sent")
        else:
            self.send(Pubrec(packet_id=pid))
            b.metrics.incr("mqtt_pubrec_sent")
        return True

    def wire_ack(self, rec) -> None:
        """Resolve one 2-byte ack-family frame straight from the frame
        table: the pid checks against the waiting_acks / awaiting_rel
        bookkeeping with no frame object. The table only classifies
        the no-property rc=0 shape as K_ACK, so the v5 reason-code
        forms stay on the classic codec path."""
        ptype = rec[1] >> 4
        pid = rec[2]
        m = self.broker.metrics
        self.last_activity = time.monotonic()
        if ptype == PUBACK_T:
            m.incr("mqtt_puback_received")
            entry = self.waiting_acks.get(pid)
            if entry and entry[0] == "puback":
                del self.waiting_acks[pid]
                self._pump_pending()
            else:  # ack for nothing we sent (vmq_metrics *_invalid_error)
                m.incr("mqtt_puback_invalid_error")
        elif ptype == PUBREC_T:
            m.incr("mqtt_pubrec_received")
            entry = self.waiting_acks.get(pid)
            if entry and entry[0] == "pubrec":
                entry[0] = "pubcomp"
                entry[2] = time.monotonic()
                self.send(Pubrel(packet_id=pid))
                m.incr("mqtt_pubrel_sent")
            elif not (entry and entry[0] == "pubcomp"):
                # a DUP PUBREC while we await PUBCOMP is legal
                # retransmission; anything else is unexpected
                m.incr("mqtt_pubrec_invalid_error")
        elif ptype == PUBREL_T:
            m.incr("mqtt_pubrel_received")
            existed = self.awaiting_rel.pop(pid, None)
            comp = Pubcomp(packet_id=pid)
            if existed is None and self.proto_ver == PROTO_5:
                comp.reason_code = RC_PACKET_ID_NOT_FOUND
            self.send(comp)
            m.incr("mqtt_pubcomp_sent")
        else:  # PUBCOMP
            m.incr("mqtt_pubcomp_received")
            entry = self.waiting_acks.get(pid)
            if entry and entry[0] == "pubcomp":
                del self.waiting_acks[pid]
                self._pump_pending()
            else:
                m.incr("mqtt_pubcomp_invalid_error")
        fastpath.fastpath_acks += 1

    def wire_take_qos(self, msg: Msg) -> Optional[int]:
        """Register a wire-plane QoS≥1 delivery in the in-flight
        window: allocate the packet id and the waiting_acks entry (the
        bookkeeping half of the classic deliver path) WITHOUT encoding
        the frame — the registry batch-encodes all recipients' headers
        in one native call. 0 = window full, message parked — session
        pending first, then the queue-level backlog via the same
        ``_backpressure`` tier the classic refusal takes (the
        ack-driven pump and ``notify_ready`` replay deliver it
        classically later); None = no park tier available, dropped.
        Neither takes a wire write now."""
        window = min(self.broker.config.max_inflight_messages,
                     self.receive_max_out)
        if len(self.waiting_acks) >= window:
            if len(self.pending) >= \
                    self.broker.config.max_online_messages:
                if self.queue is not None:
                    self.queue._backpressure(msg)
                    return 0
                self.broker.metrics.incr("queue_message_drop")
                return None
            self.pending.append(msg)
            return 0
        pid = self._next_packet_id()
        self.waiting_acks[pid] = ["puback" if msg.qos == 1 else "pubrec",
                                  msg, time.monotonic(), False]
        return pid

    def wire_v5_fast_ok(self, frame_bound: int = 0) -> bool:
        """May this v5 session take wire-plane fast delivery? Capless
        sessions always can. A client maximum_packet_size admits the
        fast path only when the fanout's conservative worst-case frame
        bound (full topic, pid, alias property — computed once in
        ``_wire_route``) fits under the cap: every batch-encoded
        variant is smaller, so an admitted frame can never violate
        MQTT-3.1.2-24. An unknown bound (0) keeps the exact classic
        per-frame measurement (_plan_v5_delivery)."""
        cap = self.max_packet_out
        if not cap:
            return True
        return 0 < frame_bound <= cap

    def wire_alias_for(self, words: Tuple[str, ...]) -> int:
        """Outbound topic-alias decision for one wire-plane delivery,
        against the same per-connection LRU table the classic
        _build_v5_publish drives. Returns the signed alias convention
        of ``fastpath.publish_headers_batch``: 0 = no aliasing (full
        topic), +a = established (alias-only header), -a = newly
        established here (header carries BOTH topic and alias). A full
        table evicts the least-recently-sent topic and re-establishes
        its alias number (MQTT5 3.3.2.3.4 permits remapping)."""
        amax = self.topic_alias_max_out
        if not amax:
            return 0
        tbl = self.topic_alias_out
        alias = tbl.get(words)
        if alias is not None:
            tbl.move_to_end(words)
            return alias
        if len(tbl) < amax:
            alias = len(tbl) + 1
        else:
            _lru, alias = tbl.popitem(last=False)
        tbl[words] = alias
        return -alias

    def wire_fast_done(self, n: int, nq: int = 0) -> None:
        """Batch-level bookkeeping for ``n`` fast-admitted QoS0 and
        ``nq`` QoS1/2 publishes (classic path does these per frame)."""
        self.last_activity = time.monotonic()
        b = self.broker
        b.metrics.incr("mqtt_publish_received", n + nq)
        if b.overload is not None:
            # the heaviest-talker signal keeps integrating even though
            # the fast path never parks (it only runs at level 0)
            b.overload.record_publish_n(self.sid, n + nq)
        fastpath.fastpath_pubs += n
        fastpath.fastpath_pubs_qos += nq

    async def _route(self, msg: Msg, nowait: bool = False,
                     trace=None) -> int:
        """Route via the registry; returns match count, or -1 on an internal
        matcher failure (distinct from the not_ready gate: internal errors
        are logged and, for QoS2, leave the packet eligible for re-route on
        the client's DUP retry). ``nowait`` (QoS0 under the batched view)
        submits without awaiting the batch window so one publisher can fill
        a batch instead of sending one message per window. ``trace`` is
        the flight-recorder context of a sampled publish; the registry
        finishes it when routing completes (async for nowait)."""
        try:
            if self.broker.registry.batched_view_active():
                if nowait:
                    n = self.broker.registry.publish_nowait(
                        msg, from_sid=self.sid, trace=trace)
                    trace = None  # finished by the route callback
                else:
                    n = await self.broker.registry.publish_async(
                        msg, from_sid=self.sid, trace=trace)
            else:
                n = self.broker.registry.publish(msg, from_sid=self.sid,
                                                 trace=trace)
            if trace is not None:
                trace.stamp("route")
                self.broker.recorder.finish(trace)
        except RuntimeError as e:
            self.broker.metrics.incr("mqtt_publish_error")
            if e.args != ("not_ready",):
                log.exception("publish routing failed for %s", self.sid)
            # not_ready (netsplit CAP gate, vmq_reg.erl:293-318) behaves like
            # the reference's {error, not_ready}: no ack — client retries
            return -1
        except Exception:
            self.broker.metrics.incr("mqtt_publish_error")
            log.exception("publish routing failed for %s", self.sid)
            return -1
        self.broker.hooks_fire_all(
            "on_publish", self.username, self.sid, msg.qos, msg.topic,
            msg.payload, msg.retain,
        )
        return n

    async def _pub_nack(self, f: Publish, rc: int) -> None:
        if f.qos == 1:
            self.send(Puback(packet_id=f.packet_id, reason_code=rc))
        elif f.qos == 2:
            self.send(Pubrec(packet_id=f.packet_id, reason_code=rc))

    def _handle_pubrel(self, f: Pubrel) -> None:
        existed = self.awaiting_rel.pop(f.packet_id, None)
        comp = Pubcomp(packet_id=f.packet_id)
        if existed is None and self.proto_ver == PROTO_5:
            comp.reason_code = RC_PACKET_ID_NOT_FOUND
        self.send(comp)
        self.broker.metrics.incr("mqtt_pubcomp_sent")

    # --------------------------------------------------------- PUBLISH out

    def _queue_deliver(self, msg: Msg) -> bool:
        """Called by the SubscriberQueue to hand a message to this session.
        Returns False when the session can't take it (caller drops/offlines)."""
        if self.closed:
            return False
        if msg.expires_at is not None and msg.expires_at < time.monotonic():
            self.broker.metrics.incr("queue_message_expired")
            return True  # consumed (expired), not a drop by us
        # only capped clients (maximum_packet_size announced, or
        # m5_max_packet_size configured) pay the extra build+serialise
        # inside _plan_v5_delivery; everyone else short-circuits inside
        plan = self._plan_v5_delivery(msg)
        if plan == "drop":
            # the client's maximum_packet_size forbids this frame even
            # without an alias: drop it (never truncate, never error the
            # session) with the same hook the reference fires
            # (vmq_mqtt5_fsm.erl:1422-1427); checked BEFORE packet-id
            # allocation so nothing leaks into waiting_acks
            self.broker.metrics.incr("queue_message_drop")
            self.broker.hooks_fire_all("on_message_drop", self.sid, msg,
                                       "max_packet_size_exceeded")
            return True
        allow_alias = plan == "fits"
        if msg.qos == 0:
            self._send_publish(msg, None, allow_alias=allow_alias)
            return True
        window = min(self.broker.config.max_inflight_messages, self.receive_max_out)
        if len(self.waiting_acks) < window:
            pid = self._next_packet_id()
            self.waiting_acks[pid] = ["puback" if msg.qos == 1 else "pubrec",
                                      msg, time.monotonic(), False]
            self._send_publish(msg, pid, allow_alias=allow_alias)
        else:
            if len(self.pending) >= self.broker.config.max_online_messages:
                return False
            self.pending.append(msg)
        return True

    def _build_v5_publish(self, msg: Msg, pid: Optional[int],
                          dup: bool = False, commit: bool = True,
                          allow_alias: bool = True) -> Publish:
        """The ONE place the broker->client v5 PUBLISH frame is shaped:
        remaining message expiry (MQTT5 3.3.2.3.3) and outbound topic
        alias (vmq_mqtt5_fsm.erl topic_aliases out).  With
        ``commit=False`` an alias the send path WOULD allocate is
        simulated (same 3-byte property, placeholder id) without
        mutating alias state; ``allow_alias=False`` skips the
        allocation entirely (an established alias is still used — it
        only shrinks the frame)."""
        props = dict(msg.properties)
        if msg.expires_at is not None:
            props["message_expiry_interval"] = max(
                0, int(msg.expires_at - time.monotonic()))
        topic_str = T.unword(list(msg.topic))
        if self.topic_alias_max_out:
            alias = self.topic_alias_out.get(msg.topic)
            if alias is not None:
                if commit:
                    self.topic_alias_out.move_to_end(msg.topic)
                topic_str = ""
                props["topic_alias"] = alias
            elif allow_alias:
                # LRU allocation: a free slot takes the next number; a
                # full table evicts the least-recently-SENT topic and
                # re-establishes its alias number for this one (MQTT5
                # 3.3.2.3.4 permits remapping mid-connection), so hot
                # topics keep alias-only frames under churn
                if len(self.topic_alias_out) < self.topic_alias_max_out:
                    alias = len(self.topic_alias_out) + 1
                    if commit:
                        self.topic_alias_out[msg.topic] = alias
                else:
                    if commit:
                        _lru, alias = self.topic_alias_out.popitem(
                            last=False)
                        self.topic_alias_out[msg.topic] = alias
                    else:  # simulate without mutating (peek the LRU)
                        alias = next(iter(self.topic_alias_out.values()))
                # the alias-establishing frame carries BOTH the full
                # topic and the alias property
                props["topic_alias"] = alias
        return Publish(topic=topic_str, payload=msg.payload, qos=msg.qos,
                       retain=msg.retain, dup=dup, packet_id=pid,
                       properties=props)

    def _plan_v5_delivery(self, msg: Msg) -> str:
        """How does this delivery fit the client's maximum_packet_size?
        Measures the exact frame the send path would build — the analog
        of maybe_reduce_packet_size serialising to check
        (vmq_mqtt5_fsm.erl:297-315; we carry no reason-string/user-props
        on PUBLISH, so the only thing strippable is the alias property):

        - ``"fits"``  — full frame (alias allocation included) fits;
        - ``"bare"``  — only the alias-ESTABLISHING overhead (full topic
          + 3-byte property) pushes it over: deliver without allocating
          the alias rather than lose a legal message;
        - ``"drop"``  — exceeds the cap even without an alias.
        """
        if self.proto_ver != PROTO_5 or not self.max_packet_out:
            return "fits"
        pid = 1 if msg.qos else None
        frame = self._build_v5_publish(msg, pid, commit=False)
        if len(codec_v5.serialise(frame)) <= self.max_packet_out:
            return "fits"
        if "topic_alias" in frame.properties and frame.topic:
            # the over-measure came from the would-be allocation
            bare = self._build_v5_publish(msg, pid, commit=False,
                                          allow_alias=False)
            if len(codec_v5.serialise(bare)) <= self.max_packet_out:
                return "bare"
        return "drop"

    def _send_publish(self, msg: Msg, pid: Optional[int], dup: bool = False,
                      allow_alias: bool = True) -> None:
        self.broker.hooks_fire_all(
            "on_deliver", self.username, self.sid, msg.topic, msg.payload
        )
        if (not dup and self.proto_ver != PROTO_5
                and (pid is not None or msg.qos == 0)
                and self.broker.tracer is None and not self.closed):
            # v4 fanout fast path: across recipients the frame is
            # identical (QoS0: no packet id, no props, no per-session
            # alias state) or differs only in the 2-byte packet id
            # (QoS1/2) — one cached header per Msg, the shared payload
            # rides the transport iovec uncopied (the analog of the
            # reference serialising in vmq_mqtt_fsm once per frame, but
            # across recipients, minus the per-recipient payload copy)
            from .message import wire_v4_iov_qos, wire_v4_iov_qos0

            iov = (wire_v4_iov_qos0(msg) if pid is None
                   else wire_v4_iov_qos(msg, pid))
            self.transport.write_iov(iov)
            m = self.broker.metrics
            m.incr("bytes_sent", sum(len(c) for c in iov))
            m.incr("mqtt_publish_sent")
            return
        if self.proto_ver == PROTO_5:
            frame = self._build_v5_publish(msg, pid, dup,
                                           allow_alias=allow_alias)
        else:
            frame = Publish(
                topic=T.unword(list(msg.topic)), payload=msg.payload,
                qos=msg.qos, retain=msg.retain, dup=dup, packet_id=pid,
                properties={},
            )
        self.send(frame)
        self.broker.metrics.incr("mqtt_publish_sent")

    def _next_packet_id(self) -> int:
        for _ in range(65535):
            self._next_pid = (self._next_pid % 65535) + 1
            if self._next_pid not in self.waiting_acks:
                return self._next_pid
        raise SessionError("no_free_packet_id")

    def _pump_pending(self) -> None:
        window = min(self.broker.config.max_inflight_messages, self.receive_max_out)
        while self.pending and len(self.waiting_acks) < window:
            msg = self.pending.pop(0)
            if msg.expires_at is not None and msg.expires_at < time.monotonic():
                self.broker.metrics.incr("queue_message_expired")
                continue
            # re-plan against the cap: alias state may have moved while
            # the message waited in pending
            plan = self._plan_v5_delivery(msg)
            if plan == "drop":
                self.broker.metrics.incr("queue_message_drop")
                self.broker.hooks_fire_all("on_message_drop", self.sid,
                                           msg, "max_packet_size_exceeded")
                continue
            pid = self._next_packet_id()
            self.waiting_acks[pid] = ["puback" if msg.qos == 1 else "pubrec",
                                      msg, time.monotonic(), False]
            self._send_publish(msg, pid, allow_alias=plan == "fits")
        # session window freed and nothing pending here: pull messages the
        # queue parked under backpressure (notify→active transition)
        if (not self.pending and self.queue is not None
                and len(self.waiting_acks) < window):
            self.queue.notify_ready(self)
        # capacity freed: a rate-throttled reader may re-check its budget
        self._throttle_wake.set()

    def _handle_puback(self, f: Puback) -> None:
        entry = self.waiting_acks.get(f.packet_id)
        if entry and entry[0] == "puback":
            del self.waiting_acks[f.packet_id]
            self._pump_pending()
        else:  # ack for nothing we sent (vmq_metrics *_invalid_error)
            self.broker.metrics.incr("mqtt_puback_invalid_error")

    def _handle_pubrec(self, f: Pubrec) -> None:
        entry = self.waiting_acks.get(f.packet_id)
        if entry and entry[0] == "pubrec":
            if self.proto_ver == PROTO_5 and f.reason_code >= 0x80:
                del self.waiting_acks[f.packet_id]
                self._pump_pending()
                return
            entry[0] = "pubcomp"
            entry[2] = time.monotonic()
            self.send(Pubrel(packet_id=f.packet_id))
            self.broker.metrics.incr("mqtt_pubrel_sent")
        elif not (entry and entry[0] == "pubcomp"):
            # a DUP PUBREC while we await PUBCOMP is legal retransmission;
            # anything else is unexpected
            self.broker.metrics.incr("mqtt_pubrec_invalid_error")

    def _handle_pubcomp(self, f: Pubcomp) -> None:
        entry = self.waiting_acks.get(f.packet_id)
        if entry and entry[0] == "pubcomp":
            del self.waiting_acks[f.packet_id]
            self._pump_pending()
        else:
            self.broker.metrics.incr("mqtt_pubcomp_invalid_error")

    # ----------------------------------------------------------- SUBSCRIBE

    async def _handle_subscribe(self, f: Subscribe) -> None:
        cfg = self.broker.config
        sub_id = None
        if self.proto_ver == PROTO_5:
            ids = f.properties.get("subscription_identifier")
            if ids:
                sub_id = ids[0]
        topics: List[Tuple[List[str], SubOpts]] = []
        codes: List[int] = []
        filters_on = cfg.get("payload_filters_enabled", True)
        for topic_str, opts in f.topics:
            # MQTT+ payload-filter suffix (vernemq_tpu/filters/):
            # `sensors/+/temp?$gt(value,30)` splits into the plain topic
            # filter plus a predicate/aggregation expression carried in
            # SubOpts. Works identically for v4 and v5 (the suffix rides
            # the topic string, no new packet fields). With the feature
            # disabled the `?` stays part of the topic, byte-identical
            # to the pre-filter broker.
            if filters_on:
                base_str, fexpr = split_filter_suffix(topic_str)
                if fexpr is not None:
                    try:
                        parse_filter(fexpr)
                    except FilterError:
                        self.broker.metrics.incr("mqtt_subscribe_error")
                        codes.append(0x8F if self.proto_ver == PROTO_5
                                     else 0x80)
                        topics.append(None)
                        continue
                    topic_str = base_str
                    opts.filter_expr = fexpr
            try:
                words = T.validate_topic("subscribe", topic_str)
            except T.TopicError:
                codes.append(0x8F if self.proto_ver == PROTO_5 else 0x80)
                topics.append(None)
                continue
            topics.append((words, opts))
            codes.append(opts.qos)
        # auth chain (may rewrite topics/qos)
        hook = "auth_on_subscribe_m5" if self.proto_ver == PROTO_5 else "auth_on_subscribe"
        try:
            res = await self.broker.hooks.all_till_ok(
                hook, self.username, self.sid,
                [(t[0], t[1].qos) for t in topics if t],
            )
            if isinstance(res, tuple):
                # modifiers: list of (topic_words, qos) or qos 128 to deny
                mod_list = res[1]
                new_topics, new_codes, i = [], [], 0
                for t in topics:
                    if t is None:
                        new_topics.append(None)
                        new_codes.append(0x8F if self.proto_ver == PROTO_5 else 0x80)
                        continue
                    words, qos = mod_list[i]
                    i += 1
                    if qos == 128 or qos == 0x80:
                        new_topics.append(None)
                        new_codes.append(0x80 if self.proto_ver != PROTO_5 else 0x87)
                    else:
                        opts = t[1]
                        opts.qos = qos
                        new_topics.append((list(words), opts))
                        new_codes.append(qos)
                topics, codes = new_topics, new_codes
        except HookError as e:
            # no plugin answered → allowed only without default-deny
            # (vmq_auth.erl:3-8 registers deny hooks when allow_anonymous=off)
            if (e.reason != "no_matching_hook_found"
                    or not self.broker.config.allow_anonymous):
                self.broker.metrics.incr("mqtt_subscribe_auth_error")
                fail = 0x80 if self.proto_ver != PROTO_5 else 0x87
                self.send(Suback(packet_id=f.packet_id,
                                 reason_codes=[fail] * len(f.topics)))
                self.broker.metrics.incr("mqtt_suback_sent")
                return
        # SUBACK first so retained replay serialises behind it on the wire
        good = [t for t in topics if t is not None]
        # netsplit CAP gate, checked before the SUBACK goes out
        # (vmq_reg:subscribe if_ready, vmq_reg.erl:62-70)
        if good and not self.broker.cluster_ready() \
                and not self.broker.config.allow_subscribe_during_netsplit:
            fail = 0x80 if self.proto_ver != PROTO_5 else 0x83
            self.send(Suback(packet_id=f.packet_id,
                             reason_codes=[fail] * len(f.topics)))
            self.broker.metrics.incr("mqtt_suback_sent")
            return
        # SUBACK first so retained replay serialises behind it on the wire
        self.send(Suback(packet_id=f.packet_id, reason_codes=codes))
        self.broker.metrics.incr("mqtt_suback_sent")
        if good:
            for words, opts in good:
                if sub_id:
                    opts.subscription_id = sub_id
            try:
                self.broker.registry.subscribe(self.sid, good)
            except RuntimeError:
                # gate flipped between check and write: drop the session so
                # the client re-subscribes on reconnect
                await self.close("not_ready")
                return
            self.broker.hooks_fire_all(
                "on_subscribe", self.username, self.sid,
                [(w, o.qos) for w, o in good],
            )

    async def _handle_unsubscribe(self, f: Unsubscribe) -> None:
        topics = []
        filters_on = self.broker.config.get("payload_filters_enabled", True)
        for topic_str in f.topics:
            if filters_on:
                # a filter-suffixed UNSUBSCRIBE targets its base topic
                # filter (the suffix rides SubOpts, not the sub key)
                topic_str, _fexpr = split_filter_suffix(topic_str)
            try:
                topics.append(T.validate_topic("subscribe", topic_str))
            except T.TopicError:
                topics.append(None)
        try:
            res = await self.broker.hooks.all_till_ok(
                "on_unsubscribe", self.username, self.sid,
                [t for t in topics if t],
            )
            if isinstance(res, tuple):
                topics = [list(t) for t in res[1]]
        except HookError:
            pass
        valid = [t for t in topics if t is not None]
        try:
            results = self.broker.registry.unsubscribe(self.sid, valid)
        except RuntimeError:
            # netsplit CAP gate (vmq_reg.erl:65-70)
            fail = 0x80
            self.send(Unsuback(packet_id=f.packet_id,
                               reason_codes=[fail] * len(f.topics)))
            self.broker.metrics.incr("mqtt_unsuback_sent")
            return
        codes: List[int] = []
        ri = iter(results)
        for t in topics:
            if t is None:
                codes.append(0x8F)
            else:
                codes.append(RC_SUCCESS if next(ri) else RC_NO_SUBSCRIPTION_EXISTED)
        self.send(Unsuback(packet_id=f.packet_id, reason_codes=codes))
        self.broker.metrics.incr("mqtt_unsuback_sent")

    # ---------------------------------------------------------------- AUTH

    async def _handle_auth(self, f: Auth) -> None:
        if self.proto_ver != PROTO_5:
            await self.close("protocol_violation")
            return
        method = f.properties.get("authentication_method")
        if method != self.auth_method:
            await self._disconnect_v5(0x8C)
            return
        res = await self._run_enhanced_auth(f.properties.get("authentication_data"))
        if res == "ok":
            if self._pending_connect is not None:
                pc, self._pending_connect = self._pending_connect, None
                await self._finish_connect(pc)
            else:
                # re-auth complete
                self.send(Auth(reason_code=0, properties={
                    "authentication_method": self.auth_method}))
                self.broker.metrics.incr("mqtt_auth_sent")

    # -------------------------------------------------------------- timers

    def _start_timers(self) -> None:
        loop = asyncio.get_event_loop()
        if self.keepalive:
            self._tasks.append(loop.create_task(self._keepalive_loop()))
        self._tasks.append(loop.create_task(self._retry_loop()))

    async def _keepalive_loop(self) -> None:
        # close if silent for 1.5× keepalive (vmq_mqtt_fsm.erl:422-432)
        limit = self.keepalive * 1.5
        while not self.closed:
            await asyncio.sleep(max(0.05, limit / 4))
            if time.monotonic() - self.last_activity > limit:
                await self.close("keepalive_expired")
                return

    async def _retry_loop(self) -> None:
        interval = self.broker.config.retry_interval
        while not self.closed:
            await asyncio.sleep(interval)
            now = time.monotonic()
            for pid, entry in list(self.waiting_acks.items()):
                kind, msg, ts, _resent = entry
                if now - ts < interval:
                    continue
                entry[2] = now
                entry[3] = True
                if kind in ("puback", "pubrec"):
                    # re-plan against the client's packet cap: the frame
                    # the original send skipped an alias allocation for
                    # must not regrow one on retry. An in-flight message
                    # is never dropped here — "drop" is unreachable
                    # within a connection (nothing a frame is built from
                    # can grow between send and retry), so worst case it
                    # goes bare
                    plan = self._plan_v5_delivery(msg)
                    self._send_publish(msg, pid, dup=True,
                                       allow_alias=plan == "fits")
                else:  # pubcomp: retransmit PUBREL
                    self.send(Pubrel(packet_id=pid))

    # --------------------------------------------------------------- close

    async def close(self, reason: str, send_will: Optional[bool] = None) -> None:
        if self.closed:
            return
        self.closed = True
        self.close_reason = reason
        self._throttle_wake.set()  # release a parked throttle wait
        for t in self._tasks:
            t.cancel()
        if send_will is None:
            send_will = reason not in ("client_disconnect", "connack_fail")
        if send_will and self.will is not None and self.connected:
            self.broker.schedule_will(self.sid, self.will, self.mountpoint,
                                      self.proto_ver, self.session_expiry)
        if self.connected and self.sid is not None:
            if self.broker.sessions.get(self.sid) is self:
                del self.broker.sessions[self.sid]
            if self.queue is not None:
                # persistent session keeps undelivered inflight/pending msgs:
                # move them back to the queue as offline backlog
                if not self.queue.opts.clean_session:
                    for pid, (kind, msg, _, _) in sorted(self.waiting_acks.items()):
                        if kind in ("puback", "pubrec"):
                            self.queue.offline.append(msg)
                    for msg in self.pending:
                        if msg.qos > 0:
                            self.queue.offline.append(msg)
                self.waiting_acks.clear()
                self.pending.clear()
                self.queue.del_session(self)
        self.broker.metrics.drop_rate_state(self.sid)
        self.transport.close()

    async def overload_disconnect(self) -> None:
        """L3 top-talker shed (robustness/overload.py): Server busy, then
        the normal close path — persistent sessions keep their backlog,
        QoS>=1 inflight re-queues, nothing acked is lost."""
        if self.closed:
            return
        if self.proto_ver == PROTO_5:
            self.send(Disconnect(reason_code=RC_SERVER_BUSY))
            self._count_disconnect_sent(RC_SERVER_BUSY)
        await self.close("overload_shed")

    async def redirect_close(self, server_reference: str = "") -> None:
        """MQTT5 server redirect (live handoff): the session's state is
        already fenced+adopted at another node, so tell the client
        WHERE it went — DISCONNECT 0x9D (Server moved, permanent) with
        the Server Reference property, or 0x9C (Use another server)
        when no address is known — instead of a bare takeover kick that
        makes it knock here again. v3/4 clients have no redirect frame
        and never reach this path (the handoff keeps takeover_close
        for them)."""
        if self.proto_ver == PROTO_5:
            if server_reference:
                self.send(Disconnect(
                    reason_code=RC_SERVER_MOVED,
                    properties={"server_reference": server_reference}))
                rc = RC_SERVER_MOVED
            else:
                self.send(Disconnect(reason_code=RC_USE_ANOTHER_SERVER))
                rc = RC_USE_ANOTHER_SERVER
            self._count_disconnect_sent(rc)
        suppress = self.broker.config.suppress_lwt_on_session_takeover
        await self.close("server_redirect", send_will=not suppress)

    def detach_inflight(self) -> List[Any]:
        """Strip this session's undelivered QoS>=1 state (unacked
        in-flight + pending) WITHOUT closing it, oldest first — the
        live-handoff drain ships these to the new owner while the
        connection stays up, instead of close() parking them in the
        local offline backlog the handoff is about to tear down.
        Redelivery at the target beats loss, as with any QoS1 retry."""
        out: List[Any] = []
        for pid, (kind, msg, _, _) in sorted(self.waiting_acks.items()):
            if kind in ("puback", "pubrec"):
                out.append(msg)
        for msg in self.pending:
            if msg.qos > 0:
                out.append(msg)
        self.waiting_acks.clear()
        self.pending.clear()
        return out

    async def takeover_close(self) -> None:
        """Kicked by a newer session with the same client id."""
        if self.proto_ver == PROTO_5:
            self.send(Disconnect(reason_code=RC_SESSION_TAKEN_OVER))
            self._count_disconnect_sent(RC_SESSION_TAKEN_OVER)
        suppress = self.broker.config.suppress_lwt_on_session_takeover
        await self.close("session_taken_over", send_will=not suppress)

    def _count_disconnect_sent(self, rc: int) -> None:
        m = self.broker.metrics
        m.incr("mqtt_disconnect_sent")
        m.incr_labeled("mqtt_disconnect_sent", mqtt_version="5",
                       reason_code=reason_name(rc,
                                               zero="normal_disconnect"))

    async def _disconnect_v5(self, rc: int) -> None:
        if self.proto_ver == PROTO_5:
            self.send(Disconnect(reason_code=rc))
            self._count_disconnect_sent(rc)
        await self.close(f"disconnect_rc_{rc:#x}")

    def info(self) -> Dict[str, Any]:
        return {
            "client_id": self.client_id,
            "mountpoint": self.mountpoint,
            "user": self.username,
            "peer_host": self.peer[0],
            "peer_port": self.peer[1],
            "protocol": self.proto_ver,
            "waiting_acks": len(self.waiting_acks),
            "pending": len(self.pending),
            "clean_session": self.clean_start,
            "keepalive": self.keepalive,
        }


RC_CLIENT_ID_NOT_VALID = 0x85
RC_TOPIC_NAME_INVALID = 0x90

_IN_METRIC = {
    Publish: "mqtt_publish_received",
    Puback: "mqtt_puback_received",
    Pubrec: "mqtt_pubrec_received",
    Pubrel: "mqtt_pubrel_received",
    Pubcomp: "mqtt_pubcomp_received",
    Subscribe: "mqtt_subscribe_received",
    Unsubscribe: "mqtt_unsubscribe_received",
    Pingreq: "mqtt_pingreq_received",
    Disconnect: "mqtt_disconnect_received",
    Auth: "mqtt_auth_received",
}


class Transport:
    """Minimal transport interface the session writes to; implemented by the
    asyncio server (write-batched like vmq_ranch.erl:253-262) and by test
    fixtures."""

    def write(self, data: bytes) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def write_iov(self, chunks) -> None:
        """Write a writev-ready iovec. Transports that can scatter
        (StreamTransport) override; the default join keeps framing
        transports (websocket, test fixtures) seeing ONE contiguous
        write per frame — byte-identical on the wire either way."""
        self.write(b"".join(chunks))

    def close(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError
