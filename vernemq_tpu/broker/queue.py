"""Per-subscriber queue: the broker-side mailbox between the registry fanout
and the client session(s).

Mirrors the reference queue gen_fsm (``apps/vmq_server/src/vmq_queue.erl``):
states ``online`` (≥1 attached session) / ``offline`` (persistent session,
no attachment) / ``drain`` (migration, later rounds); per-session delivery
with ``fanout``/``balance`` modes for multiple sessions per ClientId
(``vmq_queue.erl:826-835``); an offline queue capped by
``max_offline_messages`` with FIFO tail-drop or LIFO oldest-drop
(``vmq_queue.erl:845-865``); QoS0 dropped when offline; session-expiry
timer (``vmq_queue.erl:913-930``); lifecycle hooks ``on_client_wakeup`` /
``on_client_offline`` / ``on_client_gone`` / ``on_offline_message`` /
``on_message_drop`` (``vmq_queue.erl:614,658-700,1059-1070``).

The reference's active/passive/notify backpressure protocol between queue
and session process (``vmq_queue.erl:752-774``, ``vmq_mqtt_fsm.erl:264-293``)
collapses here to a two-level window: the session holds an inflight window
plus a ``pending`` list; when every attached session refuses a message the
queue keeps it in its own ``backlog`` (the passive-state queue) and the
session pulls it back via :meth:`SubscriberQueue.notify_ready` once acks
free its window (the notify→active transition). Only past
``max_online_messages`` of queue-level backlog do messages drop, with
accounting — matching the reference's online-queue cap.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import TYPE_CHECKING, Callable, Deque, Dict, List, Optional

from .message import Msg, SubscriberId

if TYPE_CHECKING:
    from .broker import Broker

ONLINE = "online"
OFFLINE = "offline"
DRAIN = "drain"
TERMINATED = "terminated"


class QueueOpts:
    __slots__ = (
        "clean_session",
        "max_offline_messages",
        "max_online_messages",
        "deliver_mode",
        "queue_type",
        "session_expiry",
        "is_plugin",
    )

    def __init__(
        self,
        clean_session: bool = True,
        max_offline_messages: int = 1000,
        max_online_messages: int = 1000,
        deliver_mode: str = "fanout",
        queue_type: str = "fifo",
        session_expiry: int = 0,  # seconds; 0 = persistent_client_expiration config
        is_plugin: bool = False,
    ):
        self.clean_session = clean_session
        self.max_offline_messages = max_offline_messages
        self.max_online_messages = max_online_messages
        self.deliver_mode = deliver_mode
        self.queue_type = queue_type
        self.session_expiry = session_expiry
        self.is_plugin = is_plugin


class SubscriberQueue:
    """One queue per SubscriberId (the reference partitions these across
    phash2 supervisors, vmq_queue_sup_sup.erl:65-92; a Python dict gives the
    same O(1) lookup without the supervision tree)."""

    def __init__(self, broker: "Broker", subscriber_id: SubscriberId, opts: QueueOpts):
        self.broker = broker
        self.subscriber_id = subscriber_id
        self.opts = opts
        self.state = OFFLINE
        # session_handle -> deliver callback; a handle is the Session object
        self.sessions: Dict[object, Callable[[Msg], bool]] = {}
        self._rr: int = 0  # round-robin cursor for balance mode
        self.offline: Deque[Msg] = deque()
        # online backpressure backlog: messages every session refused
        # (windows full) parked until notify_ready — the passive-state
        # per-session queue of the reference (vmq_queue.erl:752-774)
        self.backlog: Deque[Msg] = deque()
        # batched-resume window (storage/resume.py): while the stored
        # offline backlog is in flight through the ResumeCollector, live
        # publishes park here — delivering them first would reorder
        # same-topic delivery against the older stored messages
        # (MQTT-4.6.0)
        self._resuming = False
        self._resume_buf: Deque[Msg] = deque()
        # lazy boot recovery: True when this queue's stored backlog was
        # NOT loaded at queue (re)creation — a million parked sessions
        # boot without a million read_alls; the backlog loads on first
        # attach (through the ResumeCollector) or at drain time
        self.offline_in_store = False
        self._expiry_task: Optional[asyncio.Task] = None
        self.created = time.time()

    # -- lifecycle ---------------------------------------------------------

    def add_session(self, session: object, deliver: Callable[[Msg], bool]) -> None:
        """Attach a session; offline→online wakes the queue and flushes the
        offline backlog through the new session (vmq_queue.erl:458-460 +
        init_offline_queue)."""
        was_offline = self.state == OFFLINE
        self.sessions[session] = deliver
        self.state = ONLINE
        self._cancel_expiry()
        if was_offline:
            self.broker.hooks_fire_all("on_client_wakeup", self.subscriber_id)
            if self.offline_in_store and not self._resuming:
                # lazily-booted queue: the stored backlog loads NOW —
                # batched through the ResumeCollector when available
                # (begin_resume parks live publishes), synchronously
                # into the offline deque otherwise (flushed below)
                self.offline_in_store = False
                self.broker.recover_offline(self.subscriber_id, self,
                                            may_defer=True)
            if self._resuming:
                # a batched resume is still in flight for this queue: the
                # offline deque holds only messages NEWER than the stored
                # backlog being read — finish_resume delivers stored +
                # deque + parked in order and clears storage ONCE.
                # Flushing (and delete_offline-ing) here would race the
                # executor read and could delete stored messages that
                # were never delivered.
                return
            backlog, self.offline = self.offline, deque()
            if backlog:
                # handed to the session's inflight tracking; clear storage
                # (per-ref deletes on ack come with the native store)
                self.broker.delete_offline(self.subscriber_id)
            for msg in backlog:
                if msg.expires_at is not None and msg.expires_at < time.monotonic():
                    self.broker.metrics.incr("queue_message_expired")
                    continue
                self._deliver_online(msg)

    def del_session(self, session: object) -> None:
        """Detach; last session out moves the queue offline (persistent) or
        tears it down (clean session), vmq_queue wait_for_offline."""
        self.sessions.pop(session, None)
        if self.sessions:
            return
        if self.opts.clean_session:
            self.terminate("normal")
        else:
            self.state = OFFLINE
            # park the backpressure backlog offline (insert_from_session,
            # vmq_queue.erl:867-881: undelivered messages survive the session)
            backlog, self.backlog = self.backlog, deque()
            for msg in backlog:
                self._enqueue_offline(msg)
            # publishes parked behind an in-flight resume go offline
            # too; finish_resume later puts the (older) stored backlog
            # at the FRONT, preserving arrival order
            buf, self._resume_buf = self._resume_buf, deque()
            for msg in buf:
                self._enqueue_offline(msg)
            self.broker.hooks_fire_all("on_client_offline", self.subscriber_id)
            self._arm_expiry()

    def start_drain(self) -> List[Msg]:
        """Enter the drain state and hand the offline backlog to the
        migration driver (vmq_queue drain state, vmq_queue.erl:338-400).
        Enqueues arriving mid-drain are queued (drain({enqueue,..})
        inserts, vmq_queue.erl:383-390) and picked up by
        :meth:`drain_pending` — never dropped."""
        prev_state = self.state
        self.state = DRAIN
        self._cancel_expiry()
        if self._resuming:
            # supersede an in-flight batched resume: the drain needs
            # the stored backlog NOW — read it synchronously; the
            # late-landing collector read becomes a no-op (finish_resume
            # guards on _resuming) so nothing is dropped or doubled.
            # Stored messages merge to the FRONT of the offline deque,
            # the parked live publishes (newest) go AFTER them — the
            # drained list keeps per-subscriber order (MQTT-4.6.0)
            self._resuming = False
            buf, self._resume_buf = self._resume_buf, deque()
            try:
                self.broker.recover_offline(self.subscriber_id, self)
            except Exception:
                self._drain_read_failed(prev_state, buf)
                raise
            self.offline.extend(buf)
        if self.offline_in_store:
            # a lazily-booted queue drains its STORED backlog too: load
            # it synchronously (migration correctness beats boot speed)
            self.offline_in_store = False
            try:
                self.broker.recover_offline(self.subscriber_id, self)
            except Exception:
                self._drain_read_failed(prev_state)
                raise
        backlog = list(self.backlog)
        self.backlog.clear()
        backlog += list(self._resume_buf)
        self._resume_buf.clear()
        backlog += list(self.offline)
        self.offline.clear()
        return [m for m in backlog
                if m.expires_at is None or m.expires_at >= time.monotonic()]

    def _drain_read_failed(self, prev_state: str,
                           parked: Optional[Deque[Msg]] = None) -> None:
        """A drain could not load the stored backlog: leave the queue
        exactly as it was — state restored, parked live publishes back
        in the offline deque, the stored backlog STILL marked in-store
        (nothing read, so nothing may be deleted) — and let the raised
        error fail the migration, which retries or retargets. Zero
        loss: the store keeps every message the read could not serve."""
        if parked:
            self.offline.extend(parked)
        self.offline_in_store = True
        self.broker.metrics.incr("msg_store_read_errors")
        self.state = prev_state
        if prev_state == OFFLINE:
            self._arm_expiry()

    def restore_online(self, msgs: List[Msg]) -> None:
        """Cancel a drain whose session is STILL ATTACHED (the MQTT5
        redirect path keeps the connection up through the drain): the
        handoff rolled back before the client was told anything, so
        re-enter ONLINE and redeliver ``msgs`` — the restored backlog,
        including chunks the target may have acked — locally. Chunks
        the target kept surface as QoS1 dupes if a later handoff
        succeeds; dupes beat loss."""
        self.state = ONLINE
        self._resuming = False
        buf, self._resume_buf = self._resume_buf, deque()
        for msg in msgs:
            self._deliver_online(msg)
        for msg in buf:
            self._deliver_online(msg)

    def drain_pending(self) -> List[Msg]:
        """Messages that raced into the queue after start_drain — the
        migration driver keeps draining until this runs dry (the reference
        re-fires drain_start on every mid-drain enqueue)."""
        more = [m for m in self.offline
                if m.expires_at is None or m.expires_at >= time.monotonic()]
        self.offline.clear()
        return more

    def terminate(self, reason: str) -> None:
        if self.state == TERMINATED:
            return
        self.state = TERMINATED
        self._cancel_expiry()
        for msg in self.offline:
            self._drop(msg)
        self.offline.clear()
        for msg in self.backlog:
            self._drop(msg)
        self.backlog.clear()
        for msg in self._resume_buf:
            self._drop(msg)
        self._resume_buf.clear()
        self._resuming = False
        self.broker.registry.queue_terminated(self.subscriber_id)
        self.broker.hooks_fire_all("on_client_gone", self.subscriber_id)
        self.broker.metrics.incr("queue_teardown")

    def _arm_expiry(self) -> None:
        """Persistent-session expiry (persistent_client_expiration config or
        MQTT5 session_expiry_interval), vmq_queue.erl:913-930."""
        expiry = self.opts.session_expiry or self.broker.config.persistent_client_expiration
        if expiry <= 0:
            return
        loop = asyncio.get_event_loop()

        async def _expire():
            await asyncio.sleep(expiry)
            while self.state == OFFLINE:
                try:
                    # serialized: expiry racing a re-register on another
                    # node must not delete the record it just claimed
                    await self.broker.registry.cleanup_subscriber_synced(
                        self.subscriber_id)
                    self.broker.metrics.incr("client_expired")
                    return
                except RuntimeError:
                    # coordinator unreachable (netsplit): retry — an
                    # expired client must eventually be cleaned, not leak
                    await asyncio.sleep(5.0)

        self._expiry_task = loop.create_task(_expire())

    def _cancel_expiry(self) -> None:
        if self._expiry_task is not None:
            self._expiry_task.cancel()
            self._expiry_task = None

    # -- enqueue path ------------------------------------------------------

    def enqueue(self, msg: Msg) -> None:
        """Hot-path entry from the registry fanout (vmq_queue:enqueue/2)."""
        self.broker.metrics.incr("queue_message_in")
        if self.state == ONLINE:
            if self._resuming:
                # the stored offline backlog is still in flight through
                # the batched resume: park live publishes until it has
                # been delivered (finish_resume drains this buffer) —
                # delivering now would reorder against older messages
                self._resume_buf.append(msg)
                return
            self._deliver_online(msg)
        elif self.state == OFFLINE:
            self._enqueue_offline(msg)
        elif self.state == DRAIN:
            # mid-drain arrival: queue it so the drain forwards it to the
            # new node (vmq_queue.erl:383-390) — dropping here was the
            # migration message-loss window. Goes through the normal
            # offline path: caps apply and the message is persisted in
            # case the broker dies mid-migration.
            self._enqueue_offline(msg)
        else:  # terminated: drop with accounting
            self._drop(msg)

    def _deliver_online(self, msg: Msg) -> None:
        if not self.sessions:
            self._enqueue_offline(msg)
            return
        if not self._try_sessions(msg):
            self._backpressure(msg)

    def _try_sessions(self, msg: Msg) -> bool:
        """Offer to the attached session(s); True iff someone took it."""
        if self.opts.deliver_mode == "balance" and len(self.sessions) > 1:
            # balance: one session per message, round-robin (the reference
            # picks randomly, vmq_queue.erl:826-835 — RR gives fairer tests)
            handlers = list(self.sessions.values())
            self._rr = (self._rr + 1) % len(handlers)
            ok = handlers[self._rr](msg)
            if ok:
                self.broker.metrics.incr("queue_message_out")
            return ok
        delivered = False
        for deliver in list(self.sessions.values()):
            if deliver(msg):
                delivered = True
                self.broker.metrics.incr("queue_message_out")
        return delivered

    def _backpressure(self, msg: Msg) -> None:
        """Every session refused (inflight + pending windows full): park in
        the queue-level backlog instead of dropping; cap + drop policy as
        the reference's online-queue cap (vmq_queue.erl:845-865)."""
        cap = self.opts.max_online_messages
        if cap > 0 and len(self.backlog) >= cap:
            if self.opts.queue_type == "fifo":
                self._drop(msg)  # tail-drop the new message
                return
            self._drop(self.backlog.popleft())  # lifo: oldest makes room
        self.backlog.append(msg)

    def notify_ready(self, session: object) -> None:
        """A session's window freed up (the notify→active transition,
        vmq_mqtt_fsm.erl:264-293): replay the parked backlog in arrival
        order until it refuses again. Peek-then-pop: a refused head must
        stay at the FRONT or same-subscriber delivery reorders
        (MQTT-4.6.0)."""
        if not self.backlog or self._resuming:
            return
        t0 = time.monotonic()
        while self.backlog and self.state == ONLINE and self.sessions:
            if not self._try_sessions(self.backlog[0]):
                break
            self.backlog.popleft()
        self.broker.metrics.observe(
            "stage_queue_flush_ms", (time.monotonic() - t0) * 1e3)

    # -- batched resume (storage/resume.py) --------------------------------

    def begin_resume(self) -> None:
        """The stored offline backlog is being read through the
        ResumeCollector: hold live delivery order until it lands."""
        self._resuming = True

    def merge_recovered(self, msgs: List[Msg]) -> None:
        """Merge a store-read backlog with whatever already sits in the
        offline deque: stored messages FIRST (they are the oldest),
        then deque entries that are NOT copies of a stored one. On the
        lazy-boot path the deque is a suffix of the store content (a
        publish arriving while parked lands in both), so a plain extend
        would deliver those twice; the multiset dedup keeps only the
        deque's store-write-failed stragglers (kept in memory only)."""
        if not msgs:
            return
        have: Dict[bytes, int] = {}
        for m in msgs:
            have[m.msg_ref] = have.get(m.msg_ref, 0) + 1
        keep = []
        for m in self.offline:
            if have.get(m.msg_ref, 0) > 0:
                have[m.msg_ref] -= 1
            else:
                keep.append(m)
        self.offline = deque(list(msgs) + keep)

    def finish_resume(self, msgs: List[Msg]) -> None:
        """The collector resolved this queue's stored backlog. Deliver
        it FIRST (it is older than anything parked), then drain the
        parked live publishes — same per-queue order a synchronous
        ``recover_offline`` + ``add_session`` flush would have
        produced."""
        if not self._resuming:
            return
        self._resuming = False
        buf, self._resume_buf = self._resume_buf, deque()
        if self.state == ONLINE and self.sessions:
            # delivery order: stored backlog (oldest) → offline-deque
            # stragglers (a detach window mid-resume, deduped against
            # the store read) → parked live publishes (newest) — the
            # same per-queue order the synchronous recover + flush
            # produced
            self.merge_recovered(msgs)
            parked, self.offline = self.offline, deque()
            if msgs:
                self.broker.metrics.incr("queue_initialized_from_storage")
            if parked:
                # handed to the session's inflight tracking; clear
                # storage exactly like the add_session offline flush
                self.broker.delete_offline(self.subscriber_id)
            for msg in parked:
                if (msg.expires_at is not None
                        and msg.expires_at < time.monotonic()):
                    self.broker.metrics.incr("queue_message_expired")
                    continue
                self._deliver_online(msg)
            for msg in buf:
                self._deliver_online(msg)
        elif self.state in (OFFLINE, DRAIN):
            # the session left (or a drain started) before the read
            # landed: stored messages merge to the FRONT of the offline
            # deque (deduped — anything the deque already holds from a
            # mid-resume detach is the same stored message); they stay
            # in the store, matching the sync recover path's
            # post-recover state. Parked live publishes were already
            # moved by del_session/start_drain; stragglers take the
            # offline path.
            self.merge_recovered(msgs)
            for msg in buf:
                self._enqueue_offline(msg)
        else:  # terminated while resuming: drop with accounting
            for msg in list(msgs) + list(buf):
                self._drop(msg)

    def _enqueue_offline(self, msg: Msg) -> None:
        if self.opts.clean_session:
            self._drop(msg)
            return
        if msg.qos == 0:
            # QoS0 is not stored for offline sessions (vmq_queue offline drop)
            self._drop(msg)
            return
        cap = self.opts.max_offline_messages
        if cap > 0 and len(self.offline) >= cap:
            if self.opts.queue_type == "fifo":
                self._drop(msg)  # tail-drop the new message
                return
            # lifo: drop the oldest to make room (vmq_queue.erl:845-865)
            self._drop(self.offline.popleft())
        self.offline.append(msg)
        self.broker.hooks_fire_all("on_offline_message", self.subscriber_id, msg)
        self.broker.store_offline(self.subscriber_id, msg)

    def _drop(self, msg: Msg) -> None:
        self.broker.metrics.incr("queue_message_drop")
        self.broker.hooks_fire_all("on_message_drop", self.subscriber_id, msg, "queue_drop")

    # -- introspection -----------------------------------------------------

    def info(self) -> Dict[str, object]:
        return {
            "subscriber_id": self.subscriber_id,
            "state": self.state,
            "sessions": len(self.sessions),
            "offline_messages": len(self.offline),
            "backlog_messages": len(self.backlog),
            "resuming": self._resuming,
            "clean_session": self.opts.clean_session,
            "deliver_mode": self.opts.deliver_mode,
            "started": self.created,
        }
