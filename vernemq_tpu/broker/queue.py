"""Per-subscriber queue: the broker-side mailbox between the registry fanout
and the client session(s).

Mirrors the reference queue gen_fsm (``apps/vmq_server/src/vmq_queue.erl``):
states ``online`` (≥1 attached session) / ``offline`` (persistent session,
no attachment) / ``drain`` (migration, later rounds); per-session delivery
with ``fanout``/``balance`` modes for multiple sessions per ClientId
(``vmq_queue.erl:826-835``); an offline queue capped by
``max_offline_messages`` with FIFO tail-drop or LIFO oldest-drop
(``vmq_queue.erl:845-865``); QoS0 dropped when offline; session-expiry
timer (``vmq_queue.erl:913-930``); lifecycle hooks ``on_client_wakeup`` /
``on_client_offline`` / ``on_client_gone`` / ``on_offline_message`` /
``on_message_drop`` (``vmq_queue.erl:614,658-700,1059-1070``).

The reference's active/passive/notify backpressure protocol between queue
and session process (``vmq_queue.erl:752-774``, ``vmq_mqtt_fsm.erl:264-293``)
collapses here to a two-level window: the session holds an inflight window
plus a ``pending`` list; when every attached session refuses a message the
queue keeps it in its own ``backlog`` (the passive-state queue) and the
session pulls it back via :meth:`SubscriberQueue.notify_ready` once acks
free its window (the notify→active transition). Only past
``max_online_messages`` of queue-level backlog do messages drop, with
accounting — matching the reference's online-queue cap.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import TYPE_CHECKING, Callable, Deque, Dict, List, Optional

from .message import Msg, SubscriberId

if TYPE_CHECKING:
    from .broker import Broker

ONLINE = "online"
OFFLINE = "offline"
DRAIN = "drain"
TERMINATED = "terminated"


class QueueOpts:
    __slots__ = (
        "clean_session",
        "max_offline_messages",
        "max_online_messages",
        "deliver_mode",
        "queue_type",
        "session_expiry",
        "is_plugin",
    )

    def __init__(
        self,
        clean_session: bool = True,
        max_offline_messages: int = 1000,
        max_online_messages: int = 1000,
        deliver_mode: str = "fanout",
        queue_type: str = "fifo",
        session_expiry: int = 0,  # seconds; 0 = persistent_client_expiration config
        is_plugin: bool = False,
    ):
        self.clean_session = clean_session
        self.max_offline_messages = max_offline_messages
        self.max_online_messages = max_online_messages
        self.deliver_mode = deliver_mode
        self.queue_type = queue_type
        self.session_expiry = session_expiry
        self.is_plugin = is_plugin


class SubscriberQueue:
    """One queue per SubscriberId (the reference partitions these across
    phash2 supervisors, vmq_queue_sup_sup.erl:65-92; a Python dict gives the
    same O(1) lookup without the supervision tree)."""

    def __init__(self, broker: "Broker", subscriber_id: SubscriberId, opts: QueueOpts):
        self.broker = broker
        self.subscriber_id = subscriber_id
        self.opts = opts
        self.state = OFFLINE
        # session_handle -> deliver callback; a handle is the Session object
        self.sessions: Dict[object, Callable[[Msg], bool]] = {}
        self._rr: int = 0  # round-robin cursor for balance mode
        self.offline: Deque[Msg] = deque()
        # online backpressure backlog: messages every session refused
        # (windows full) parked until notify_ready — the passive-state
        # per-session queue of the reference (vmq_queue.erl:752-774)
        self.backlog: Deque[Msg] = deque()
        self._expiry_task: Optional[asyncio.Task] = None
        self.created = time.time()

    # -- lifecycle ---------------------------------------------------------

    def add_session(self, session: object, deliver: Callable[[Msg], bool]) -> None:
        """Attach a session; offline→online wakes the queue and flushes the
        offline backlog through the new session (vmq_queue.erl:458-460 +
        init_offline_queue)."""
        was_offline = self.state == OFFLINE
        self.sessions[session] = deliver
        self.state = ONLINE
        self._cancel_expiry()
        if was_offline:
            self.broker.hooks_fire_all("on_client_wakeup", self.subscriber_id)
            backlog, self.offline = self.offline, deque()
            if backlog:
                # handed to the session's inflight tracking; clear storage
                # (per-ref deletes on ack come with the native store)
                self.broker.delete_offline(self.subscriber_id)
            for msg in backlog:
                if msg.expires_at is not None and msg.expires_at < time.monotonic():
                    self.broker.metrics.incr("queue_message_expired")
                    continue
                self._deliver_online(msg)

    def del_session(self, session: object) -> None:
        """Detach; last session out moves the queue offline (persistent) or
        tears it down (clean session), vmq_queue wait_for_offline."""
        self.sessions.pop(session, None)
        if self.sessions:
            return
        if self.opts.clean_session:
            self.terminate("normal")
        else:
            self.state = OFFLINE
            # park the backpressure backlog offline (insert_from_session,
            # vmq_queue.erl:867-881: undelivered messages survive the session)
            backlog, self.backlog = self.backlog, deque()
            for msg in backlog:
                self._enqueue_offline(msg)
            self.broker.hooks_fire_all("on_client_offline", self.subscriber_id)
            self._arm_expiry()

    def start_drain(self) -> List[Msg]:
        """Enter the drain state and hand the offline backlog to the
        migration driver (vmq_queue drain state, vmq_queue.erl:338-400).
        Enqueues arriving mid-drain are queued (drain({enqueue,..})
        inserts, vmq_queue.erl:383-390) and picked up by
        :meth:`drain_pending` — never dropped."""
        self.state = DRAIN
        self._cancel_expiry()
        backlog = list(self.backlog)
        self.backlog.clear()
        backlog += list(self.offline)
        self.offline.clear()
        return [m for m in backlog
                if m.expires_at is None or m.expires_at >= time.monotonic()]

    def drain_pending(self) -> List[Msg]:
        """Messages that raced into the queue after start_drain — the
        migration driver keeps draining until this runs dry (the reference
        re-fires drain_start on every mid-drain enqueue)."""
        more = [m for m in self.offline
                if m.expires_at is None or m.expires_at >= time.monotonic()]
        self.offline.clear()
        return more

    def terminate(self, reason: str) -> None:
        if self.state == TERMINATED:
            return
        self.state = TERMINATED
        self._cancel_expiry()
        for msg in self.offline:
            self._drop(msg)
        self.offline.clear()
        for msg in self.backlog:
            self._drop(msg)
        self.backlog.clear()
        self.broker.registry.queue_terminated(self.subscriber_id)
        self.broker.hooks_fire_all("on_client_gone", self.subscriber_id)
        self.broker.metrics.incr("queue_teardown")

    def _arm_expiry(self) -> None:
        """Persistent-session expiry (persistent_client_expiration config or
        MQTT5 session_expiry_interval), vmq_queue.erl:913-930."""
        expiry = self.opts.session_expiry or self.broker.config.persistent_client_expiration
        if expiry <= 0:
            return
        loop = asyncio.get_event_loop()

        async def _expire():
            await asyncio.sleep(expiry)
            while self.state == OFFLINE:
                try:
                    # serialized: expiry racing a re-register on another
                    # node must not delete the record it just claimed
                    await self.broker.registry.cleanup_subscriber_synced(
                        self.subscriber_id)
                    self.broker.metrics.incr("client_expired")
                    return
                except RuntimeError:
                    # coordinator unreachable (netsplit): retry — an
                    # expired client must eventually be cleaned, not leak
                    await asyncio.sleep(5.0)

        self._expiry_task = loop.create_task(_expire())

    def _cancel_expiry(self) -> None:
        if self._expiry_task is not None:
            self._expiry_task.cancel()
            self._expiry_task = None

    # -- enqueue path ------------------------------------------------------

    def enqueue(self, msg: Msg) -> None:
        """Hot-path entry from the registry fanout (vmq_queue:enqueue/2)."""
        self.broker.metrics.incr("queue_message_in")
        if self.state == ONLINE:
            self._deliver_online(msg)
        elif self.state == OFFLINE:
            self._enqueue_offline(msg)
        elif self.state == DRAIN:
            # mid-drain arrival: queue it so the drain forwards it to the
            # new node (vmq_queue.erl:383-390) — dropping here was the
            # migration message-loss window. Goes through the normal
            # offline path: caps apply and the message is persisted in
            # case the broker dies mid-migration.
            self._enqueue_offline(msg)
        else:  # terminated: drop with accounting
            self._drop(msg)

    def _deliver_online(self, msg: Msg) -> None:
        if not self.sessions:
            self._enqueue_offline(msg)
            return
        if not self._try_sessions(msg):
            self._backpressure(msg)

    def _try_sessions(self, msg: Msg) -> bool:
        """Offer to the attached session(s); True iff someone took it."""
        if self.opts.deliver_mode == "balance" and len(self.sessions) > 1:
            # balance: one session per message, round-robin (the reference
            # picks randomly, vmq_queue.erl:826-835 — RR gives fairer tests)
            handlers = list(self.sessions.values())
            self._rr = (self._rr + 1) % len(handlers)
            ok = handlers[self._rr](msg)
            if ok:
                self.broker.metrics.incr("queue_message_out")
            return ok
        delivered = False
        for deliver in list(self.sessions.values()):
            if deliver(msg):
                delivered = True
                self.broker.metrics.incr("queue_message_out")
        return delivered

    def _backpressure(self, msg: Msg) -> None:
        """Every session refused (inflight + pending windows full): park in
        the queue-level backlog instead of dropping; cap + drop policy as
        the reference's online-queue cap (vmq_queue.erl:845-865)."""
        cap = self.opts.max_online_messages
        if cap > 0 and len(self.backlog) >= cap:
            if self.opts.queue_type == "fifo":
                self._drop(msg)  # tail-drop the new message
                return
            self._drop(self.backlog.popleft())  # lifo: oldest makes room
        self.backlog.append(msg)

    def notify_ready(self, session: object) -> None:
        """A session's window freed up (the notify→active transition,
        vmq_mqtt_fsm.erl:264-293): replay the parked backlog in arrival
        order until it refuses again. Peek-then-pop: a refused head must
        stay at the FRONT or same-subscriber delivery reorders
        (MQTT-4.6.0)."""
        if not self.backlog:
            return
        t0 = time.monotonic()
        while self.backlog and self.state == ONLINE and self.sessions:
            if not self._try_sessions(self.backlog[0]):
                break
            self.backlog.popleft()
        self.broker.metrics.observe(
            "stage_queue_flush_ms", (time.monotonic() - t0) * 1e3)

    def _enqueue_offline(self, msg: Msg) -> None:
        if self.opts.clean_session:
            self._drop(msg)
            return
        if msg.qos == 0:
            # QoS0 is not stored for offline sessions (vmq_queue offline drop)
            self._drop(msg)
            return
        cap = self.opts.max_offline_messages
        if cap > 0 and len(self.offline) >= cap:
            if self.opts.queue_type == "fifo":
                self._drop(msg)  # tail-drop the new message
                return
            # lifo: drop the oldest to make room (vmq_queue.erl:845-865)
            self._drop(self.offline.popleft())
        self.offline.append(msg)
        self.broker.hooks_fire_all("on_offline_message", self.subscriber_id, msg)
        self.broker.store_offline(self.subscriber_id, msg)

    def _drop(self, msg: Msg) -> None:
        self.broker.metrics.incr("queue_message_drop")
        self.broker.hooks_fire_all("on_message_drop", self.subscriber_id, msg, "queue_drop")

    # -- introspection -----------------------------------------------------

    def info(self) -> Dict[str, object]:
        return {
            "subscriber_id": self.subscriber_id,
            "state": self.state,
            "sessions": len(self.sessions),
            "offline_messages": len(self.offline),
            "backlog_messages": len(self.backlog),
            "clean_session": self.opts.clean_session,
            "deliver_mode": self.opts.deliver_mode,
            "started": self.created,
        }
