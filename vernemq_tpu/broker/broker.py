"""The Broker: assembly root tying config, metrics, hooks, registry, retain
store, message store, sessions, and background services together.

Plays the role of the reference's supervision root
(``vmq_server_sup.erl:43-58`` boot order: config → msg store → queues →
registry → cluster → metrics → listeners) — in asyncio there is no
supervision tree, so this object owns construction order and shutdown.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..protocol import topic as T
from ..protocol.types import Will
from ..robustness import faults
from ..storage.msg_store import FileMsgStore, MemoryMsgStore, MsgStore
from .config import Config
from .message import Msg, SubscriberId
from .metrics import Metrics
from .plugins import HookError, HookRegistry
from .queue import SubscriberQueue
from .reg import Registry
from .retain import RetainStore

log = logging.getLogger("vernemq_tpu.broker")


def _log_hook_task_error(task: "asyncio.Task") -> None:
    if not task.cancelled() and task.exception() is not None:
        log.error("async hook handler failed", exc_info=task.exception())


class Broker:
    def __init__(self, config: Optional[Config] = None, node_name: str = "node1"):
        self.config = config or Config()
        self.node_name = node_name
        self._resolve_base_dirs()
        self.metrics = Metrics()
        self.hooks = HookRegistry()
        from ..plugins import PluginManager

        self.plugins = PluginManager(self)
        # replicated metadata store (vmq_metadata facade,
        # vmq_metadata.erl:24-28): ``metadata_plugin`` picks the backend the
        # way metadata_impl selects vmq_plumtree or vmq_swc — "lww" is the
        # plumtree-flavored LWW store, "swc" the server-wide-clock store
        persist_dir = (self.config.metadata_dir
                       if self.config.get("metadata_persistence", False)
                       else None)
        if self.config.get("metadata_plugin", "lww") == "swc":
            from ..cluster.swc_store import SWCMetadata

            self.metadata = SWCMetadata(
                node_name, persist_dir=persist_dir,
                n_groups=self.config.get("swc_replication_groups", 8),
                sync_interval=self.config.get("swc_sync_interval", 2.0),
                db_backend=self.config.get("swc_db_backend", "kvstore"))
        else:
            from ..cluster.metadata import MetadataStore

            self.metadata = MetadataStore(node_name, persist_dir=persist_dir)
        self.cluster: Optional[Any] = None  # set by cluster.Cluster
        # stall watchdog (robustness/watchdog.py): monitored-operation
        # registry + sacrificial dispatch for every cross-boundary wait
        # (device dispatch, rebuild threads, delta scatter, store
        # writes, cluster ack progress). Created unconditionally so the
        # gauges always exist; the monitor thread starts in start()
        # when watchdog_enabled.
        from ..robustness.watchdog import StallWatchdog

        self.watchdog = StallWatchdog(
            tick_s=self.config.get("watchdog_tick_ms", 100) / 1e3)
        self.retain = RetainStore(on_dirty=self._retain_dirty)
        # device-resident retained index (vernemq_tpu/retained/): created
        # lazily on the first replay once the tpu reg view is live; the
        # retain dirty hook write-throughs deltas into it
        self._retained_engine: Optional[Any] = None
        self._retained_collector: Optional[Any] = None
        self.metadata.subscribe("retain", self._on_retain_event)
        self.registry = Registry(self)
        # payload filtering & windowed aggregation (vernemq_tpu/filters/,
        # MQTT+): per-mountpoint schemas replicate through the metadata
        # plane like the mesh slice map; the engine runs the predicate
        # phase behind topic match. Disabled ⇒ both stay None and every
        # hook is one attribute test — byte-identical to the pre-filter
        # broker.
        self.schema_registry: Optional[Any] = None
        self.filter_engine: Optional[Any] = None
        if self.config.get("payload_filters_enabled", True):
            from ..filters.engine import FilterEngine
            from ..filters.schema_registry import SchemaRegistry

            self.schema_registry = SchemaRegistry(self.metadata, node_name)
            self.schema_registry.boot_install(
                self.config.get("payload_schemas", []))
            cfg = self.config
            self.filter_engine = FilterEngine(
                self.schema_registry, metrics=self.metrics,
                breaker_enabled=cfg.get("tpu_breaker_enabled", True),
                breaker_failure_threshold=cfg.get(
                    "tpu_breaker_failure_threshold", 3),
                breaker_backoff_initial=cfg.get(
                    "tpu_breaker_backoff_initial_ms", 200) / 1e3,
                breaker_backoff_max=cfg.get(
                    "tpu_breaker_backoff_max_ms", 10_000) / 1e3,
                host_threshold=cfg.get("predicate_host_threshold", 16),
                max_pairs=cfg.get("predicate_max_pairs", 65536),
                window_initial=cfg.get("aggregate_initial_windows", 256),
                window_cap=cfg.get("aggregate_max_windows", 4096),
                tick_ms=cfg.get("aggregate_tick_ms", 250),
                # the device phase runs only where the device lives:
                # never in SO_REUSEPORT workers (the match service owns
                # JAX; rows come back over the rings and the worker's
                # exact host evaluator filters them), and only while
                # the tpu view actually serves
                device_gate=lambda: (
                    self.match_client is None
                    and self.registry.batched_view_active()),
            )
            self.filter_engine.emit = self._deliver_aggregate
        # mesh slice map (cluster/mesh_map.py): slice→node ownership in
        # the replicated metadata plane, gossiped like the netsplit
        # CAPs. Created whenever a tpu_mesh is configured — single-node
        # deployments claim every slice at start; cluster membership
        # changes re-run the deterministic round-robin claim.
        self.mesh_map: Optional[Any] = None
        n_slices = self._mesh_slice_count()
        if n_slices:
            from ..cluster.mesh_map import MeshSliceMap

            self.mesh_map = MeshSliceMap(
                self.metadata, node_name, n_slices,
                on_adopt=self._on_mesh_adopt, metrics=self.metrics)
        fsync = bool(self.config.get("msg_store_fsync", False))
        # fsync group-commit: one fsync per write burst at the flush-tick
        # boundary instead of per record (msg_store_fsync_coalesced)
        gc_on = bool(self.config.get("msg_store_group_commit", True))
        seg_max = int(self.config.get("store_segment_max_bytes",
                                      8 * 1024 * 1024))
        ckpt_every = int(self.config.get("store_checkpoint_every_bytes",
                                         32 * 1024 * 1024))
        if self.config.message_store == "file":
            from ..storage.msg_store import SegmentMsgStore

            store_dir = self.config.message_store_dir
            if os.path.exists(os.path.join(store_dir, "msgstore.log")):
                # a legacy flat-log store already lives here — honour
                # its data rather than silently orphaning it
                log.warning("legacy flat-log msg store found in %s; "
                            "serving it (new dirs open the segment "
                            "engine)", store_dir)
                self.msg_store: MsgStore = FileMsgStore(
                    store_dir, fsync=fsync, group_commit=gc_on)
            else:
                # the pure-Python half of the unified segment engine
                # (storage/segment.py): checkpointed recovery, budgeted
                # broker-driven compaction — the same engine layer the
                # cluster spool journals through
                self.msg_store = SegmentMsgStore(
                    store_dir, fsync=fsync, group_commit=gc_on,
                    segment_max_bytes=seg_max,
                    checkpoint_every_bytes=ckpt_every)
        elif self.config.message_store == "native":
            from ..storage.msg_store import BucketedMsgStore, NativeMsgStore

            try:
                n = int(self.config.get("msg_store_instances", 1))
                store_dir = self.config.message_store_dir
                if n > 1 and os.path.exists(
                        os.path.join(store_dir, "msgstore.kv")):
                    # a flat single-instance store already lives here —
                    # honour it rather than silently orphaning its data
                    log.warning("legacy single-instance msg store found in "
                                "%s; ignoring msg_store_instances=%d",
                                store_dir, n)
                    n = 1
                # N engines hashed by msg-ref (vmq_lvldb_store_sup.erl:47-54)
                self.msg_store = (BucketedMsgStore(store_dir, n, fsync=fsync,
                                                   group_commit=gc_on)
                                  if n > 1
                                  else NativeMsgStore(store_dir, fsync=fsync,
                                                      group_commit=gc_on))
            except Exception as e:  # no toolchain → segment-log twin
                from ..storage.msg_store import SegmentMsgStore

                log.warning("native msg store unavailable (%s); "
                            "falling back to the segment-log engine", e)
                self.msg_store = SegmentMsgStore(
                    self.config.message_store_dir, fsync=fsync,
                    group_commit=gc_on, segment_max_bytes=seg_max,
                    checkpoint_every_bytes=ckpt_every)
        else:
            self.msg_store = MemoryMsgStore()
        # batched reconnect-storm resumption (storage/resume.py): built
        # lazily on the first deferrable recover when the store supports
        # off-loop batched reads; the store breaker + compaction driver
        # state lives here so the gauges always exist
        self._resume_collector: Optional[Any] = None
        self._store_commit_scheduled = False
        from ..robustness.breaker import CircuitBreaker

        self.store_breaker = CircuitBreaker(
            failure_threshold=self.config.get(
                "tpu_breaker_failure_threshold", 3),
            backoff_initial=self.config.get(
                "tpu_breaker_backoff_initial_ms", 200) / 1e3,
            backoff_max=self.config.get(
                "tpu_breaker_backoff_max_ms", 10_000) / 1e3,
            name="store")
        self.store_compactions = 0
        self.store_compacted_bytes = 0
        self.store_compact_paused = 0
        self.store_compact_errors = 0
        # last-drained (hits, misses) snapshot of the bucketed store's
        # probe counters (the maintenance tick moves deltas into $SYS)
        self._probe_drained = (0, 0)
        # corrupt records skipped by the store's recovery scan are
        # surfaced, not silent (the old behavior discarded the tail) —
        # and so is a checkpoint-discarding full-scan fallback
        skipped = getattr(self.msg_store, "recover_skipped", 0)
        if skipped:
            self.metrics.incr("msg_store_recover_skipped", skipped)
        fallbacks = sum(
            getattr(getattr(st, "engine", None), "recover_fallbacks", 0)
            for st in (getattr(self.msg_store, "instances", None)
                       or [self.msg_store]))
        if fallbacks:
            self.metrics.incr("store_recover_fallbacks", fallbacks)
        # live sessions: sid -> Session (the reference reaches sessions via
        # queue pids; a direct map is equivalent single-node)
        self.sessions: Dict[SubscriberId, Any] = {}
        # live queue-migration state, surfaced via `vmq-admin cluster
        # migrations` (the reference surfaces drain progress via queue
        # status / cluster show): sid -> {target, pending, retries, state}
        self.migrations: Dict[SubscriberId, Dict[str, Any]] = {}
        # live-handoff engine (cluster/handoff.py): the reusable
        # freeze->drain->fence->adopt FSM behind `vmq-admin handoff
        # drain|rebalance` and `cluster drain-node`; its breaker gates
        # admission so repeated rollbacks stop new moves piling onto a
        # broken successor
        from ..cluster.handoff import HandoffManager

        self.handoff = HandoffManager(self)
        self._delayed_wills: Dict[SubscriberId, asyncio.Task] = {}
        self.tracer: Optional[Any] = None  # single active session tracer
        # hot-path flight recorder (observability/recorder.py): the
        # 1-in-N publish sample decision is made once at admission
        # (session._handle_publish) and the trace rides the fold
        # envelope; `vmq-admin timeline show|dump` read the ring. The
        # dispatch profiler is process-global (observability/profiler)
        # — the matcher records into it without a broker handle.
        from ..observability import FlightRecorder

        self.recorder = FlightRecorder(
            sample_n=int(self.config.get("flight_recorder_sample_n", 32)),
            capacity=int(self.config.get("flight_recorder_capacity",
                                         4096)),
            node=node_name)
        # canary SLO probe (observability/canary.py): built at start()
        # when canary_enabled — the loopback subscription must not
        # exist unless the operator asked for the probe
        self.canary: Optional[Any] = None
        # multi-process session front end (broker/workers.py): when this
        # broker is one of N SO_REUSEPORT workers, the parent hands it a
        # shared stats slot (fused overload pressure, `vmq-admin workers
        # show`) and optionally a ring pair to the device-match service.
        # Both stay None in the classic single-process boot — the
        # workers=1 byte-identical guarantee.
        self.worker_index = int(self.config.get("worker_index", 0) or 0)
        self.worker_stats: Optional[Any] = None
        self.match_client: Optional[Any] = None
        self.sysmon: Optional[Any] = None
        self.overload: Optional[Any] = None  # adaptive overload governor
        self.supervisor: Optional[Any] = None  # crash-restart supervision
        self.crl_refresher: Optional[Any] = None
        self.http: Optional[Any] = None
        self.graphite: Optional[Any] = None
        self.listeners: Optional[Any] = None  # ListenerManager (transports)
        self._servers: List[Any] = []
        self._bg_tasks: List[asyncio.Task] = []
        self._started = time.time()
        self._cluster_ready = True  # single-node; cluster layer overrides
        self.metrics.register_gauges(self._gauges, {
            "router_subscriptions": "Subscriptions in the routing table.",
            "router_memory": "Approximate routing table memory (bytes).",
            "queue_processes": "Live subscriber queues.",
            "retain_messages": "Retained messages.",
            "retain_memory": "Approximate bytes used for storing "
                             "retained messages.",
            "active_sessions": "Currently connected sessions.",
            "uptime_seconds": "Broker uptime.",
            "tpu_hybrid_host_pubs": "Small flushes served by the host "
                                    "trie (hybrid dispatch).",
            "tpu_overload_shed_pubs": "Publishes shed to the trie at "
                                      "collector overload.",
            "tpu_rebuild_shed_pubs": "Publishes the trie served during "
                                     "a device table rebuild.",
            "tpu_busy_shed_pubs": "Publishes the trie served past the "
                                  "matcher-lock/cold-compile bound.",
            "tpu_saturated_merges": "Flushes merged into a later batch "
                                    "(both pipeline slots busy).",
            "tpu_async_rebuilds": "Background device-table rebuilds.",
            # degraded-mode observability (robustness tentpole): breaker
            # state + fallback/fault counters, published to $SYS like
            # every other metric by the systree reporter
            # adaptive overload governor (robustness/overload.py):
            # current level + composite pressure, per-level cumulative
            # seconds and entry counts, hysteresis extends, plus the
            # sysmon hysteresis counters the governor builds on
            "overload_level": "Current overload governor level (0 ok, "
                              "1 throttle, 2 shed, 3 refuse).",
            "overload_pressure": "Composite overload pressure score "
                                 "(max of the fused signal severities, "
                                 "0..1).",
            "overload_level_pinned": "Manually pinned overload level "
                                     "(-1 = automatic).",
            "overload_level_extends": "Overload hysteresis windows "
                                      "re-armed by boundary pressure.",
            "overload_l1_seconds": "Cumulative seconds spent at "
                                   "overload level 1.",
            "overload_l2_seconds": "Cumulative seconds spent at "
                                   "overload level 2.",
            "overload_l3_seconds": "Cumulative seconds spent at "
                                   "overload level 3.",
            "overload_level_enters_l1": "Transitions into overload "
                                        "level 1.",
            "overload_level_enters_l2": "Transitions into overload "
                                        "level 2.",
            "overload_level_enters_l3": "Transitions into overload "
                                        "level 3.",
            "sysmon_overload_extends": "Sysmon overload cooldowns "
                                       "re-armed by boundary lag "
                                       "(hysteresis extends).",
            "sysmon_last_loop_lag_seconds": "Most recent event-loop "
                                            "lag sample.",
            "tpu_breaker_state": "Device circuit breaker state "
                                 "(0 closed, 1 half-open, 2 open; worst "
                                 "across mountpoints).",
            "tpu_breaker_opens": "Breaker open transitions (device path "
                                 "degraded to the host trie).",
            "tpu_breaker_closes": "Breaker close transitions (device "
                                  "path recovered).",
            "tpu_breaker_time_degraded_seconds":
                "Cumulative seconds the device path spent degraded.",
            "tpu_device_failures": "Device dispatch/upload failures fed "
                                   "to the breaker.",
            "tpu_degraded_sheds": "Match calls refused while the "
                                  "breaker was open.",
            "tpu_degraded_host_pubs": "Publishes the host trie served "
                                      "while the breaker was open.",
            "tpu_delta_shapes_warmed": "Delta-scatter shapes "
                                       "pre-compiled at startup.",
            "fault_plan_active": "1 while a fault-injection plan is "
                                 "installed.",
            "faults_injected": "Faults raised by the active plan.",
            "faults_delayed": "Latency/hang faults applied by the "
                              "active plan.",
            # wire plane (protocol/fastpath.py + native/codec.cc)
            "wire_native_active": "1 while the native wire codec is "
                                  "serving batch parse/encode (built, "
                                  "enabled, breaker closed).",
            "wire_native_batches": "Recv buffers batch-parsed by the "
                                   "native frame-table builder.",
            "wire_pure_batches": "Recv buffers batch-parsed by the "
                                 "bit-identical pure-Python twin.",
            "wire_native_errors": "Native codec calls that failed and "
                                  "fed the wire breaker (the batch was "
                                  "re-served by the pure codec).",
            "wire_degraded_batches": "Batches served pure-Python while "
                                     "the wire breaker was open.",
            "wire_fastpath_pubs": "QoS0 publishes admitted through the "
                                  "object-free wire fast path (no "
                                  "frame/Msg objects materialised).",
            "wire_fastpath_pubs_qos": "QoS1/2 publishes admitted "
                                      "through the wire fast path (pid "
                                      "stamped from the frame-table "
                                      "span, no inbound frame object).",
            "wire_fastpath_acks": "Ack-family frames (PUBACK/PUBREC/"
                                  "PUBREL/PUBCOMP) resolved straight "
                                  "from the frame table with no frame "
                                  "object.",
            "wire_fanout_batches": "One-call batched fanout header "
                                   "encodes (publish_headers_batch): "
                                   "each emitted N per-recipient "
                                   "pid/alias-patched headers into one "
                                   "arena.",
            "wire_breaker_state": "Wire-codec breaker state (0 closed, "
                                  "1 half-open, 2 open).",
            # cluster delivery spool (cluster/spool.py): depth +
            # outstanding-ack gauges, published to $SYS/Prometheus
            "cluster_spool_depth_frames": "QoS>=1 cluster frames "
                                          "journaled awaiting acks.",
            "cluster_spool_depth_bytes": "Bytes journaled in the "
                                         "cluster delivery spool.",
            "cluster_spool_outstanding_acks": "Peers with spooled "
                                              "frames awaiting a "
                                              "cumulative ack.",
            "cluster_spool_peers_blocked": "Peers whose spooled stream "
                                           "is paused pending replay "
                                           "resync.",
            # device retained index (vernemq_tpu/retained/): monotonic
            # counts exposed like the tpu_breaker_* family
            "retained_index_rows": "Retained messages mirrored in the "
                                   "device reverse-match index.",
            "retained_index_rebuilds": "Full device retained-table "
                                       "(re)builds.",
            "retained_match_dispatches": "Batched retained reverse-match "
                                         "device dispatches.",
            "retained_match_queries": "Subscription filters served by "
                                      "the retained device path.",
            "retained_host_fallback_queries": "Filters the device could "
                                              "not serve exactly "
                                              "(host-resolved).",
            "retained_device_failures": "Retained dispatch/upload "
                                        "failures fed to the breaker.",
            "retained_degraded_sheds": "Retained match calls refused "
                                       "while the breaker was open.",
            "retained_breaker_state": "Retained device breaker state "
                                      "(0 closed, 1 half-open, 2 open; "
                                      "worst across mountpoints).",
            "retained_replay_deferred_flushes": "Replay flushes deferred "
                                                "by the overload "
                                                "governor (level 2+).",
            "retained_replay_device_batches": "Replay flushes served by "
                                              "the device path.",
            "retained_replay_device_filters": "Replay filters that rode "
                                              "a device dispatch.",
            "retained_replay_host_filters": "Small replay flushes served "
                                            "by the host walk (hybrid "
                                            "dispatch).",
            "retained_replay_degraded_filters": "Replay filters the host "
                                                "walk served while the "
                                                "breaker was open.",
            "retained_replay_rebuild_filters": "Replay filters the host "
                                               "walk served during a "
                                               "table rebuild.",
            "retained_replay_fallback_filters": "Per-filter device "
                                                "escapes resolved "
                                                "against the host store.",
            "retained_replay_stalled_filters": "Replay filters the host "
                                               "walk served after a "
                                               "dispatch deadline "
                                               "abandonment.",
            "retained_replay_expired_filters": "Queued replay filters "
                                               "host-served past their "
                                               "collector expiry.",
            # storage tier (storage/segment.py + storage/resume.py):
            # the unified segment engine's health + the batched
            # reconnect-storm resumption counters
            "store_breaker_state": "Store compaction breaker state "
                                   "(0 closed, 1 half-open, 2 open; "
                                   "open = append-only degraded mode).",
            "store_live_bytes": "Live record bytes across every "
                                "segment/kv engine (msg store + "
                                "cluster spool).",
            "store_garbage_bytes": "Dead record bytes awaiting "
                                   "budgeted compaction across every "
                                   "engine.",
            "store_segments": "On-disk segment files across every "
                              "segment-log engine.",
            "resume_batched_sessions": "Reconnecting sessions whose "
                                       "offline replay rode a batched "
                                       "off-loop store read.",
            "resume_batched_reads": "Batched off-loop read_many calls "
                                    "issued by the resume collector.",
            "resume_host_sessions": "Small resume flushes served by "
                                    "the per-session read on the loop "
                                    "(hybrid dispatch).",
            "resume_expired_sessions": "Queued resumes served by the "
                                       "exact per-session fallback "
                                       "past their expiry.",
            "resume_fallback_sessions": "Sessions served per-session "
                                        "after a batched read failed.",
            "resume_deferred_flushes": "Resume flushes deferred by the "
                                       "overload governor (level 2+).",
            "resume_pending_sessions": "Reconnect resumes queued in "
                                       "the collector window.",
            "retained_dispatch_stalls": "Retained dispatches abandoned "
                                        "at the watchdog deadline (fed "
                                        "to the breaker).",
            "retained_rebuild_abandons": "Wedged retained rebuilds "
                                         "abandoned by the watchdog.",
            # stall watchdog (robustness/watchdog.py): the silent-stall
            # observability family — every cross-boundary wait registers
            # here, overdue ops are counted/abandoned, late results of
            # abandoned ops are discarded (never delivered)
            "watchdog_stalls": "Monitored operations observed past "
                               "their deadline.",
            "watchdog_abandoned": "Stalled operations abandoned "
                                  "(waiters released to the host "
                                  "fallback; breaker fed).",
            "watchdog_late_discarded": "Abandoned operations that "
                                       "completed late; their results "
                                       "were discarded, never "
                                       "delivered.",
            "watchdog_cluster_stalls": "Cluster channels cycled by "
                                       "ack-progress stall detection.",
            "watchdog_inflight_ops": "Monitored operations currently "
                                     "in flight.",
            "watchdog_inflight_age_max": "Age (seconds) of the oldest "
                                         "in-flight monitored "
                                         "operation.",
            "watchdog_sacrificed_threads": "Executor workers lost to "
                                           "abandoned (wedged) "
                                           "dispatches; the pool "
                                           "spawned around each.",
            "faults_wedged_now": "Injection points currently blocked "
                                 "in a wedge fault.",
            "faults_wedge_releases": "Wedge faults released (watchdog "
                                     "abandonment or `vmq-admin fault "
                                     "release`).",
            "tpu_stalled_host_pubs": "Publishes the host trie served "
                                     "after a dispatch deadline "
                                     "abandonment.",
            "tpu_expired_host_pubs": "Queued publishes host-served "
                                     "past their collector expiry.",
            "tpu_dispatch_stalls": "Device dispatches abandoned at the "
                                   "watchdog deadline (fed to the "
                                   "breaker).",
            "tpu_rebuild_abandons": "Wedged device-table rebuilds "
                                    "abandoned by the watchdog.",
            # multi-process front end (broker/workers.py +
            # broker/match_service.py): per-worker counters aggregated
            # at the scrape/$SYS point from the shared stats block,
            # plus the worker's own match-service client stats
            "workers_total": "Worker slots in the shared stats block "
                             "(the SO_REUSEPORT group size).",
            "workers_alive": "Workers with a fresh heartbeat in the "
                             "shared stats block.",
            "workers_sessions_total": "Connected sessions summed "
                                      "across live workers.",
            "workers_admitted_pubs_total": "PUBLISHes admitted summed "
                                           "across live workers.",
            "workers_level_max": "Highest overload level any live "
                                 "worker reports (the fused L2/L3 "
                                 "shedding gate).",
            "workers_pressure_max": "Highest local overload pressure "
                                    "any live worker reports.",
            "overload_peer_pressure": "Peer-worker pressure fused into "
                                      "this governor (0 outside "
                                      "multi-process mode).",
            "match_client_folds": "Fold batches this worker shipped to "
                                  "the match service.",
            "match_client_fold_pubs": "Publishes that rode a "
                                      "match-service fold batch.",
            "match_client_timeouts": "Match-service folds abandoned at "
                                     "the reply deadline (local trie "
                                     "served).",
            "match_client_stalls": "Match-service folds abandoned by "
                                   "the stall watchdog (local trie "
                                   "served).",
            "match_client_degraded": "Folds refused while the "
                                     "match-service breaker was open "
                                     "(local trie served).",
            "match_client_held": "Folds served locally while an op "
                                 "backlog/resync was still in flight "
                                 "(ordering fence).",
            "match_client_ops_sent": "Subscription write ops forwarded "
                                     "to the match service.",
            "match_client_ops_dropped": "Subscription ops dropped on "
                                        "backlog overflow (a full "
                                        "resync replaces them).",
            "match_client_resyncs": "Owned-row replays after a "
                                    "match-service (re)start.",
            "match_client_breaker_state": "Match-service client breaker "
                                          "state (0 closed, 1 "
                                          "half-open, 2 open).",
            "match_client_op_backlog": "Subscription ops buffered "
                                       "while the request ring is "
                                       "full.",
            # flight recorder (observability/recorder.py)
            "flight_sampled": "Publishes sampled by the flight "
                              "recorder (1-in-N at admission).",
            "flight_records": "Stage-stamped publish records currently "
                              "in the flight-recorder ring.",
            "flight_sample_n": "Flight-recorder sampling divisor "
                               "(every Nth admitted publish records).",
            "flight_resumed": "Flight-recorder traces resumed from a "
                              "cluster peer's propagated context "
                              "(cross-node publishes).",
            # mesh-native matcher (parallel/mesh_match.py) + slice map
            # (cluster/mesh_map.py): slice residency and delta-routing
            # effectiveness — all zero outside mesh mode
            "mesh_slices_total": "Mesh matcher slices in the slice map "
                                 "(the 'sub' axis size; 0 when no mesh "
                                 "is configured).",
            "mesh_slices_local": "Mesh slices owned by this node per "
                                 "the gossiped slice map.",
            "mesh_rows_resident": "Active subscription rows resident "
                                  "across the local mesh slices.",
            "mesh_dispatches": "Mesh-native match dispatches pulled.",
            "mesh_delta_flushes": "Slice-routed delta flushes applied "
                                  "to the mesh table.",
            "mesh_delta_dirty_slices": "Dirty slices scattered across "
                                       "all delta flushes (flushes x "
                                       "slices touched; the routing "
                                       "numerator).",
            "mesh_delta_gzone_flushes": "Delta flushes that also "
                                        "touched the replicated dense "
                                        "g-zone mirrors (replication "
                                        "cost, not a routing miss).",
            "mesh_delta_rows": "Subscription rows shipped by "
                               "slice-routed delta flushes.",
            "mesh_full_scatters": "Full-table mesh placements (builds "
                                  "and growth re-partitions — never a "
                                  "delta path).",
            "mesh_slice_adoptions": "Slice-map adoptions replayed into "
                                    "the device table (exactly once "
                                    "per epoch).",
            # shared-memory ring publish ordering (parallel/shm_ring.py)
            "shm_ring_fence": "1 when the native release fence backs "
                              "ShmRing tail publishes, 0 on the "
                              "pure-Python x86-TSO fallback.",
            # payload filtering & aggregation (vernemq_tpu/filters/):
            # predicate-phase + window-table health, the tpu_breaker_*
            # pattern extended to the third device path
            "predicate_compiled": "Distinct compiled predicate rows "
                                  "resident in the device predicate "
                                  "tables.",
            "predicate_dispatches_total": "Predicate-phase device "
                                          "dispatches completed.",
            "predicate_host_batches": "Predicate batches served by the "
                                      "exact host evaluator (degraded/"
                                      "small/forced-host).",
            "predicate_rows_filtered_total": "Matched fanout rows "
                                             "removed by payload "
                                             "predicates.",
            "predicate_degraded_sheds_total": "Predicate dispatches "
                                              "refused while the "
                                              "breaker was open (host "
                                              "evaluator served).",
            "predicate_device_failures_total": "Predicate device "
                                               "failures fed to the "
                                               "breaker.",
            "predicate_dispatch_stalls": "Predicate dispatches "
                                         "abandoned at the watchdog "
                                         "deadline (fed to the "
                                         "breaker).",
            "predicate_fail_open_errors": "Predicate phase internal "
                                          "errors that delivered the "
                                          "batch unfiltered (fail-"
                                          "open, loud).",
            "predicate_breaker_state": "Predicate device breaker state "
                                       "(0 closed, 1 half-open, 2 "
                                       "open).",
            "predicate_breaker_opens": "Predicate breaker open "
                                       "transitions (device phase "
                                       "degraded to the host "
                                       "evaluator).",
            "aggregate_windows_open": "Aggregation windows currently "
                                      "accumulating.",
            "aggregate_window_capacity": "Aggregation accumulator-"
                                         "table capacity (grows in "
                                         "doublings to the cap).",
            "aggregate_window_overflows": "Aggregation subscriptions "
                                          "degraded to raw delivery "
                                          "because the window table "
                                          "was full.",
            "aggregate_emissions_total": "Synthesized aggregate "
                                         "PUBLISHes emitted by closed "
                                         "windows.",
            # membership health plane (cluster/health.py): detector
            # verdicts + this node's gossiped load, published like the
            # breaker/governor families
            "cluster_health_suspect_peers": "Peers the accrual failure "
                                            "detector currently marks "
                                            "suspect.",
            "cluster_health_down_peers": "Peers the accrual failure "
                                         "detector currently declares "
                                         "down.",
            "cluster_health_quorum": "1 while this node sees a "
                                     "majority of the joined "
                                     "membership (automatic rebalance "
                                     "admissible).",
            "cluster_load_score": "This node's gossiped load score "
                                  "(queue depth + loop-lag p99 + "
                                  "governor pressure; order matters, "
                                  "not units).",
            "rebalance_cycles": "Automatic planner cycles that passed "
                                "every safety rail and acted.",
        })
        from ..observability import events as _events
        from ..observability.canary import GAUGE_HELP as _canary_help

        self.metrics.register_gauges(self._observability_gauges,
                                     {**_events.gauge_help(),
                                      **_canary_help})

    # ------------------------------------------------------------ plumbing

    def _mesh_slice_count(self) -> int:
        """'sub'-axis size from the ``tpu_mesh`` spec via the ONE
        shared (jax-free) parser — the slice map must exist before
        (and regardless of whether) a backend initialises."""
        if not bool(self.config.get("tpu_mesh_native", True)):
            return 0
        from ..cluster.mesh_map import parse_mesh_spec

        parsed = parse_mesh_spec(self.config.get("tpu_mesh", ""))
        return parsed[1] if parsed else 0

    def _on_mesh_adopt(self, slice_ids, epoch: int) -> None:
        """Slice-map adoption: replay the newly-owned slices' rows into
        the mesh matcher exactly once per epoch. Touches only an
        ALREADY-BUILT tpu view — adoption before the view exists is a
        no-op because the first build ships every owned row anyway.
        The replay takes the matcher lock, which a device flush can
        hold for a long time — and this fires from metadata gossip
        callbacks on the event-loop thread, so it is pushed to an
        executor (the exactly-once guard lives inside adopt_slices,
        so deferred execution stays idempotent)."""
        view = self.registry.reg_views.get("tpu")
        fn = getattr(view, "adopt_slices", None)
        if fn is None:
            return

        def _adopt() -> None:
            try:
                fn(slice_ids, epoch)
            except Exception:
                log.exception("mesh slice adoption failed for %s",
                              slice_ids)

        try:
            asyncio.get_running_loop().run_in_executor(None, _adopt)
        except RuntimeError:
            _adopt()  # no loop (sync/unit-test use): inline is safe

    def _mesh_gauges(self) -> Dict[str, float]:
        out = {
            "mesh_slices_total": 0.0, "mesh_slices_local": 0.0,
            "mesh_rows_resident": 0.0, "mesh_dispatches": 0.0,
            "mesh_delta_flushes": 0.0, "mesh_delta_dirty_slices": 0.0,
            "mesh_delta_gzone_flushes": 0.0, "mesh_delta_rows": 0.0,
            "mesh_full_scatters": 0.0, "mesh_slice_adoptions": 0.0,
        }
        mm = self.mesh_map
        if mm is not None:
            out["mesh_slices_total"] = float(mm.n_slices)
            out["mesh_slices_local"] = float(len(mm.local_slices()))
        view = self.registry.reg_views.get("tpu")
        st = getattr(view, "mesh_status", None)
        st = st() if st is not None else None
        if view is not None and st is None:
            # tpu view built but serving single-chip (tpu_mesh degraded
            # / mesh-native off): local residency must read zero — the
            # configured slice count stays visible for diagnosis
            out["mesh_slices_local"] = 0.0
        if st:
            out["mesh_slices_total"] = max(out["mesh_slices_total"],
                                           float(st["slices"]))
            out["mesh_rows_resident"] = float(sum(st["rows_per_slice"]))
            out["mesh_dispatches"] = float(st["mesh_dispatches"])
            out["mesh_delta_flushes"] = float(st["route_flushes"])
            out["mesh_delta_dirty_slices"] = float(
                st["route_dirty_slices"])
            out["mesh_delta_gzone_flushes"] = float(
                st["route_gzone_flushes"])
            out["mesh_delta_rows"] = float(st["route_rows"])
            out["mesh_full_scatters"] = float(st["full_scatters"])
            out["mesh_slice_adoptions"] = float(st["slice_adoptions"])
        return out

    def _gauges(self) -> Dict[str, float]:
        out = dict(self.registry.stats())
        out["retain_messages"] = len(self.retain)
        out["retain_memory"] = self.retain.memory()
        out["active_sessions"] = len(self.sessions)
        out["uptime_seconds"] = time.time() - self._started
        if self.overload is not None:
            out.update(self.overload.stats())
        if self.sysmon is not None:
            st = self.sysmon
            out["sysmon_overload_extends"] = float(st.overload_extends)
            out["sysmon_last_loop_lag_seconds"] = round(st.last_lag, 4)
        spool = getattr(self.cluster, "spool", None)
        if spool is not None:
            out.update(spool.stats())
        if self.worker_stats is not None:
            # scrape-point aggregation: every worker writes only its own
            # slot; any worker's scrape (and the parent's bench reads)
            # fuse the block into one node-level view
            try:
                slots = self.worker_stats.read_all()
                live = [s for s in slots
                        if s["heartbeat_age_s"] is not None
                        and s["heartbeat_age_s"] < 5.0]
                out["workers_total"] = float(self.worker_stats.n_workers)
                out["workers_alive"] = float(len(live))
                out["workers_sessions_total"] = float(
                    sum(s["sessions"] for s in live))
                out["workers_admitted_pubs_total"] = float(
                    sum(s["admitted_pubs"] for s in live))
                out["workers_level_max"] = float(
                    max((s["level"] for s in live), default=0))
                out["workers_pressure_max"] = round(
                    max((s["pressure"] for s in live), default=0.0), 4)
            except Exception:
                pass  # a torn attach must never break the scrape
        if self.match_client is not None:
            out.update(self.match_client.stats_dict())
        if self._retained_engine is not None:
            out.update(self._retained_engine.stats())
        if self._retained_collector is not None:
            out.update(self._retained_collector.stats())
        if self.filter_engine is not None:
            out.update(self.filter_engine.stats())
        # storage tier (unified segment engine + batched resumption)
        out["store_breaker_state"] = float(self.store_breaker.state)
        live = garbage = segs = 0.0
        for eng in self._store_engines():
            try:
                est = eng.stats()
            except Exception:
                continue
            live += float(est.get("live_bytes", 0))
            garbage += float(est.get("garbage_bytes", 0))
            segs += float(est.get("segments", 0))
        out["store_live_bytes"] = live
        out["store_garbage_bytes"] = garbage
        out["store_segments"] = segs
        if self._resume_collector is not None:
            out.update(self._resume_collector.stats())
        out.update(self.watchdog.stats())
        out.update(self.recorder.stats())
        out.update(self._mesh_gauges())
        health = getattr(self.cluster, "health", None)
        if health is not None:
            from ..cluster.health import DOWN, SUSPECT, local_load_score

            states = [p.state for p in health.peers.values()]
            out["cluster_health_suspect_peers"] = float(
                states.count(SUSPECT))
            out["cluster_health_down_peers"] = float(states.count(DOWN))
            out["cluster_health_quorum"] = 1.0 if health.quorum_ok() \
                else 0.0
            out["cluster_load_score"] = local_load_score(self)
            planner = getattr(self.cluster, "planner", None)
            if planner is not None:
                out["rebalance_cycles"] = float(planner.cycles)
        from ..parallel.shm_ring import fence_active

        out["shm_ring_fence"] = 1.0 if fence_active() else 0.0
        return out

    def _observability_gauges(self) -> Dict[str, float]:
        """Event-journal counters (process-global ring) plus the canary
        probe's counters — split from _gauges so the HELP text comes
        from the registries themselves (events.KNOWN_EVENTS / canary
        GAUGE_HELP), never a drifting literal."""
        from ..observability import events as _events

        out = _events.journal().stats()
        if self.canary is not None:
            out.update(self.canary.stats())
        return out

    def _peer_histograms(self):
        """Merged stage-histogram blocks of every OTHER live worker
        (heartbeat-fresh slots only — a dead worker's frozen block must
        not pin the tail forever). Wired as ``metrics.histogram_extra``
        in worker mode."""
        from ..observability import histogram as _hist

        ws = self.worker_stats
        out = {}
        if ws is None:
            return out
        for i in range(ws.n_workers):
            if i == self.worker_index:
                continue
            slot = ws.read_slot(i)
            hb = slot.get("heartbeat_age_s")
            if hb is None or hb > 5.0:
                continue
            for name, snap in _hist.unpack_flat(ws.read_hist(i)).items():
                cur = out.get(name)
                out[name] = _hist.merge(cur, snap) if cur else snap
        # the match service's block carries the device-side seams
        # (dispatch/delta/rebuild run in ITS process) — merged when the
        # service is live and a DIFFERENT process (an in-process service
        # shares this worker's registry; merging its block would double
        # count every observation)
        try:
            svc = ws.service_info()
            if (svc.get("pid") and svc["pid"] != os.getpid()
                    and svc.get("heartbeat_age_s") is not None
                    and svc["heartbeat_age_s"] < 5.0):
                for name, snap in _hist.unpack_flat(
                        ws.read_service_hist()).items():
                    cur = out.get(name)
                    out[name] = _hist.merge(cur, snap) if cur else snap
        except Exception:
            pass
        return out

    def merged_journal_events(self, merge: bool = False):
        """The control-plane event stream for this node: the local
        journal (full detail), plus — with ``merge`` in worker mode —
        every OTHER live worker's packed slot events and the match
        service's, interleaved by monotonic stamp into ONE list
        (`vmq-admin events dump --merge` / `timeline dump --merge`; the
        on-hardware capture item scrapes one worker instead of N)."""
        from ..observability import events as _events

        out = _events.journal().snapshot()
        ws = self.worker_stats
        if not merge or ws is None:
            return out
        my_pid = os.getpid()
        for i in range(ws.n_workers):
            if i == self.worker_index:
                continue
            slot = ws.read_slot(i)
            hb = slot.get("heartbeat_age_s")
            if hb is None or hb > 5.0:
                continue
            out.extend(_events.unpack(ws.read_events(i),
                                      pid=slot.get("pid", 0)))
        try:
            svc = ws.service_info()
            if (svc.get("pid") and svc["pid"] != my_pid
                    and svc.get("heartbeat_age_s") is not None
                    and svc["heartbeat_age_s"] < 5.0):
                out.extend(_events.unpack(ws.read_service_events(),
                                          pid=svc["pid"]))
        except Exception:
            pass  # an old-layout block (no event region) stays healthy
        # a peer's packed ring may overlap what we read last time;
        # dedup on the (stamp, code, pid) identity, then one timeline
        seen = set()
        uniq = []
        for e in sorted(out, key=lambda e: e["t"]):
            key = (round(e["t"], 6), e["code"], e.get("pid", 0))
            if key in seen:
                continue
            seen.add(key)
            uniq.append(e)
        return uniq

    def cluster_ready(self) -> bool:
        """is_ready consistency gate (vmq_cluster.erl:67-92)."""
        if self.cluster is not None:
            return self.cluster.is_ready()
        return self._cluster_ready

    # ------------------------------------------------- retain replication

    def _retain_dirty(self, mountpoint: str, topic, value) -> None:
        """Write-behind from the retain cache into the replicated metadata
        store (vmq_retain_srv.erl:186-191 persist + broadcast)."""
        term = None
        if value is not None:
            term = {"payload": value.payload, "props": value.properties,
                    "qos": value.qos, "exp": value.expiry_ts}
        self.metadata.put("retain", (mountpoint,) + tuple(topic), term)
        if self._retained_engine is not None:
            # delta-scatter write-through into the device retained index
            self._retained_engine.on_retain(mountpoint, tuple(topic), value)

    @staticmethod
    def _retain_term(value):
        """Replicated retain term → RetainedMsg (None passes through)."""
        if value is None:
            return None
        from .reg import RetainedMsg

        return RetainedMsg(value["payload"], dict(value.get("props") or {}),
                           value.get("qos", 0), value.get("exp"))

    def _on_retain_event(self, key, old, new, origin) -> None:
        if origin == self.node_name:
            return  # local writes already applied write-through
        mountpoint, topic = key[0], tuple(key[1:])
        value = self._retain_term(new)
        self.retain.apply_remote(mountpoint, topic, value)
        if self._retained_engine is not None:
            # replicated retain changes bypass the dirty hook; the
            # device index must still see them
            self._retained_engine.on_retain(mountpoint, topic, value)

    # -------------------------------------------------- queue migration

    def on_subscriber_moved(self, sid: SubscriberId, new_node: str) -> None:
        """A persistent subscriber's record now points at another node:
        hand off our queue — close any live session (cross-node takeover),
        drain the offline backlog over the acked cluster channel, drop
        local state (vmq_reg_mgr.erl:155-243 + vmq_queue migrate/drain)."""
        queue = self.registry.queues.get(sid)
        if queue is None:
            return
        cur = self.migrations.get(sid)
        if cur is not None and cur.get("state") == "handoff":
            # the live-handoff FSM is already moving this queue — its
            # own fence phase wrote the record that fired this hook;
            # a second concurrent drain task would double-ship
            return
        # register the migration BEFORE the task first runs: callers (the
        # graceful-leave wait loop) poll this map right after the record
        # rewrite, and a not-yet-scheduled task must already count.
        # Retarget bookkeeping (a leave retrying a dead target) survives
        # the re-registration so each peer is tried at most once.
        prev = self.migrations.get(sid) or {}
        self.migrations[sid] = {"target": new_node,
                                "pending": len(queue.offline),
                                "retries": 0, "state": "draining",
                                **{k: prev[k] for k in ("tried",)
                                   if k in prev}}
        task = asyncio.get_event_loop().create_task(
            self._migrate_queue(sid, queue, new_node))
        self._bg_tasks.append(task)

    async def _migrate_queue(self, sid: SubscriberId, queue, new_node: str) -> None:
        session = self.sessions.get(sid)
        if session is not None:
            await session.takeover_close()
        try:
            backlog = queue.start_drain()
        except Exception:
            # the stored backlog could not be read (start_drain restored
            # the queue untouched — state, parked publishes, in-store
            # marker): fail the migration so the retarget/retry machinery
            # owns recovery; nothing was shipped, nothing may be deleted
            st = self.migrations.get(sid)
            if st is not None:
                st["state"] = "failed"
            self.metrics.incr("queue_drain_failed")
            log.exception("queue drain %s -> %s could not load the "
                          "stored backlog; migration failed, local "
                          "state intact", sid, new_node)
            return
        step = self.config.max_msgs_per_drain_step
        # retry/settle delay between drain steps (vmq_server.schema
        # max_drain_time, ms): the reference re-arms drain_start after
        # DrainTimeout on a failed step (vmq_queue.erl:365-368); the ack
        # timeout itself stays remote_enqueue_timeout
        drain_retry_delay = self.config.get("max_drain_time", 500) / 1000.0
        max_retries = self.config.get("migrate_drain_retries", 60)
        state = self.migrations.setdefault(
            sid, {"target": new_node, "retries": 0, "state": "draining"})
        state["pending"] = len(backlog)
        while True:
            sent = 0
            ok = self.cluster is not None
            if backlog and ok:
                for i in range(0, len(backlog), step):
                    try:
                        ok = await self.cluster.remote_enqueue(
                            new_node, sid, backlog[i:i + step])
                    except (ConnectionError, asyncio.TimeoutError):
                        ok = False
                    if not ok:
                        break
                    sent = i + step
                    state["pending"] = len(backlog) - sent
            if ok:
                # messages that raced in mid-drain follow the migration
                # (drain({enqueue,..}) re-fires drain_start,
                # vmq_queue.erl:383-390): keep pulling until dry
                more = queue.drain_pending()
                if more:
                    backlog = more
                    state["pending"] = len(backlog)
                    continue
                self.delete_offline(sid)
                self.metrics.incr("queue_migrated")
                # clean_session stays False: queue_terminated must NOT delete
                # the subscriber record — the new owner just rewrote it
                queue.terminate("migrated")
                self.migrations.pop(sid, None)
                return
            # drain failed mid-way: keep the unsent tail (an unacked chunk
            # may have landed — at-least-once, like any QoS1 redelivery) and
            # retry while the record still points away (block_until_migrated
            # retry loop, vmq_reg.erl:225-244) — bounded: a peer that never
            # acks must not pin a drain task forever
            backlog = backlog[sent:]
            state["pending"] = len(backlog)
            state["retries"] += 1
            self.metrics.incr("queue_drain_retry")
            log.warning("queue drain %s -> %s failed, %d msgs pending "
                        "(retry %d/%d)", sid, new_node, len(backlog),
                        state["retries"], max_retries)
            if state["retries"] >= max_retries:
                from .queue import OFFLINE

                queue.offline.extend(backlog)
                queue.state = OFFLINE
                queue._arm_expiry()  # start_drain cancelled the clock
                state["state"] = "failed"
                self.metrics.incr("queue_drain_failed")
                log.error("queue drain %s -> %s abandoned after %d retries; "
                          "%d msgs restored to the local offline queue",
                          sid, new_node, max_retries, len(backlog))
                return
            await asyncio.sleep(drain_retry_delay)
            rec = self.registry.db.read(sid)
            if rec is None or rec.node == self.node_name:
                # moved back / cleaned up: restore what's left locally
                from .queue import OFFLINE

                queue.offline.extend(backlog)
                queue.state = OFFLINE
                queue._arm_expiry()  # start_drain cancelled the clock
                self.migrations.pop(sid, None)
                return

    def hooks_fire_all(self, name: str, *args: Any) -> None:
        """Fire-and-forget lifecycle hooks (on_register/on_publish/...).
        Sync handlers run inline on the hot path; async handlers are
        scheduled (the reference calls these synchronously in-process)."""
        for fn in self.hooks.handlers(name):
            try:
                res = fn(*args)
                if inspect.isawaitable(res):
                    task = asyncio.ensure_future(res)
                    task.add_done_callback(_log_hook_task_error)
            except Exception:
                log.exception("hook %s handler %r failed", name, fn)

    async def auth_publish(
        self,
        sid: SubscriberId,
        username: Optional[str],
        topic: Tuple[str, ...],
        payload: bytes,
        qos: int,
        retain: bool,
        proto_ver: int,
        properties: Optional[dict] = None,
    ) -> Dict[str, Any]:
        """auth_on_publish(_m5) chain; returns modifier dict (may rewrite
        topic/payload), raises HookError on deny
        (vmq_mqtt_fsm.erl:681-746)."""
        hook = "auth_on_publish_m5" if proto_ver == 5 else "auth_on_publish"
        try:
            res = await self.hooks.all_till_ok(
                hook, username, sid, qos, topic, payload, retain
            )
        except HookError as e:
            if e.reason == "no_matching_hook_found":
                # no plugin answered: allowed unless default-deny is active
                # (vmq_auth.erl:3-8 registers deny hooks when
                # allow_anonymous=off)
                if self.config.allow_anonymous:
                    return {}
                raise HookError("not_authorized") from None
            raise
        if isinstance(res, tuple):
            return res[1]
        return {}

    # ----------------------------------------------------- session support

    async def takeover(self, sid: SubscriberId, new_session: Any) -> None:
        """Duplicate ClientId: disconnect the live session
        (vmq_connect_SUITE takeover semantics)."""
        old = self.sessions.get(sid)
        if old is not None and old is not new_session:
            await old.takeover_close()

    def schedule_will(self, sid: SubscriberId, will: Will, mountpoint: str,
                      proto_ver: int, session_expiry: int) -> None:
        """Publish the LWT, possibly after the v5 will-delay interval
        (vmq_mqtt5_fsm set_delayed_will; vmq_queue.erl:932-942). The will is
        cancelled if the client reconnects before the delay elapses."""
        delay = will.properties.get("will_delay_interval", 0)
        cap = self.config.max_last_will_delay
        if cap:
            delay = min(delay, cap)
        if session_expiry:
            delay = min(delay, session_expiry)

        def _publish_will() -> None:
            try:
                words = tuple(T.validate_topic("publish", will.topic))
            except T.TopicError:
                return
            props = {
                k: v for k, v in will.properties.items()
                if k in ("payload_format_indicator", "message_expiry_interval",
                         "content_type", "response_topic", "correlation_data",
                         "user_property")
            }
            msg = Msg(topic=words, payload=will.payload, qos=will.qos,
                      retain=will.retain, mountpoint=mountpoint, properties=props)
            expiry = props.get("message_expiry_interval")
            if expiry:
                msg.expires_at = time.monotonic() + expiry
            try:
                self.registry.publish(msg)
            except RuntimeError:
                pass

        if delay <= 0:
            _publish_will()
            return

        async def _delayed() -> None:
            await asyncio.sleep(delay)
            self._delayed_wills.pop(sid, None)
            _publish_will()

        self.cancel_delayed_will(sid)
        self._delayed_wills[sid] = asyncio.get_event_loop().create_task(_delayed())

    def cancel_delayed_will(self, sid: SubscriberId) -> None:
        t = self._delayed_wills.pop(sid, None)
        if t is not None:
            t.cancel()

    # ------------------------------------------------------ offline storage

    def store_offline(self, sid: SubscriberId, msg: Msg) -> None:
        try:
            # loop-side synchronous seam: injected latency models a slow
            # disk blocking the loop exactly like the real store would,
            # but capped so a hang drill stays a stall, not an outage.
            # Registered with the stall watchdog for visibility — a
            # synchronous loop-side write cannot be abandoned, but a
            # stall here shows up in watchdog_stalls / `watchdog show`
            # instead of reading as unexplained loop lag.
            with self.watchdog.monitored("store.write", 2.0,
                                         label=f"{sid[0]}/{sid[1]}"):
                faults.inject("store.write", max_delay_s=1.0)
                t0 = time.monotonic()
                self.msg_store.write(sid, msg)
                self.metrics.observe("stage_store_append_ms",
                                     (time.monotonic() - t0) * 1e3)
        except Exception:
            # degraded, not fatal: the in-memory queue still holds the
            # message, so live delivery is unaffected — only the
            # crash-restart durability of THIS message is lost. A failed
            # write must never fail the enqueue (the reference's store
            # is likewise fire-and-forget from the queue's view).
            self.metrics.incr("msg_store_write_errors")
            log.exception("offline store write failed for %s "
                          "(message kept in memory only)", sid)
            return
        self.metrics.incr("msg_store_ops_write")
        if self.msg_store.needs_commit() and not self._store_commit_scheduled:
            # fsync group-commit: the burst's records are flushed; ONE
            # fsync lands at the flush-tick boundary for all of them
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                self._commit_msg_store()  # no loop (tests): sync now
            else:
                self._store_commit_scheduled = True
                loop.call_soon(self._commit_msg_store)

    def _commit_msg_store(self) -> None:
        self._store_commit_scheduled = False
        try:
            coalesced = self.msg_store.commit()
        except Exception:
            self.metrics.incr("msg_store_write_errors")
            log.exception("msg store group commit failed")
            return
        if coalesced:
            self.metrics.incr("msg_store_fsync_coalesced", coalesced)

    def resume_collector(self):
        """Lazy batched-resume collector (storage/resume.py), or None
        when disabled or the store cannot serve off-loop batched reads
        (memory / legacy flat-log stores) — reconnects then recover on
        the synchronous per-session path, unchanged."""
        if (not self.config.get("resume_batched", True)
                or not getattr(self.msg_store, "supports_batched_read",
                               False)):
            return None
        if self._resume_collector is None:
            from ..storage.resume import ResumeCollector

            cfg = self.config
            self._resume_collector = ResumeCollector(
                self.msg_store,
                window_us=cfg.get("resume_window_us", 500),
                max_batch=cfg.get("resume_max_batch", 512),
                host_threshold=cfg.get("resume_host_threshold", 4),
                item_expiry_ms=float(cfg.get("resume_expiry_ms",
                                             30_000)),
                metrics=self.metrics)
            if self.overload is not None:
                # L2 response: resume storms defer behind live publishes
                # exactly like retained replays
                self._resume_collector.defer_gate = \
                    self.overload.defer_replay
        return self._resume_collector

    def recover_offline(self, sid: SubscriberId, queue: SubscriberQueue,
                        may_defer: bool = False,
                        lazy: bool = False) -> None:
        """Rebuild the offline backlog from storage on queue re-creation
        (vmq_queue offline(init_offline_queue), vmq_lvldb_store.erl:396-416).

        ``lazy`` marks boot/remap recovery of a DETACHED persistent
        queue: with a batched-read store the backlog stays parked in
        storage (``queue.offline_in_store``) and loads on first attach
        (through the collector) or at drain — a million parked sessions
        boot without a million read_alls. ``may_defer`` marks the
        reconnect path (a session is attaching right now): the replay
        rides the ResumeCollector — one batched off-loop read per storm
        window instead of one loop-side ``read_all`` per session — with
        the queue parking live publishes until the stored backlog
        lands."""
        if (lazy and self.config.get("resume_batched", True)
                and getattr(self.msg_store, "supports_batched_read",
                            False)):
            queue.offline_in_store = True
            return
        coll = self.resume_collector() if may_defer else None
        if coll is not None:
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                coll = None  # no loop (tests/boot): sync path below
        if coll is None:
            msgs = self.msg_store.read_all(sid)
            if msgs:
                # merge, not extend: on the lazy path the deque may
                # already hold a suffix of the store content (a publish
                # that arrived while parked lands in both)
                queue.merge_recovered(msgs)
                self.metrics.incr("queue_initialized_from_storage")
            return
        queue.begin_resume()
        fut = coll.submit(sid)

        def _done(f: "asyncio.Future") -> None:
            exc = None if f.cancelled() else f.exception()
            if f.cancelled() or exc is not None:
                # batched AND fallback read failed (or the future was
                # cancelled): serve the exact per-session read inline —
                # never leave the queue wedged in the resuming state
                if exc is not None:
                    log.warning("offline resume for %s failed: %s",
                                sid, exc)
                try:
                    msgs = self.msg_store.read_all(sid)
                except Exception:
                    self.metrics.incr("msg_store_read_errors")
                    log.exception("per-session resume fallback read "
                                  "failed for %s", sid)
                    msgs = []
            else:
                msgs = f.result()
            queue.finish_resume(msgs)

        fut.add_done_callback(_done)

    def delete_offline(self, sid: SubscriberId) -> None:
        self.msg_store.delete_all(sid)
        self.metrics.incr("msg_store_ops_delete")

    def offline_delivered(self, sid: SubscriberId, msg: Msg) -> None:
        self.msg_store.delete(sid, msg.msg_ref)

    # ------------------------------------------------- store maintenance

    def _store_engines(self) -> List[Any]:
        """Every compactable engine this broker owns: the msg store's
        (one per bucket instance) plus the cluster spool's journal —
        they share the engine layer, so ONE budgeted driver maintains
        both."""
        engines: List[Any] = []
        ms = self.msg_store
        for st in (getattr(ms, "instances", None) or [ms]):
            eng = getattr(st, "engine", None)
            if eng is not None and hasattr(eng, "compact_step"):
                engines.append(eng)
        spool = getattr(self.cluster, "spool", None) \
            if self.cluster is not None else None
        eng = getattr(spool, "engine", None) if spool is not None else None
        if eng is not None and hasattr(eng, "compact_step"):
            engines.append(eng)
        return engines

    async def store_maintain_once(self, budget: Optional[int] = None) -> int:
        """One budgeted compaction/checkpoint pass over every engine,
        off the event loop on the watchdog's sacrificial executor.
        ``store.compact`` is the drill seam: injected (or real) failures
        feed the store breaker — open, compaction PAUSES and the store
        degrades to append-only (counted) while writes/reads/delivery
        continue untouched; the half-open probe resumes it."""
        from ..robustness.watchdog import StallAbandoned

        if budget is None:
            budget = int(self.config.get("store_compact_budget_bytes",
                                         4 * 1024 * 1024))
        reclaimed = 0
        for eng in self._store_engines():
            if not self.store_breaker.allow():
                self.store_compact_paused += 1
                self.metrics.incr("store_compact_paused")
                break

            def _step(e=eng):
                faults.inject("store.compact", max_delay_s=5.0)
                return e.compact_step(budget)

            label = getattr(eng, "directory", None) \
                or getattr(eng, "path", "") or type(eng).__name__
            try:
                deadline = self._dispatch_deadline_ms() / 1e3
                if deadline > 0:
                    n = await self.watchdog.dispatch_async(
                        "store.compact", _step, deadline, label=label)
                else:
                    n = await asyncio.get_event_loop().run_in_executor(
                        None, _step)
            except StallAbandoned:
                self.store_breaker.record_failure()
                self.store_compact_errors += 1
                self.metrics.incr("store_compact_errors")
                continue
            except Exception:
                opened = self.store_breaker.record_failure()
                self.store_compact_errors += 1
                self.metrics.incr("store_compact_errors")
                if opened:
                    log.warning("store compaction breaker OPEN: the "
                                "store runs append-only until the "
                                "half-open probe succeeds")
                continue
            self.store_breaker.record_success()
            if n:
                self.store_compactions += 1
                self.store_compacted_bytes += int(n)
                self.metrics.incr("store_compactions")
                self.metrics.incr("store_compacted_bytes", int(n))
                reclaimed += int(n)
        # the TTL sweep of expired parked messages rides the same tick,
        # budgeted like compaction and gated by the same breaker (it is
        # store maintenance: a failing engine must not be hammered)
        ms = self.msg_store
        sweep = getattr(ms, "sweep_expired", None)
        if sweep is not None and self.store_breaker.allow():
            sweep_budget = int(self.config.get(
                "store_expire_sweep_budget", 256))
            try:
                n = await asyncio.get_event_loop().run_in_executor(
                    None, sweep, sweep_budget)
            except Exception:
                if self.store_breaker.record_failure():
                    log.warning("store TTL sweep failed; store "
                                "maintenance breaker OPEN")
                self.store_compact_errors += 1
                self.metrics.incr("store_compact_errors")
            else:
                # no record_success here: the compaction steps own the
                # breaker's success/probe accounting — a healthy sweep
                # must not mask an accumulating compaction failure run
                if n:
                    self.metrics.incr("msg_store_expired_swept", n)
        # bucket-probe telemetry: move the bucketed store's counter
        # deltas into $SYS (the store layer holds no metrics handle)
        hits = getattr(ms, "probe_hits", 0)
        misses = getattr(ms, "probe_misses", 0)
        dh, dm = self._probe_drained
        if hits - dh or misses - dm:
            if hits - dh:
                self.metrics.incr("store_bucket_probe_hits", hits - dh)
            if misses - dm:
                self.metrics.incr("store_bucket_probe_misses",
                                  misses - dm)
            self._probe_drained = (hits, misses)
        return reclaimed

    async def _store_maintenance_loop(self) -> None:
        interval = max(0.05, float(self.config.get(
            "store_compact_interval_ms", 1000)) / 1e3)
        while True:
            await asyncio.sleep(interval)
            try:
                await self.store_maintain_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the maintenance tick must never die: the next tick
                # retries (a persistent failure shows in the breaker)
                log.exception("store maintenance tick failed")

    def store_status(self) -> Dict[str, Any]:
        """`vmq-admin store show` / bench introspection."""
        engines = []
        for eng in self._store_engines():
            st = {"kind": getattr(eng, "kind", "?")}
            try:
                st.update(eng.stats())
            except Exception:
                pass
            engines.append(st)
        out: Dict[str, Any] = {
            "engine_kind": getattr(self.msg_store, "engine_kind",
                                   "memory"),
            "engines": engines,
            "breaker": self.store_breaker.status(),
            "compactions": self.store_compactions,
            "compacted_bytes": self.store_compacted_bytes,
            "compact_paused": self.store_compact_paused,
            "compact_errors": self.store_compact_errors,
        }
        if self._resume_collector is not None:
            out["resume"] = self._resume_collector.stats()
        if hasattr(self.msg_store, "stats"):
            out["msg_store"] = self.msg_store.stats()
        return out

    # ------------------------------------------------------------ lifecycle

    def batch_collector(self):
        """Lazy publish batch collector for the TPU reg view (µs-scale
        coalescing, SURVEY.md §5.8 host↔TPU batching layer)."""
        if getattr(self, "_collector", None) is None:
            from ..models.tpu_matcher import BatchCollector

            self._collector = BatchCollector(
                self.registry.reg_view("tpu"),
                window_us=self.config.tpu_batch_window_us,
                host_threshold=self.config.tpu_host_batch_threshold,
                lock_busy_shed_ms=self.config.tpu_lock_busy_shed_ms,
                super_batch_k=self.config.tpu_super_batch_k,
                latency_budget_ms=self.config.get(
                    "overload_dispatch_budget_ms", 50.0),
                watchdog=self.watchdog,
                dispatch_deadline_ms=self._dispatch_deadline_ms(),
                item_expiry_ms=self._collector_expiry_ms(),
                filter_engine=self.filter_engine,
            )
        return self._collector

    def _deliver_aggregate(self, mountpoint: str, sub_key, opts,
                           topic_words, payload: bytes) -> None:
        """A closed aggregation window emits ONE synthesized PUBLISH to
        its subscriber (the telemetry-downsampling delivery): topic =
        the concrete aggregated topic, payload = the JSON aggregate.
        Runs on the event loop (the engine marshals emissions here);
        the subscriber's queue applies the normal delivery transform."""
        sid = sub_key[2] if (isinstance(sub_key, tuple) and len(sub_key) == 3
                             and sub_key[0] == "$g") else sub_key
        queue = self.registry.queues.get(sid)
        if queue is None:
            return  # subscriber gone between fold and close: drop
        msg = Msg(topic=tuple(topic_words), payload=payload,
                  qos=getattr(opts, "qos", 0), mountpoint=mountpoint)
        self.registry._enqueue_to(sid, msg, opts)
        self.metrics.incr("aggregate_publishes_delivered")

    def _dispatch_deadline_ms(self) -> float:
        """Device-dispatch abandon deadline (0 when the watchdog is
        off: the pre-watchdog unbounded wait)."""
        if not self.config.get("watchdog_enabled", True):
            return 0.0
        return float(self.config.get("watchdog_dispatch_deadline_ms",
                                     5000))

    def _collector_expiry_ms(self) -> float:
        """Queued-item expiry: derived from the overload dispatch
        budget so the bounded-tail guarantee tracks the same knob the
        governor judges dispatch latency against."""
        if not self.config.get("watchdog_enabled", True):
            return 0.0
        budgets = float(self.config.get(
            "watchdog_collector_expiry_budgets", 4))
        return budgets * float(self.config.get(
            "overload_dispatch_budget_ms", 50.0))

    def retained_engine(self):
        """Lazy per-mountpoint device retained index (the reverse-match
        engine, vernemq_tpu/retained/). Shares the tpu_breaker_* knob
        family with the publish matcher's breaker."""
        if self._retained_engine is None:
            from ..retained.index import RetainedEngine

            cfg = self.config
            self._retained_engine = RetainedEngine(
                self.retain,
                initial_capacity=cfg.get("tpu_retained_initial_capacity",
                                         2048),
                max_fanout=cfg.get("tpu_retained_max_fanout", 256),
                breaker_enabled=cfg.get("tpu_breaker_enabled", True),
                breaker_failure_threshold=cfg.get(
                    "tpu_breaker_failure_threshold", 3),
                breaker_backoff_initial=cfg.get(
                    "tpu_breaker_backoff_initial_ms", 200) / 1e3,
                breaker_backoff_max=cfg.get(
                    "tpu_breaker_backoff_max_ms", 10_000) / 1e3,
                watchdog=(self.watchdog
                          if cfg.get("watchdog_enabled", True) else None),
                rebuild_deadline_s=cfg.get(
                    "watchdog_rebuild_deadline_s", 120.0),
            )
        return self._retained_engine

    def retained_collector(self):
        """Retained-replay batch collector, or None when the device
        retained path is off (config) or the accelerator never came up —
        the subscribe path then serves the exact host walk directly."""
        cfg = self.config
        if (cfg.default_reg_view != "tpu"
                or not cfg.get("tpu_retained_enabled", True)):
            return None
        if not self.registry.batched_view_active():
            return None  # accelerator down/cold: host walk serves replays
        if self._retained_collector is None:
            from ..retained.collector import RetainedBatchCollector

            self._retained_collector = RetainedBatchCollector(
                self.retained_engine(), self.retain,
                window_us=cfg.get("tpu_retained_window_us", 500),
                max_batch=cfg.get("tpu_retained_max_batch", 1024),
                host_threshold=cfg.get("tpu_retained_host_threshold", 4),
                latency_budget_ms=cfg.get(
                    "overload_dispatch_budget_ms", 50.0),
                watchdog=self.watchdog,
                dispatch_deadline_ms=self._dispatch_deadline_ms(),
                item_expiry_ms=self._collector_expiry_ms(),
            )
            if self.overload is not None:
                # L2 response: replay storms defer behind live publishes
                self._retained_collector.defer_gate = \
                    self.overload.defer_replay
        return self._retained_collector

    def _resolve_base_dirs(self) -> None:
        """Honor the setup.data_dir / setup.log_dir release knobs
        (vmq_server.schema setup.* tree): relative storage paths resolve
        under data_dir, a bare log filename under log_dir."""
        import os as _os

        data_dir = self.config.get("data_dir", "")
        if data_dir:
            for key in ("message_store_dir", "metadata_dir",
                        "cluster_spool_dir"):
                path = self.config.get(key, "")
                if path and not _os.path.isabs(path):
                    self.config.set(
                        key,
                        _os.path.normpath(_os.path.join(data_dir, path)))
        log_dir = self.config.get("log_dir", "")
        log_file = self.config.get("log_file", "")
        if log_dir and log_file and not _os.path.isabs(log_file):
            self.config.set("log_file", _os.path.join(log_dir, log_file))

    async def _publish_worker_stats(self, interval: float = 0.25) -> None:
        """Heartbeat this worker's health row into the shared stats
        block (pid, live sessions, admitted publishes). The overload
        level/pressure pair is written by the governor's own tick and
        the loop-lag samples by sysmon — every field has exactly one
        writer, so the block needs no locking."""
        from ..observability import events as _events
        from ..observability import histogram as _hist

        ws = self.worker_stats
        idx = self.worker_index
        while True:
            try:
                ws.write_health(
                    idx, pid=os.getpid(), sessions=len(self.sessions),
                    admitted=self.metrics.value("mqtt_publish_received"))
                # publish this worker's stage histograms into its slot:
                # the scrape-point aggregation reads every live slot so
                # ANY worker's /metrics (and the parent's bench read)
                # shows the node-level merged families
                ws.write_hist(idx, _hist.pack_all())
                ws.write_events(idx, _events.journal().pack())
            except Exception:
                log.exception("worker stats heartbeat failed")
            await asyncio.sleep(interval)

    async def start_systree(self) -> None:
        """$SYS tree publisher (vmq_systree.erl): periodic internal publish
        of all metrics to $SYS/<node>/... topics. Mountpoint, QoS and
        retain flag follow the systree_* knobs (vmq_server.schema
        systree_mountpoint/qos/retain)."""
        interval = self.config.systree_interval
        if interval <= 0:
            return  # 0 = disabled (reference schema systree_interval)
        mountpoint = self.config.get("systree_mountpoint", "")
        qos = min(max(int(self.config.get("systree_qos", 0)), 0), 2)
        retain = bool(self.config.get("systree_retain", False))
        while True:
            await asyncio.sleep(interval)
            for name, value in self.metrics.all_metrics().items():
                topic = ("$SYS", self.node_name, *name.split("_"))
                msg = Msg(topic=topic, payload=str(value).encode(),
                          qos=qos, retain=retain, mountpoint=mountpoint)
                try:
                    self.registry.publish(msg)
                except RuntimeError:
                    pass

    # ---------------------------------------------------- session tracing

    def trace_frame(self, direction: str, mountpoint: str,
                    client_id: Optional[str], frame: Any,
                    session_start: bool = False) -> None:
        """Frame tap from the session layer; no-op unless a tracer is
        active and the client matches (vmq_tracer role)."""
        t = self.tracer
        if t is None or not t.matches(mountpoint, client_id):
            return
        if session_start:
            t.session_event(f'New session for client "{client_id}"')
        t.trace(direction, client_id, frame)

    def start_trace(self, client_id: str, mountpoint: str = "",
                    **opts) -> Any:
        """vmq-admin trace client client-id=X; single tracer at a time
        (vmq_tracer_cli: "another trace is already running")."""
        if self.tracer is not None:
            raise RuntimeError("another trace is already running")
        from ..admin.tracer import Tracer

        opts.setdefault("metrics", self.metrics)
        self.tracer = Tracer(client_id, mountpoint, **opts)
        n = sum(1 for sid in self.sessions
                if sid == (mountpoint, client_id))
        self.tracer.session_event(
            f'Starting trace for {n} existing sessions for client "{client_id}"')
        return self.tracer

    def stop_trace(self) -> None:
        self.tracer = None

    def _setup_logging(self) -> None:
        """Attach the configured log sinks (console is the host app's
        concern; file + syslog mirror the reference's lager sinks)."""
        import logging as _logging

        if not self.config.log_file and not self.config.log_syslog:
            return  # no sink knobs set: leave the host app's config alone
        root = _logging.getLogger("vernemq_tpu")
        level = getattr(_logging, str(self.config.log_level).upper(),
                        _logging.INFO)
        root.setLevel(level)
        fmt = _logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s")
        if self.config.log_file:
            fh = _logging.FileHandler(self.config.log_file)
            fh.setFormatter(fmt)
            root.addHandler(fh)
            self._log_handlers.append(fh)
        if self.config.log_syslog:
            import logging.handlers as _lh

            try:
                sh = _lh.SysLogHandler(address=self.config.log_syslog_address)
                sh.setFormatter(fmt)
                root.addHandler(sh)
                self._log_handlers.append(sh)
            except OSError as e:
                log.warning("syslog sink unavailable: %s", e)

    async def start(self) -> None:
        self._log_handlers: List[Any] = []
        self._setup_logging()
        # observability master switch: off reduces every histogram/
        # profiler seam to one module-global boolean test (the bench
        # overhead guard measures exactly this difference). The flag is
        # process-global like the registries it gates.
        from ..observability import histogram as _hist
        from ..observability import profiler as _profiler

        _hist.set_enabled(
            bool(self.config.get("observability_enabled", True)))
        _profiler().set_capacity(
            int(self.config.get("profiler_capacity", 2048)))
        from ..observability import events as _events

        _events.journal().set_capacity(
            int(self.config.get("events_capacity", 2048)))
        # warm-load from persisted metadata: routing state, offline queues,
        # retain cache (boot order of vmq_server_sup + vmq_reg_trie /
        # vmq_retain_srv warm-loads)
        self.registry.bootstrap()
        if self.filter_engine is not None:
            # time-window closes + aggregate emissions marshal onto the
            # loop from the dispatch threads
            self.filter_engine.arm(asyncio.get_event_loop())
        for key, value in self.metadata.fold("retain"):
            self.retain.apply_remote(key[0], tuple(key[1:]),
                                     self._retain_term(value))
        # mesh slice map: claim this node's slices (deterministic
        # round-robin over the membership; a single node claims all) and
        # re-claim whenever membership changes — the map gossips through
        # the metadata plane like the netsplit CAPs, and newly-owned
        # slices replay their rows exactly once (_on_mesh_adopt)
        if self.mesh_map is not None:
            def _mesh_reclaim(*_a) -> None:
                try:
                    # a built tpu view that came up WITHOUT its mesh
                    # (tpu_mesh asked for more devices than exist — the
                    # documented loud degrade to single-chip) must not
                    # keep advertising slice ownership it cannot serve
                    view = self.registry.reg_views.get("tpu")
                    if view is not None and (
                            getattr(view, "mesh_status", None) is None
                            or view.mesh_status() is None):
                        log.warning(
                            "mesh slice claim skipped: the tpu view is "
                            "serving single-chip (tpu_mesh degraded or "
                            "mesh-native disabled)")
                        return
                    members = (self.cluster.members()
                               if self.cluster is not None else None)
                    self.mesh_map.claim_local(members)
                except Exception:
                    log.exception("mesh slice claim failed")

            _mesh_reclaim()
            self.metadata.subscribe("members", _mesh_reclaim)
        # boot-time fault plan (robustness harness): deterministic
        # injected faults per the fault_injection config — empty list =
        # nothing installed, zero overhead
        plan_spec = self.config.get("fault_injection", [])
        if plan_spec:
            self._boot_fault_plan = faults.install(
                faults.FaultPlan.from_config(
                    plan_spec,
                    seed=self.config.get("fault_injection_seed", 0)))
            log.warning("fault-injection plan ACTIVE at boot: %d rules, "
                        "seed %s", len(plan_spec),
                        self.config.get("fault_injection_seed", 0))
        # crash-restart supervision (vmq_server_sup one_for_one analog)
        from .supervisor import Supervisor

        self.supervisor = Supervisor(
            self,
            max_restarts=self.config.get("supervisor_max_restarts", 20),
            restart_window=self.config.get("supervisor_restart_window",
                                           60.0))
        self.supervisor.watch_listeners()
        if self.config.systree_enabled:
            self.supervisor.spawn("systree", self.start_systree)
        if self.config.http_enabled:
            from ..admin.http import HttpServer

            self.http = HttpServer(self, self.config.http_host,
                                   self.config.http_port,
                                   tuple(self.config.http_modules))
            await self.http.start()
        if self.config.graphite_enabled:
            from ..admin.graphite import GraphiteReporter

            self.graphite = GraphiteReporter(self)
            self.graphite.start()
        if self.config.get("bridges"):
            self.plugins.enable("vmq_bridge")
        # conf-file plugins (plugins.<name> = on) and listeners
        # (listener.<kind>.<name> = ip:port) — the boot-time half of the
        # vernemq.conf layer (broker/conf.py)
        for p in self.config.get("plugins", []):
            self.plugins.enable(p["name"], **p.get("opts", {}))
        conf_listeners = self.config.get("listeners", [])
        if conf_listeners:
            if self.listeners is None:
                from .listeners import ListenerManager

                ListenerManager(self)
            for ln in conf_listeners:
                await self.listeners.start_listener(
                    ln["kind"], ln.get("addr", "127.0.0.1"),
                    ln.get("port", 0), ln.get("opts"))
        # stall watchdog: monitor thread scanning the monitored-op
        # registry for overdue waits (robustness/watchdog.py). Started
        # before the governor/sysmon so a wedge during boot warm-up is
        # already observable.
        if self.config.get("watchdog_enabled", True):
            self.watchdog.tick_s = self.config.get(
                "watchdog_tick_ms", 100) / 1e3
            self.watchdog.start()
        # budgeted store maintenance: segment compaction + checkpoints
        # for every engine (msg store buckets + cluster spool journal)
        # run OFF the loop on the sacrificial executor, at most
        # store_compact_budget_bytes copied per engine per tick; the
        # store breaker pauses it (append-only degraded mode) on
        # injected or real failures without touching delivery
        if float(self.config.get("store_compact_interval_ms", 1000)) > 0:
            self._bg_tasks.append(asyncio.get_event_loop().create_task(
                self._store_maintenance_loop()))
        # multi-process front end: attach the shared worker stats slot
        # and, when the parent configured a match service, mount the
        # ring-backed reg view so folds route to the service process
        # (broker/match_service.py). Both are worker-only — the classic
        # boot leaves the config keys empty and changes nothing.
        stats_name = str(self.config.get("worker_stats_block", "") or "")
        if stats_name:
            from ..parallel.shm_ring import WorkerStatsBlock

            try:
                self.worker_stats = WorkerStatsBlock.attach(stats_name)
                # the parent's workers_total must agree with the slot
                # count baked into the segment header: a mismatch means
                # this worker attached a STALE block from a previous
                # group generation (or a torn rolling restart) — peer
                # pressure fusion and `workers show` would read slots
                # that belong to nobody
                expected = int(self.config.get("workers_total", 1) or 0)
                if expected and expected != self.worker_stats.n_workers:
                    log.warning(
                        "worker stats block %r has %d slots but "
                        "workers_total=%d — parent and worker config "
                        "generations disagree (stale segment?)",
                        stats_name, self.worker_stats.n_workers,
                        expected)
                # scrape-point histogram aggregation: merge the OTHER
                # live workers' slot blocks into this worker's scrape
                # (our own observations come from the live in-process
                # registry, which is fresher than our own slot)
                self.metrics.histogram_extra = self._peer_histograms
            except Exception:
                log.exception("worker stats block %r unavailable; "
                              "running without fused worker pressure",
                              stats_name)
        req_ring = str(self.config.get("match_service_req_ring", "") or "")
        if req_ring and stats_name:
            from .match_service import MatchServiceClient, ShmMatchView

            try:
                client = MatchServiceClient(
                    req_ring,
                    str(self.config.get("match_service_resp_ring", "")),
                    stats_name, self.worker_index, self.node_name,
                    timeout_ms=float(self.config.get(
                        "match_service_timeout_ms", 2000)))
                self.match_client = client
                # pre-mounting "tpu" short-circuits the accelerator
                # probe: the worker never touches a device — the
                # service owns the mirror; the worker's trie stays the
                # degraded-mode oracle
                self.registry.reg_views["tpu"] = ShmMatchView(
                    self.registry, client)
                client.start(self.registry)
            except Exception:
                log.exception("match-service rings unavailable; this "
                              "worker matches on its local trie")
        # materialize the reg views listed in the reg_views knob
        # (vmq_server.schema reg_views: views started at BOOT, not on
        # first default_reg_view routing) — an operator listing tpu with
        # default_reg_view=trie wants the device table building now so
        # a later `config set default_reg_view tpu` flips onto a warm
        # view; the worker-mode ShmMatchView mount above stays
        # authoritative (already-present names are skipped)
        from .schema import REG_VIEW_ALIASES

        valid_views = sorted(set(REG_VIEW_ALIASES.values()))
        for view_name in self.config.get("reg_views", ["trie"]):
            if view_name in self.registry.reg_views:
                continue
            if view_name not in valid_views:
                log.error("reg_views names unknown view %r (valid: %s)",
                          view_name, ", ".join(valid_views))
                continue
            try:
                self.registry.reg_view(view_name)
            except Exception:
                # pre-building is an optimization, never a boot gate: a
                # failing device-view build logs and stays lazy (the
                # accel probe/recovery machinery retries it), routing
                # serves on the default view either way
                log.exception("reg_views: building view %r failed at "
                              "boot; it stays lazy", view_name)
        # adaptive overload governor BEFORE sysmon so the lag sampler can
        # feed it from its very first sample (robustness/overload.py)
        from ..robustness.overload import OverloadGovernor

        cfg = self.config
        self.overload = OverloadGovernor(
            self,
            mode=cfg.get("overload_mode", "governor"),
            tick_s=cfg.get("overload_tick_ms", 250) / 1e3,
            hold_s=cfg.get("overload_hold_s", 5.0),
            exit_ratio=cfg.get("overload_exit_ratio", 0.5),
            l1_enter=cfg.get("overload_l1_enter", 0.25),
            l2_enter=cfg.get("overload_l2_enter", 0.5),
            l3_enter=cfg.get("overload_l3_enter", 0.8),
            l1_throttle_ms=cfg.get("overload_l1_throttle_ms", 100),
            l2_client_rate=cfg.get("overload_l2_client_rate", 50),
            l2_burst=cfg.get("overload_l2_burst", 100),
            l3_disconnect_top=cfg.get("overload_l3_disconnect_top", 5))
        self.overload.start()
        if self.worker_stats is not None:
            # fuse per-worker governors into one cluster-style level:
            # each tick writes THIS worker's local pressure into its
            # slot and reads the peers' as the "workers" signal
            self.overload.attach_worker_stats(self.worker_stats,
                                              self.worker_index)
            self.supervisor.spawn("worker-stats",
                                  self._publish_worker_stats)
        if self.config.get("sysmon_enabled", True):
            from .sysmon import Sysmon

            self.sysmon = Sysmon(
                self,
                lag_threshold=self.config.get("sysmon_lag_threshold", 0.25),
                memory_high_watermark=self.config.get(
                    "sysmon_memory_high_watermark", 0),
                lag_exit_ratio=self.config.get("sysmon_lag_exit_ratio",
                                               0.5))
            self.sysmon.start()
        from .sysmon import CrlRefresher

        self.crl_refresher = CrlRefresher(
            self, interval=self.config.get("crl_refresh_interval", 60.0))
        self.crl_refresher.start()
        # canary SLO probe: a loopback subscriber + a periodic synthetic
        # publish through the FULL path feeding e2e_canary_ms — the
        # continuous black-box end-to-end signal. Supervised like the
        # systree reporter; zero footprint unless enabled.
        if (bool(self.config.get("canary_enabled", False))
                and bool(self.config.get("observability_enabled", True))):
            from ..observability.canary import CanaryProbe

            self.canary = CanaryProbe(
                self,
                interval_ms=float(self.config.get("canary_interval_ms",
                                                  1000)),
                slo_ms=float(self.config.get("canary_slo_ms", 250.0)))
            self.supervisor.spawn("canary", self.canary.run)
        # hot-upgrade baseline LAST, after every boot-time lazy import,
        # so `vmq-admin updo diff` is relative to what this boot loaded
        # (vmq_updo.erl:60-71 diffs loaded vsn vs on-disk beam); modules
        # imported even later are adopted on first diff() sight
        from . import updo

        updo.baseline()

    async def stop(self) -> None:
        for t in self._bg_tasks:
            t.cancel()
        for t in self._delayed_wills.values():
            t.cancel()
        self._delayed_wills.clear()
        # sessions first so lifecycle hooks (on_client_offline/gone) still
        # reach enabled plugins; then plugins (a bridge keeps an outbound
        # client reconnecting); listeners last — Server.wait_closed blocks
        # until every connection handler (incl. bridge links) has returned
        if getattr(self, "supervisor", None) is not None:
            self.supervisor.stop()
        import logging as _logging

        for h in getattr(self, "_log_handlers", []):
            _logging.getLogger("vernemq_tpu").removeHandler(h)
            h.close()
        if self.sysmon is not None:
            self.sysmon.stop()
        if self.overload is not None:
            self.overload.stop()
        if self.crl_refresher is not None:
            self.crl_refresher.stop()
        for s in list(self.sessions.values()):
            await s.close("broker_shutdown", send_will=False)
        await self.plugins.stop_all()
        if self.cluster is not None:
            # the inter-node channel goes down after sessions/plugins
            # (migration + lifecycle hooks may still need it) and before
            # listeners; idempotent when the cluster was started as a
            # `vmq` listener (stop_all covers that handle too)
            await self.cluster.stop()
        if self.listeners is not None:
            await self.listeners.stop_all()
        for server in self._servers:
            server.close()
        # wind down the tpu view's background warm threads (they hold no
        # broker state, but must not keep compiling into a dead matcher)
        tpu_view = self.registry.reg_views.get("tpu")
        if tpu_view is not None and hasattr(tpu_view, "close"):
            tpu_view.close()
        if self._retained_collector is not None:
            # settle pending replay futures (host walk) and disarm the
            # flush timer BEFORE closing the engine it dispatches into
            self._retained_collector.close()
        if self._retained_engine is not None:
            self._retained_engine.close()
        if self.filter_engine is not None:
            self.filter_engine.close()
        # the fault registry is process-global: a plan THIS broker
        # installed at boot must not keep injecting into other broker
        # instances in the process (multi-node tests, embedding) — but
        # leave a plan installed live via the admin surface alone
        if (getattr(self, "_boot_fault_plan", None) is not None
                and faults.active() is self._boot_fault_plan):
            faults.clear()
        if self.worker_stats is not None:
            # the match client's own attachment went down with the tpu
            # view close above; this is the broker's direct handle
            self.worker_stats.close()
            self.worker_stats = None
        # after the collectors/views that dispatch through it are down;
        # wedged sacrificial threads are daemons and die with the process
        self.watchdog.stop()
        if self._resume_collector is not None:
            # settle pending resume futures (per-session reads) BEFORE
            # closing the store they read from
            self._resume_collector.close()
        self.msg_store.close()
        self.metadata.close()
