"""Offline message store.

Mirrors the reference message-store seam: the store is itself a plugin
(``msg_store_write/read/delete/find`` hooks, used from the queue at
``vmq_queue.erl:420,797,946,970``), with the LevelDB implementation
(``vmq_lvldb_store.erl``) keeping three key families — message payload by
ref, per-subscriber ref entries, and a per-subscriber index for recovery
scans (``vmq_lvldb_store.erl:339-416``) — plus payload refcounting across
subscribers.

Round 1 ships the in-memory store and a durable append-log file store with
the same refcounted layout; the C++/RocksDB engine lands behind this same
interface in a later round.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..broker.message import Msg, SubscriberId


class MsgStore:
    """Interface (msg_store_* plugin hooks)."""

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        raise NotImplementedError

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        raise NotImplementedError

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        raise NotImplementedError

    def delete_all(self, sid: SubscriberId) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryMsgStore(MsgStore):
    def __init__(self) -> None:
        # payload table: ref -> (msg, refcount)  (dedup across subscribers,
        # vmq_lvldb_store.erl:347,455-472)
        self._msgs: Dict[bytes, Tuple[Msg, int]] = {}
        # index: sid -> [ref] in arrival order (the sext-ordered idx family)
        self._idx: Dict[SubscriberId, List[bytes]] = {}

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        entry = self._msgs.get(msg.msg_ref)
        if entry is None:
            self._msgs[msg.msg_ref] = (msg, 1)
        else:
            self._msgs[msg.msg_ref] = (entry[0], entry[1] + 1)
        self._idx.setdefault(sid, []).append(msg.msg_ref)

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        return [self._msgs[r][0] for r in self._idx.get(sid, []) if r in self._msgs]

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        idx = self._idx.get(sid)
        if idx and msg_ref in idx:
            idx.remove(msg_ref)
            self._deref(msg_ref)

    def delete_all(self, sid: SubscriberId) -> None:
        for ref in self._idx.pop(sid, []):
            self._deref(ref)

    def _deref(self, ref: bytes) -> None:
        entry = self._msgs.get(ref)
        if entry is None:
            return
        if entry[1] <= 1:
            del self._msgs[ref]
        else:
            self._msgs[ref] = (entry[0], entry[1] - 1)

    def stats(self) -> Dict[str, int]:
        return {"stored_messages": len(self._msgs),
                "stored_refs": sum(len(v) for v in self._idx.values())}


class FileMsgStore(MemoryMsgStore):
    """Append-log-backed store: every op is journaled, state rebuilt on open
    (the recovery scan role of vmq_lvldb_store.erl:396-453). Simple but
    durable; swapped for the C++ engine later."""

    def __init__(self, directory: str):
        super().__init__()
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "msgstore.log")
        self._recover()
        self._fh = open(self._path, "ab")

    def _recover(self) -> None:
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break  # torn tail write
                op = rec["op"]
                sid = (rec["mp"], rec["cid"])
                if op == "w":
                    msg = Msg(
                        topic=tuple(rec["topic"]),
                        payload=bytes.fromhex(rec["payload"]),
                        qos=rec["qos"],
                        retain=rec.get("retain", False),
                        mountpoint=rec["mp"],
                        msg_ref=rec["ref"].encode(),
                        properties=rec.get("props", {}),
                    )
                    super().write(sid, msg)
                elif op == "d":
                    super().delete(sid, rec["ref"].encode())
                elif op == "da":
                    super().delete_all(sid)

    def _log(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec).encode() + b"\n")
        self._fh.flush()

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        super().write(sid, msg)
        self._log({
            "op": "w", "mp": sid[0], "cid": sid[1], "ref": msg.msg_ref.decode(),
            "topic": list(msg.topic), "payload": msg.payload.hex(),
            "qos": msg.qos, "retain": msg.retain,
            "props": {k: v for k, v in msg.properties.items()
                      if isinstance(v, (int, str, float))},
        })

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        super().delete(sid, msg_ref)
        self._log({"op": "d", "mp": sid[0], "cid": sid[1], "ref": msg_ref.decode()})

    def delete_all(self, sid: SubscriberId) -> None:
        super().delete_all(sid)
        self._log({"op": "da", "mp": sid[0], "cid": sid[1]})

    def close(self) -> None:
        self._fh.close()
