"""Offline message store.

Mirrors the reference message-store seam: the store is itself a plugin
(``msg_store_write/read/delete/find`` hooks, used from the queue at
``vmq_queue.erl:420,797,946,970``), with the LevelDB implementation
(``vmq_lvldb_store.erl``) keeping three key families — message payload by
ref, per-subscriber ref entries, and a per-subscriber index for recovery
scans (``vmq_lvldb_store.erl:339-416``) — plus payload refcounting across
subscribers.

The durable stores now share ONE engine layer (``storage/segment.py``,
also backing the cluster spool): :class:`EngineMsgStore` implements the
3-key-family layout over any engine, :class:`NativeMsgStore` mounts it
on the C++ kvstore, :class:`SegmentMsgStore` on the pure-Python
segment-log twin (sealed segments, checkpointed recovery, budgeted
compaction driven by the broker off the event loop). The legacy
:class:`FileMsgStore` flat JSON log is kept for on-disk compatibility
(an existing ``msgstore.log`` is honoured at boot) but new ``file``
stores open the segment engine.

When ``msg_store_fsync`` is on, stores **group-commit**: a write burst
marks the store dirty and the broker issues ONE fsync at the flush-tick
boundary (``commit()``), counting the coalesced syncs — per-record
fsync made every offline enqueue a disk round trip on the event loop.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..broker.message import Msg, SubscriberId

log = logging.getLogger("vernemq_tpu.storage")


class MsgStore:
    """Interface (msg_store_* plugin hooks)."""

    #: True when read_many may run on an executor thread concurrently
    #: with loop-side writes (the store locks internally) — the gate
    #: the batched ResumeCollector checks before going off-loop
    supports_batched_read = False

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        raise NotImplementedError

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        raise NotImplementedError

    def read_many(self, sids: List[SubscriberId]
                  ) -> Dict[SubscriberId, List[Msg]]:
        """Batched recovery read for a reconnect storm: one call
        resolves every subscriber's offline backlog (the reference's
        msg_store_find per queue, amortized)."""
        return {sid: self.read_all(sid) for sid in sids}

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        raise NotImplementedError

    def delete_all(self, sid: SubscriberId) -> None:
        raise NotImplementedError

    def needs_commit(self) -> bool:
        """True when fsync work is parked for the group commit."""
        return False

    def commit(self) -> int:
        """Flush the parked fsync (one sync per write burst); returns
        the number of COALESCED syncs (writes beyond the first since
        the last commit — what per-record fsync would have cost
        extra)."""
        return 0

    def close(self) -> None:
        pass


class MemoryMsgStore(MsgStore):
    def __init__(self) -> None:
        # payload table: ref -> (msg, refcount)  (dedup across subscribers,
        # vmq_lvldb_store.erl:347,455-472)
        self._msgs: Dict[bytes, Tuple[Msg, int]] = {}
        # index: sid -> [ref] in arrival order (the sext-ordered idx family)
        self._idx: Dict[SubscriberId, List[bytes]] = {}

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        entry = self._msgs.get(msg.msg_ref)
        if entry is None:
            self._msgs[msg.msg_ref] = (msg, 1)
        else:
            self._msgs[msg.msg_ref] = (entry[0], entry[1] + 1)
        self._idx.setdefault(sid, []).append(msg.msg_ref)

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        return [self._msgs[r][0] for r in self._idx.get(sid, []) if r in self._msgs]

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        idx = self._idx.get(sid)
        if idx and msg_ref in idx:
            idx.remove(msg_ref)
            self._deref(msg_ref)

    def delete_all(self, sid: SubscriberId) -> None:
        for ref in self._idx.pop(sid, []):
            self._deref(ref)

    def _deref(self, ref: bytes) -> None:
        entry = self._msgs.get(ref)
        if entry is None:
            return
        if entry[1] <= 1:
            del self._msgs[ref]
        else:
            self._msgs[ref] = (entry[0], entry[1] - 1)

    def stats(self) -> Dict[str, int]:
        return {"stored_messages": len(self._msgs),
                "stored_refs": sum(len(v) for v in self._idx.values())}


class SeqCounter:
    """Monotonic enqueue-order counter, shareable across store instances so
    a bucketed store's per-subscriber recovery merge preserves global
    arrival order."""

    __slots__ = ("_next", "_lock")

    def __init__(self) -> None:
        self._next = 1
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            n = self._next
            self._next += 1
            return n

    def bump(self, seen: int) -> None:
        with self._lock:
            if seen >= self._next:
                self._next = seen + 1


class EngineMsgStore(MsgStore):
    """The reference's 3-key-family layout (``vmq_lvldb_store.erl:
    339-416``) over any ``storage/segment.py`` engine:

    - ``m\\x00<ref>``                       → encoded message (payload family)
    - ``r\\x00<sid><ref>``                  → b"" (per-subscriber ref entry)
    - ``i\\x00<sid><seq:8>``                → ref (ordered recovery index)

    Payloads are deduplicated across subscribers via an in-memory refcount
    rebuilt from the ``i`` family on open; unreferenced payloads are
    garbage-collected by a startup scan (``vmq_lvldb_store.erl:418-453``).

    Thread-safety: one lock per store instance around the host-side maps
    (the engines serialize their own file/C-side ops) — the analog of the
    reference's one gen_server per bucket serializing that bucket's ops.
    Reads (``read_all_seq``/``read_many``) may therefore run on executor
    threads concurrently with loop-side writes.
    """

    supports_batched_read = True

    def __init__(self, engine, seq: Optional[SeqCounter] = None,
                 fsync: bool = False, group_commit: bool = True):
        import time as _time

        from ..cluster.codec import decode, encode
        from ..cluster.node import msg_to_term, term_to_msg

        # wrap the wire term with the wall-clock store time: the codec's
        # "remaining seconds" expiry is rebased at decode, so time spent in
        # the store counts against MQTT5 message_expiry_interval
        def _enc(m):
            return encode([msg_to_term(m), _time.time()])

        def _dec(b):
            term, stored_at = decode(b)
            if term.get("exp") is not None:
                elapsed = max(0.0, _time.time() - stored_at)
                term["exp"] = max(0.0, term["exp"] - elapsed)
            return term_to_msg(term)

        def _peek_deadline(b):
            # wall-clock expiry deadline WITHOUT building a Msg: the
            # TTL sweep classifies recovered records with this
            term, stored_at = decode(b)
            exp = term.get("exp")
            return None if exp is None else stored_at + exp

        self._enc = _enc
        self._dec = _dec
        self._peek_deadline = _peek_deadline
        self.engine = engine
        self._kv = engine
        # refcount + sid→ref→[seq] maps, rebuilt from the r/i families
        self._refcount: Dict[bytes, int] = {}
        self._seqs: Dict[SubscriberId, Dict[bytes, List[int]]] = {}
        self._seq = seq or SeqCounter()
        self._fsync = fsync
        self._group_commit = group_commit
        self._sync_pending = 0
        self._lock = threading.Lock()
        # TTL sweep state: ref -> wall-clock expiry deadline (only
        # expiring messages carry an entry); refs recovered from disk
        # have no in-memory deadline yet and queue for budgeted
        # classification on the maintenance tick
        self._exp: Dict[bytes, float] = {}
        self._exp_scan: List[bytes] = []
        self._recover()
        self._exp_scan = list(self._refcount)

    @property
    def engine_kind(self) -> str:
        return getattr(self.engine, "kind", "native")

    @staticmethod
    def _sid_key(sid: SubscriberId) -> bytes:
        mp = sid[0].encode()
        cid = sid[1].encode()
        return (len(mp).to_bytes(2, "big") + mp
                + len(cid).to_bytes(2, "big") + cid)

    @staticmethod
    def _parse_sid(b: bytes) -> Tuple[SubscriberId, bytes]:
        n = int.from_bytes(b[:2], "big")
        mp = b[2:2 + n].decode()
        rest = b[2 + n:]
        n2 = int.from_bytes(rest[:2], "big")
        cid = rest[2:2 + n2].decode()
        return (mp, cid), rest[2 + n2:]

    def _recover(self) -> None:
        # refcounts must be rebuilt one-per-enqueue (per i-entry), matching
        # the runtime write path — counting r-keys (one per sid+ref) would
        # undercount a message enqueued twice to the same subscriber and a
        # later delete would free the payload while a copy is still owed
        live_refs: Dict[bytes, int] = {}
        for key, ref in self._kv.scan(b"i\x00"):
            sid, seq_b = self._parse_sid(key[2:])
            seq = int.from_bytes(seq_b, "big")
            self._seqs.setdefault(sid, {}).setdefault(ref, []).append(seq)
            self._seq.bump(seq)
            live_refs[ref] = live_refs.get(ref, 0) + 1
        self._refcount = live_refs
        for key in self._kv.scan_keys(b"r\x00"):
            _, ref = self._parse_sid(key[2:])
            if ref not in live_refs:
                self._kv.delete(key)  # stale ref marker with no idx entries
        # startup GC: drop payloads nobody references (keys-only scan — no
        # payload bytes cross the engine boundary)
        for key in self._kv.scan_keys(b"m\x00"):
            if key[2:] not in live_refs:
                self._kv.delete(key)

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        with self._lock:
            ref = msg.msg_ref
            # the 2-3 records of one message write go down in a single
            # batched append (one engine lock acquisition) — the analog
            # of the reference's one gen_server call covering the whole
            # 3-key write (vmq_lvldb_store.erl:339-358)
            batch = []
            first = ref not in self._refcount
            if first:
                batch.append((b"m\x00" + ref, self._enc(msg)))
            sk = self._sid_key(sid)
            seq = self._seq.next()
            batch.append((b"r\x00" + sk + ref, b""))
            batch.append((b"i\x00" + sk + seq.to_bytes(8, "big"), ref))
            # durable records FIRST: if the append fails (disk full), the
            # in-memory maps are untouched and a retry of the same
            # msg_ref still writes the payload record — mutating
            # _refcount first would make a retried first-delivery skip
            # the m-record forever (silent loss after restart)
            self._kv.put_many(batch)
            if self._fsync:
                if self._group_commit:
                    # park the sync for the broker's flush-tick commit:
                    # one fsync per write burst, not per record
                    self._sync_pending += 1
                else:
                    self._kv.sync()  # per-write power-loss durability
            if first:
                self._refcount[ref] = 0
            self._refcount[ref] += 1
            self._seqs.setdefault(sid, {}).setdefault(ref, []).append(seq)
            if msg.expires_at is not None and ref not in self._exp:
                # monotonic deadline → wall clock, so the sweep can
                # compare against time.time() without a Msg decode
                self._exp[ref] = time.time() + max(
                    0.0, msg.expires_at - time.monotonic())

    def needs_commit(self) -> bool:
        return self._sync_pending > 0

    def commit(self) -> int:
        with self._lock:
            pending, self._sync_pending = self._sync_pending, 0
        if pending == 0:
            return 0
        self._kv.sync()
        return pending - 1

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        return [m for _, m in self.read_all_seq(sid)]

    def read_all_seq(self, sid: SubscriberId,
                     decoded: Optional[Dict[bytes, Msg]] = None
                     ) -> List[Tuple[int, Msg]]:
        """(enqueue-seq, msg) pairs in seq order — the merge key for a
        bucketed store's cross-instance recovery (the reference's ordset
        union in msg_store_collect, vmq_lvldb_store.erl:104-107).

        Served from the in-memory sid→ref→[seq] map (rebuilt from the
        ``i`` family at recovery, mirrored on every write/delete) with
        one engine point-get per distinct ref: a reconnect-storm read
        is O(backlog) per session, never an O(total-keys) prefix scan
        per session (the quadratic-storm cost the old per-sid engine
        scans paid). ``decoded`` is an optional shared ref→Msg cache —
        the payload family is refcounted ACROSS subscribers, so a
        broadcast's single m-record decodes once per batch, not once
        per session (sharing the Msg object mirrors the live fanout
        path, which enqueues one Msg to every queue)."""
        out: List[Tuple[int, Msg]] = []
        if decoded is None:
            decoded = {}
        with self._lock:
            pairs = [(seq, ref)
                     for ref, seqs in self._seqs.get(sid, {}).items()
                     for seq in seqs]
            pairs.sort()
            for seq, ref in pairs:
                msg = decoded.get(ref)
                if msg is None:
                    data = self._kv.get(b"m\x00" + ref)
                    if data is None:
                        continue
                    msg = decoded[ref] = self._dec(data)
                out.append((seq, msg))
        return out

    def read_many(self, sids: List[SubscriberId]
                  ) -> Dict[SubscriberId, List[Msg]]:
        """One batched recovery read (executor-friendly): a whole
        reconnect-storm batch resolves in ONE off-loop call, and the
        shared decode cache collapses cross-subscriber payload refs —
        a fan-out notification parked in 100k offline queues is ONE
        stored payload and decodes ONCE per batch here, where the
        per-session read_all baseline pays the decode per session."""
        decoded: Dict[bytes, Msg] = {}
        return {sid: [m for _, m in self.read_all_seq(sid, decoded)]
                for sid in sids}

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        with self._lock:
            seqs = self._seqs.get(sid, {}).get(msg_ref)
            if not seqs:
                return
            seq = seqs.pop(0)
            if not seqs:
                self._seqs[sid].pop(msg_ref, None)
            sk = self._sid_key(sid)
            keys = [b"i\x00" + sk + seq.to_bytes(8, "big")]
            if not self._seqs.get(sid, {}).get(msg_ref):
                keys.append(b"r\x00" + sk + msg_ref)
            keys.extend(self._deref_keys(msg_ref, 1))
            self._kv.delete_many(keys)

    def delete_all(self, sid: SubscriberId) -> None:
        with self._lock:
            sk = self._sid_key(sid)
            # the in-memory map names every live i/r key for this sid:
            # point deletes batched into ONE engine append+flush, not an
            # O(total-keys) prefix scan + a flush per record
            keys: List[bytes] = []
            for ref, seqs in self._seqs.pop(sid, {}).items():
                for seq in seqs:
                    keys.append(b"i\x00" + sk + seq.to_bytes(8, "big"))
                keys.append(b"r\x00" + sk + ref)
                keys.extend(self._deref_keys(ref, len(seqs)))
            if keys:
                self._kv.delete_many(keys)

    def _deref_keys(self, ref: bytes, n: int) -> List[bytes]:
        """Drop ``n`` refcounts; returns the payload key to delete when
        nobody references it anymore (caller batches the engine op)."""
        left = self._refcount.get(ref, 0) - n
        if left <= 0:
            self._refcount.pop(ref, None)
            self._exp.pop(ref, None)
            return [b"m\x00" + ref]
        self._refcount[ref] = left
        return []

    def sweep_expired(self, budget: int = 256) -> int:
        """Budgeted TTL sweep riding the store maintenance tick: delete
        parked copies whose v5 message-expiry deadline has passed, so a
        million-session store doesn't hold dead payloads until each
        owner reconnects. Refs recovered from disk carry no in-memory
        deadline — up to ``budget`` of them are classified per call
        (one point-get each, no Msg built), so a huge restarted store
        never stalls the tick. Returns the number of parked
        per-subscriber copies removed."""
        swept = 0
        with self._lock:
            now = time.time()
            examined = 0
            while self._exp_scan and examined < budget:
                ref = self._exp_scan.pop()
                examined += 1
                if ref not in self._refcount or ref in self._exp:
                    continue
                data = self._kv.get(b"m\x00" + ref)
                if data is None:
                    continue
                deadline = self._peek_deadline(data)
                if deadline is not None:
                    self._exp[ref] = deadline
            expired = {r for r, dl in self._exp.items() if dl <= now}
            if not expired:
                return 0
            # ONE pass over the sid map resolves every expired ref's
            # owners (there is no ref→sid reverse index to maintain)
            keys: List[bytes] = []
            for sid in list(self._seqs):
                table = self._seqs[sid]
                hit = expired.intersection(table)
                if not hit:
                    continue
                sk = self._sid_key(sid)
                for ref in hit:
                    seqs = table.pop(ref)
                    for seq in seqs:
                        keys.append(b"i\x00" + sk
                                    + seq.to_bytes(8, "big"))
                    keys.append(b"r\x00" + sk + ref)
                    keys.extend(self._deref_keys(ref, len(seqs)))
                    swept += len(seqs)
                if not table:
                    del self._seqs[sid]
            for ref in expired:
                self._exp.pop(ref, None)
            if keys:
                self._kv.delete_many(keys)
        return swept

    def stats(self) -> Dict[str, int]:
        out = {"stored_messages": len(self._refcount),
               "stored_refs": sum(len(m) for m in self._seqs.values()),
               "kv_keys": self._kv.count(),
               "kv_garbage_bytes": self._kv.garbage_bytes()}
        return out

    def sync(self) -> None:
        self._kv.sync()

    def close(self) -> None:
        if self._sync_pending:
            self.commit()
        self._kv.close()


class NativeMsgStore(EngineMsgStore):
    """C++ storage-engine-backed store (the kvstore engine mounted under
    :class:`EngineMsgStore`'s 3-key-family layout)."""

    def __init__(self, directory: str, seq: Optional[SeqCounter] = None,
                 fsync: bool = False, group_commit: bool = True):
        from .segment import NativeEngine

        os.makedirs(directory, exist_ok=True)
        super().__init__(
            NativeEngine(os.path.join(directory, "msgstore.kv")),
            seq=seq, fsync=fsync, group_commit=group_commit)


class SegmentMsgStore(EngineMsgStore):
    """Segment-log-backed store: the pure-Python twin of the native
    engine (``storage/segment.py``) under the same key families —
    sealed segments, checkpointed recovery (a million parked sessions
    boot by loading the checkpoint index, not replaying history), and
    broker-driven budgeted compaction off the event loop."""

    def __init__(self, directory: str, seq: Optional[SeqCounter] = None,
                 fsync: bool = False, group_commit: bool = True,
                 segment_max_bytes: int = 8 * 1024 * 1024,
                 checkpoint_every_bytes: int = 32 * 1024 * 1024):
        from .segment import SegmentLogEngine

        os.makedirs(directory, exist_ok=True)
        super().__init__(
            SegmentLogEngine(os.path.join(directory, "msgstore.seg"),
                             segment_max_bytes=segment_max_bytes,
                             checkpoint_every_bytes=checkpoint_every_bytes),
            seq=seq, fsync=fsync, group_commit=group_commit)

    @property
    def recover_skipped(self) -> int:
        return self.engine.recover_skipped

    def stats(self) -> Dict[str, int]:
        out = super().stats()
        for k, v in self.engine.stats().items():
            out[f"segment_{k}"] = v
        return out


class FileMsgStore(MemoryMsgStore):
    """Legacy flat append-log store: every op is one JSON line, state
    rebuilt by whole-file replay on open. Superseded by
    :class:`SegmentMsgStore` for new ``message_store = file`` data dirs
    (the broker keeps opening this class when a ``msgstore.log``
    already exists, so old data dirs stay readable)."""

    engine_kind = "file"

    def __init__(self, directory: str, fsync: bool = False,
                 group_commit: bool = True):
        super().__init__()
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "msgstore.log")
        self._fsync = fsync
        self._group_commit = group_commit
        self._sync_pending = 0
        #: corrupt mid-file records skipped at recovery (surfaced as the
        #: msg_store_recover_skipped metric by the broker)
        self.recover_skipped = 0
        self._recover()
        self._fh = open(self._path, "ab")

    def _recover(self) -> None:
        """Rebuild state from the journal, streaming (a long-lived log
        must not be slurped into memory). A torn final record (crash
        mid-append: no trailing newline) is expected — it is not applied
        and the file is TRUNCATED past it, or the next append would
        merge with the partial line and corrupt a good record. A corrupt
        newline-terminated record is skipped and counted — every later
        record still recovers (the old behavior discarded the whole
        tail)."""
        if not os.path.exists(self._path):
            return
        torn_at = None
        pos = 0
        with open(self._path, "rb") as fh:
            for line in fh:
                if not line.endswith(b"\n"):
                    torn_at = pos  # torn tail write
                    break
                pos += len(line)
                try:
                    rec = json.loads(line)
                    op = rec["op"]
                    sid = (rec["mp"], rec["cid"])
                    if op == "w":
                        msg = Msg(
                            topic=tuple(rec["topic"]),
                            payload=bytes.fromhex(rec["payload"]),
                            qos=rec["qos"],
                            retain=rec.get("retain", False),
                            mountpoint=rec["mp"],
                            msg_ref=rec["ref"].encode(),
                            properties=rec.get("props", {}),
                        )
                        super().write(sid, msg)
                    elif op == "d":
                        super().delete(sid, rec["ref"].encode())
                    elif op == "da":
                        super().delete_all(sid)
                except (json.JSONDecodeError, KeyError, ValueError,
                        TypeError):
                    self.recover_skipped += 1
        if torn_at is not None:
            with open(self._path, "r+b") as fh:
                fh.truncate(torn_at)
        if self.recover_skipped:
            log.warning("msg store %s: skipped %d corrupt record(s) "
                        "during recovery", self._path, self.recover_skipped)

    def _log(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec).encode() + b"\n")
        self._fh.flush()
        if self._fsync:  # opt-in power-loss durability
            if self._group_commit:
                self._sync_pending += 1  # one fsync per burst (commit)
            else:
                os.fsync(self._fh.fileno())

    def needs_commit(self) -> bool:
        return self._sync_pending > 0

    def commit(self) -> int:
        pending, self._sync_pending = self._sync_pending, 0
        if pending == 0:
            return 0
        os.fsync(self._fh.fileno())
        return pending - 1

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        super().write(sid, msg)
        self._log({
            "op": "w", "mp": sid[0], "cid": sid[1], "ref": msg.msg_ref.decode(),
            "topic": list(msg.topic), "payload": msg.payload.hex(),
            "qos": msg.qos, "retain": msg.retain,
            "props": {k: v for k, v in msg.properties.items()
                      if isinstance(v, (int, str, float))},
        })

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        super().delete(sid, msg_ref)
        self._log({"op": "d", "mp": sid[0], "cid": sid[1], "ref": msg_ref.decode()})

    def delete_all(self, sid: SubscriberId) -> None:
        super().delete_all(sid)
        self._log({"op": "da", "mp": sid[0], "cid": sid[1]})

    def close(self) -> None:
        if self._sync_pending:
            self.commit()
        self._fh.close()


class BucketedMsgStore(MsgStore):
    """N independent store instances hashed by MsgRef — the reference's
    bucket supervision (``vmq_lvldb_store_sup.erl:47-54``: ``phash2(Key)
    rem NR_OF_BUCKETS``, default 12 instances) so concurrent writers hit
    different engines/locks instead of serializing on one WAL mutex.

    Per-subscriber reads merge on the shared enqueue-seq (the
    reference's cross-bucket ordset union in ``msg_store_find``,
    ``vmq_lvldb_store.erl:84-107``) — but probe ONLY the buckets a
    sid→bucket membership index (exact set, rebuilt from each
    instance's recovery map at open) names: a reconnect-storm read for
    a session whose backlog landed in one bucket touches one engine,
    not all twelve. ``probe_hits``/``probe_misses`` count probed
    buckets that held messages vs stale memberships (cleaned on
    miss); the broker drains them into the
    ``store_bucket_probe_hits/misses`` counters on the maintenance
    tick.
    """

    supports_batched_read = True

    def __init__(self, directory: str, instances: int = 12,
                 fsync: bool = False, group_commit: bool = True):
        os.makedirs(directory, exist_ok=True)
        # the bucket count is part of the on-disk layout: ref→bucket hashing
        # must match what wrote the data, or deletes silently miss. Persist
        # it on first open and honour the persisted value thereafter.
        marker = os.path.join(directory, "INSTANCES")
        if os.path.exists(marker):
            with open(marker, "r", encoding="ascii") as fh:
                persisted = int(fh.read().strip())
            if persisted != instances:
                import logging

                logging.getLogger("vernemq_tpu.storage").warning(
                    "msg store in %s was created with %d instances; "
                    "ignoring configured %d", directory, persisted, instances)
            instances = persisted
        else:
            with open(marker, "w", encoding="ascii") as fh:
                fh.write(str(max(1, instances)))
        self._seqc = SeqCounter()
        self.instances: List[NativeMsgStore] = []
        try:
            for i in range(max(1, instances)):
                self.instances.append(NativeMsgStore(
                    os.path.join(directory, f"bucket{i}"), seq=self._seqc,
                    fsync=fsync, group_commit=group_commit))
        except Exception:
            for inst in self.instances:  # no half-open engines left locked
                inst.close()
            raise
        # sid → {bucket index}: membership rebuilt from each engine's
        # recovery map, maintained on write/delete. Reads probe only
        # member buckets; a stale member (emptied behind our back by
        # the TTL sweep) is a counted probe miss and is cleaned.
        self._index_lock = threading.Lock()
        self._sid_buckets: Dict[SubscriberId, set] = {}
        for i, inst in enumerate(self.instances):
            for sid in inst._seqs:
                self._sid_buckets.setdefault(sid, set()).add(i)
        self.probe_hits = 0
        self.probe_misses = 0

    @property
    def engine_kind(self) -> str:
        return self.instances[0].engine_kind

    def _bucket_idx(self, ref: bytes) -> int:
        return zlib.crc32(ref) % len(self.instances)

    def _bucket(self, ref: bytes) -> NativeMsgStore:
        return self.instances[self._bucket_idx(ref)]

    def _probe(self, sid: SubscriberId, decoded=None
               ) -> List[Tuple[int, Msg]]:
        """Merged (seq, msg) rows for ``sid`` from its member buckets
        only; counts hits/misses and drops memberships proven stale
        (the instance's recovery map no longer knows the sid)."""
        with self._index_lock:
            members = sorted(self._sid_buckets.get(sid, ()))
        merged: List[Tuple[int, Msg]] = []
        hits = misses = 0
        for i in members:
            inst = self.instances[i]
            rows = inst.read_all_seq(sid, decoded)
            if rows:
                merged.extend(rows)
                hits += 1
                continue
            misses += 1
            with self._index_lock:
                # re-check under the lock: a concurrent write adds the
                # membership only AFTER its instance write landed, so
                # an absent sid here is genuinely stale
                if sid not in inst._seqs:
                    s = self._sid_buckets.get(sid)
                    if s is not None:
                        s.discard(i)
                        if not s:
                            self._sid_buckets.pop(sid, None)
        if hits or misses:
            with self._index_lock:
                self.probe_hits += hits
                self.probe_misses += misses
        merged.sort(key=lambda p: p[0])
        return merged

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        i = self._bucket_idx(msg.msg_ref)
        self.instances[i].write(sid, msg)
        with self._index_lock:
            self._sid_buckets.setdefault(sid, set()).add(i)

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        return [m for _, m in self._probe(sid)]

    def read_many(self, sids: List[SubscriberId]
                  ) -> Dict[SubscriberId, List[Msg]]:
        decoded: Dict[bytes, Msg] = {}
        return {sid: [m for _, m in self._probe(sid, decoded)]
                for sid in sids}

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        i = self._bucket_idx(msg_ref)
        inst = self.instances[i]
        inst.delete(sid, msg_ref)
        if sid not in inst._seqs:
            with self._index_lock:
                s = self._sid_buckets.get(sid)
                if s is not None:
                    s.discard(i)
                    if not s:
                        self._sid_buckets.pop(sid, None)

    def delete_all(self, sid: SubscriberId) -> None:
        with self._index_lock:
            members = sorted(self._sid_buckets.pop(sid, ()))
        for i in members:
            self.instances[i].delete_all(sid)

    def sweep_expired(self, budget: int = 256) -> int:
        return sum(inst.sweep_expired(budget) for inst in self.instances)

    def needs_commit(self) -> bool:
        return any(inst.needs_commit() for inst in self.instances)

    def commit(self) -> int:
        return sum(inst.commit() for inst in self.instances)

    def stats(self) -> Dict[str, int]:
        agg: Dict[str, int] = {}
        for inst in self.instances:
            for k, v in inst.stats().items():
                agg[k] = agg.get(k, 0) + v
        agg["instances"] = len(self.instances)
        agg["bucket_index_sids"] = len(self._sid_buckets)
        agg["bucket_probe_hits"] = self.probe_hits
        agg["bucket_probe_misses"] = self.probe_misses
        return agg

    def sync(self) -> None:
        for inst in self.instances:
            inst.sync()

    def close(self) -> None:
        for inst in self.instances:
            inst.close()
