"""Offline message store.

Mirrors the reference message-store seam: the store is itself a plugin
(``msg_store_write/read/delete/find`` hooks, used from the queue at
``vmq_queue.erl:420,797,946,970``), with the LevelDB implementation
(``vmq_lvldb_store.erl``) keeping three key families — message payload by
ref, per-subscriber ref entries, and a per-subscriber index for recovery
scans (``vmq_lvldb_store.erl:339-416``) — plus payload refcounting across
subscribers.

Round 1 ships the in-memory store and a durable append-log file store with
the same refcounted layout; the C++/RocksDB engine lands behind this same
interface in a later round.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..broker.message import Msg, SubscriberId

log = logging.getLogger("vernemq_tpu.storage")


class MsgStore:
    """Interface (msg_store_* plugin hooks)."""

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        raise NotImplementedError

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        raise NotImplementedError

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        raise NotImplementedError

    def delete_all(self, sid: SubscriberId) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryMsgStore(MsgStore):
    def __init__(self) -> None:
        # payload table: ref -> (msg, refcount)  (dedup across subscribers,
        # vmq_lvldb_store.erl:347,455-472)
        self._msgs: Dict[bytes, Tuple[Msg, int]] = {}
        # index: sid -> [ref] in arrival order (the sext-ordered idx family)
        self._idx: Dict[SubscriberId, List[bytes]] = {}

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        entry = self._msgs.get(msg.msg_ref)
        if entry is None:
            self._msgs[msg.msg_ref] = (msg, 1)
        else:
            self._msgs[msg.msg_ref] = (entry[0], entry[1] + 1)
        self._idx.setdefault(sid, []).append(msg.msg_ref)

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        return [self._msgs[r][0] for r in self._idx.get(sid, []) if r in self._msgs]

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        idx = self._idx.get(sid)
        if idx and msg_ref in idx:
            idx.remove(msg_ref)
            self._deref(msg_ref)

    def delete_all(self, sid: SubscriberId) -> None:
        for ref in self._idx.pop(sid, []):
            self._deref(ref)

    def _deref(self, ref: bytes) -> None:
        entry = self._msgs.get(ref)
        if entry is None:
            return
        if entry[1] <= 1:
            del self._msgs[ref]
        else:
            self._msgs[ref] = (entry[0], entry[1] - 1)

    def stats(self) -> Dict[str, int]:
        return {"stored_messages": len(self._msgs),
                "stored_refs": sum(len(v) for v in self._idx.values())}


class SeqCounter:
    """Monotonic enqueue-order counter, shareable across store instances so
    a bucketed store's per-subscriber recovery merge preserves global
    arrival order."""

    __slots__ = ("_next", "_lock")

    def __init__(self) -> None:
        self._next = 1
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            n = self._next
            self._next += 1
            return n

    def bump(self, seen: int) -> None:
        with self._lock:
            if seen >= self._next:
                self._next = seen + 1


class NativeMsgStore(MsgStore):
    """C++ storage-engine-backed store with the reference's 3-key-family
    layout (``vmq_lvldb_store.erl:339-416``):

    - ``m\\x00<ref>``                       → encoded message (payload family)
    - ``r\\x00<sid><ref>``                  → b"" (per-subscriber ref entry)
    - ``i\\x00<sid><seq:8>``                → ref (ordered recovery index)

    Payloads are deduplicated across subscribers via an in-memory refcount
    rebuilt from the ``r`` family on open; unreferenced payloads are
    garbage-collected by a startup scan (``vmq_lvldb_store.erl:418-453``).

    Thread-safety: one lock per store instance around the host-side maps
    (the C++ engine has its own per-instance mutex) — the analog of the
    reference's one gen_server per bucket serializing that bucket's ops.
    """

    def __init__(self, directory: str, seq: Optional[SeqCounter] = None,
                 fsync: bool = False):
        import time as _time

        from ..cluster.codec import decode, encode
        from ..cluster.node import msg_to_term, term_to_msg
        from ..native.kvstore import KVStore

        # wrap the wire term with the wall-clock store time: the codec's
        # "remaining seconds" expiry is rebased at decode, so time spent in
        # the store counts against MQTT5 message_expiry_interval
        def _enc(m):
            return encode([msg_to_term(m), _time.time()])

        def _dec(b):
            term, stored_at = decode(b)
            if term.get("exp") is not None:
                elapsed = max(0.0, _time.time() - stored_at)
                term["exp"] = max(0.0, term["exp"] - elapsed)
            return term_to_msg(term)

        self._enc = _enc
        self._dec = _dec
        os.makedirs(directory, exist_ok=True)
        self._kv = KVStore(os.path.join(directory, "msgstore.kv"))
        # refcount + sid→ref→[seq] maps, rebuilt from the r/i families
        self._refcount: Dict[bytes, int] = {}
        self._seqs: Dict[SubscriberId, Dict[bytes, List[int]]] = {}
        self._seq = seq or SeqCounter()
        self._fsync = fsync
        self._lock = threading.Lock()
        self._recover()

    @staticmethod
    def _sid_key(sid: SubscriberId) -> bytes:
        mp = sid[0].encode()
        cid = sid[1].encode()
        return (len(mp).to_bytes(2, "big") + mp
                + len(cid).to_bytes(2, "big") + cid)

    @staticmethod
    def _parse_sid(b: bytes) -> Tuple[SubscriberId, bytes]:
        n = int.from_bytes(b[:2], "big")
        mp = b[2:2 + n].decode()
        rest = b[2 + n:]
        n2 = int.from_bytes(rest[:2], "big")
        cid = rest[2:2 + n2].decode()
        return (mp, cid), rest[2 + n2:]

    def _recover(self) -> None:
        # refcounts must be rebuilt one-per-enqueue (per i-entry), matching
        # the runtime write path — counting r-keys (one per sid+ref) would
        # undercount a message enqueued twice to the same subscriber and a
        # later delete would free the payload while a copy is still owed
        live_refs: Dict[bytes, int] = {}
        for key, ref in self._kv.scan(b"i\x00"):
            sid, seq_b = self._parse_sid(key[2:])
            seq = int.from_bytes(seq_b, "big")
            self._seqs.setdefault(sid, {}).setdefault(ref, []).append(seq)
            self._seq.bump(seq)
            live_refs[ref] = live_refs.get(ref, 0) + 1
        self._refcount = live_refs
        for key in self._kv.scan_keys(b"r\x00"):
            _, ref = self._parse_sid(key[2:])
            if ref not in live_refs:
                self._kv.delete(key)  # stale ref marker with no idx entries
        # startup GC: drop payloads nobody references (keys-only scan — no
        # payload bytes cross the C boundary)
        for key in self._kv.scan_keys(b"m\x00"):
            if key[2:] not in live_refs:
                self._kv.delete(key)

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        with self._lock:
            ref = msg.msg_ref
            # the 2-3 records of one message write go down in a single
            # batched append (one native lock acquisition) — the analog
            # of the reference's one gen_server call covering the whole
            # 3-key write (vmq_lvldb_store.erl:339-358)
            batch = []
            first = ref not in self._refcount
            if first:
                batch.append((b"m\x00" + ref, self._enc(msg)))
            sk = self._sid_key(sid)
            seq = self._seq.next()
            batch.append((b"r\x00" + sk + ref, b""))
            batch.append((b"i\x00" + sk + seq.to_bytes(8, "big"), ref))
            # durable records FIRST: if the append fails (disk full), the
            # in-memory maps are untouched and a retry of the same
            # msg_ref still writes the payload record — mutating
            # _refcount first would make a retried first-delivery skip
            # the m-record forever (silent loss after restart)
            self._kv.put_many(batch)
            if self._fsync:  # opt-in power-loss durability per write
                self._kv.sync()
            if first:
                self._refcount[ref] = 0
            self._refcount[ref] += 1
            self._seqs.setdefault(sid, {}).setdefault(ref, []).append(seq)

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        return [m for _, m in self.read_all_seq(sid)]

    def read_all_seq(self, sid: SubscriberId) -> List[Tuple[int, Msg]]:
        """(enqueue-seq, msg) pairs in seq order — the merge key for a
        bucketed store's cross-instance recovery (the reference's ordset
        union in msg_store_collect, vmq_lvldb_store.erl:104-107)."""
        out: List[Tuple[int, Msg]] = []
        with self._lock:
            for key, ref in self._kv.scan(b"i\x00" + self._sid_key(sid)):
                data = self._kv.get(b"m\x00" + ref)
                if data is not None:
                    out.append((int.from_bytes(key[-8:], "big"),
                                self._dec(data)))
        return out

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        with self._lock:
            seqs = self._seqs.get(sid, {}).get(msg_ref)
            if not seqs:
                return
            seq = seqs.pop(0)
            if not seqs:
                self._seqs[sid].pop(msg_ref, None)
            sk = self._sid_key(sid)
            self._kv.delete(b"i\x00" + sk + seq.to_bytes(8, "big"))
            if not self._seqs.get(sid, {}).get(msg_ref):
                self._kv.delete(b"r\x00" + sk + msg_ref)
            self._deref(msg_ref)

    def delete_all(self, sid: SubscriberId) -> None:
        with self._lock:
            sk = self._sid_key(sid)
            for key, ref in self._kv.scan(b"i\x00" + sk):
                self._kv.delete(key)
                self._deref(ref)
            for key, _ in self._kv.scan(b"r\x00" + sk):
                self._kv.delete(key)
            self._seqs.pop(sid, None)

    def _deref(self, ref: bytes) -> None:
        n = self._refcount.get(ref, 0) - 1
        if n <= 0:
            self._refcount.pop(ref, None)
            self._kv.delete(b"m\x00" + ref)
        else:
            self._refcount[ref] = n

    def stats(self) -> Dict[str, int]:
        return {"stored_messages": len(self._refcount),
                "stored_refs": sum(len(m) for m in self._seqs.values()),
                "kv_keys": self._kv.count(),
                "kv_garbage_bytes": self._kv.garbage_bytes()}

    def sync(self) -> None:
        self._kv.sync()

    def close(self) -> None:
        self._kv.close()


class FileMsgStore(MemoryMsgStore):
    """Append-log-backed store: every op is journaled, state rebuilt on open
    (the recovery scan role of vmq_lvldb_store.erl:396-453). Simple but
    durable; swapped for the C++ engine later."""

    def __init__(self, directory: str, fsync: bool = False):
        super().__init__()
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "msgstore.log")
        self._fsync = fsync
        #: corrupt mid-file records skipped at recovery (surfaced as the
        #: msg_store_recover_skipped metric by the broker)
        self.recover_skipped = 0
        self._recover()
        self._fh = open(self._path, "ab")

    def _recover(self) -> None:
        """Rebuild state from the journal, streaming (a long-lived log
        must not be slurped into memory). A torn final record (crash
        mid-append: no trailing newline) is expected — it is not applied
        and the file is TRUNCATED past it, or the next append would
        merge with the partial line and corrupt a good record. A corrupt
        newline-terminated record is skipped and counted — every later
        record still recovers (the old behavior discarded the whole
        tail)."""
        if not os.path.exists(self._path):
            return
        torn_at = None
        pos = 0
        with open(self._path, "rb") as fh:
            for line in fh:
                if not line.endswith(b"\n"):
                    torn_at = pos  # torn tail write
                    break
                pos += len(line)
                try:
                    rec = json.loads(line)
                    op = rec["op"]
                    sid = (rec["mp"], rec["cid"])
                    if op == "w":
                        msg = Msg(
                            topic=tuple(rec["topic"]),
                            payload=bytes.fromhex(rec["payload"]),
                            qos=rec["qos"],
                            retain=rec.get("retain", False),
                            mountpoint=rec["mp"],
                            msg_ref=rec["ref"].encode(),
                            properties=rec.get("props", {}),
                        )
                        super().write(sid, msg)
                    elif op == "d":
                        super().delete(sid, rec["ref"].encode())
                    elif op == "da":
                        super().delete_all(sid)
                except (json.JSONDecodeError, KeyError, ValueError,
                        TypeError):
                    self.recover_skipped += 1
        if torn_at is not None:
            with open(self._path, "r+b") as fh:
                fh.truncate(torn_at)
        if self.recover_skipped:
            log.warning("msg store %s: skipped %d corrupt record(s) "
                        "during recovery", self._path, self.recover_skipped)

    def _log(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec).encode() + b"\n")
        self._fh.flush()
        if self._fsync:  # opt-in power-loss durability per write
            os.fsync(self._fh.fileno())

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        super().write(sid, msg)
        self._log({
            "op": "w", "mp": sid[0], "cid": sid[1], "ref": msg.msg_ref.decode(),
            "topic": list(msg.topic), "payload": msg.payload.hex(),
            "qos": msg.qos, "retain": msg.retain,
            "props": {k: v for k, v in msg.properties.items()
                      if isinstance(v, (int, str, float))},
        })

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        super().delete(sid, msg_ref)
        self._log({"op": "d", "mp": sid[0], "cid": sid[1], "ref": msg_ref.decode()})

    def delete_all(self, sid: SubscriberId) -> None:
        super().delete_all(sid)
        self._log({"op": "da", "mp": sid[0], "cid": sid[1]})

    def close(self) -> None:
        self._fh.close()


class BucketedMsgStore(MsgStore):
    """N independent store instances hashed by MsgRef — the reference's
    bucket supervision (``vmq_lvldb_store_sup.erl:47-54``: ``phash2(Key)
    rem NR_OF_BUCKETS``, default 12 instances) so concurrent writers hit
    different engines/locks instead of serializing on one WAL mutex.

    Per-subscriber reads fan out to every instance and merge on the shared
    enqueue-seq (the reference's cross-bucket ordset union in
    ``msg_store_find``, ``vmq_lvldb_store.erl:84-107``).
    """

    def __init__(self, directory: str, instances: int = 12,
                 fsync: bool = False):
        os.makedirs(directory, exist_ok=True)
        # the bucket count is part of the on-disk layout: ref→bucket hashing
        # must match what wrote the data, or deletes silently miss. Persist
        # it on first open and honour the persisted value thereafter.
        marker = os.path.join(directory, "INSTANCES")
        if os.path.exists(marker):
            with open(marker, "r", encoding="ascii") as fh:
                persisted = int(fh.read().strip())
            if persisted != instances:
                import logging

                logging.getLogger("vernemq_tpu.storage").warning(
                    "msg store in %s was created with %d instances; "
                    "ignoring configured %d", directory, persisted, instances)
            instances = persisted
        else:
            with open(marker, "w", encoding="ascii") as fh:
                fh.write(str(max(1, instances)))
        self._seqc = SeqCounter()
        self.instances: List[NativeMsgStore] = []
        try:
            for i in range(max(1, instances)):
                self.instances.append(NativeMsgStore(
                    os.path.join(directory, f"bucket{i}"), seq=self._seqc,
                    fsync=fsync))
        except Exception:
            for inst in self.instances:  # no half-open engines left locked
                inst.close()
            raise

    def _bucket(self, ref: bytes) -> NativeMsgStore:
        return self.instances[zlib.crc32(ref) % len(self.instances)]

    def write(self, sid: SubscriberId, msg: Msg) -> None:
        self._bucket(msg.msg_ref).write(sid, msg)

    def read_all(self, sid: SubscriberId) -> List[Msg]:
        merged: List[Tuple[int, Msg]] = []
        for inst in self.instances:
            merged.extend(inst.read_all_seq(sid))
        merged.sort(key=lambda p: p[0])
        return [m for _, m in merged]

    def delete(self, sid: SubscriberId, msg_ref: bytes) -> None:
        self._bucket(msg_ref).delete(sid, msg_ref)

    def delete_all(self, sid: SubscriberId) -> None:
        for inst in self.instances:
            inst.delete_all(sid)

    def stats(self) -> Dict[str, int]:
        agg: Dict[str, int] = {}
        for inst in self.instances:
            for k, v in inst.stats().items():
                agg[k] = agg.get(k, 0) + v
        agg["instances"] = len(self.instances)
        return agg

    def sync(self) -> None:
        for inst in self.instances:
            inst.sync()

    def close(self) -> None:
        for inst in self.instances:
            inst.close()
