"""Unified segment-log storage engine.

One engine now backs BOTH durable key families of the broker: the
offline message store (``storage/msg_store.py`` — the ``m``/``r``/``i``
families mirroring ``vmq_lvldb_store.erl:339-416``) and the cluster
delivery spool (``cluster/spool.py`` — the per-peer ``s``/``h``
families). Before this module each grew its own journal: the msg store
a flat JSON append log replayed whole-file on every open, the spool a
private ``_FileJournal`` with its own compaction heuristics. At
million-offline-session scale that means two divergent recovery
disciplines and an O(total-history) boot.

The engine is an ordered byte-key store with prefix scans — exactly the
seat eleveldb occupies in the reference — in three interchangeable
implementations behind :func:`open_engine`:

- :class:`NativeEngine` — the C++ kvstore (``native/kvstore.cc``) when
  the toolchain built it; compaction and crash recovery are the
  engine's own.
- :class:`SegmentLogEngine` — the pure-Python twin: append-only
  **sealed segments** (``seg-<id>.log``), an in-memory key → (segment,
  offset, length) index (values stay ON DISK — a million parked
  offline queues must not live in the Python heap), **checkpointed
  recovery** (load the serialized index, then replay only the records
  past the checkpoint frontier — never the whole history), and
  **budgeted compaction**: :meth:`~SegmentLogEngine.compact_step`
  evacuates at most ``budget`` live bytes from the deadest sealed
  segment per call, so the broker can run it off the event loop under
  the watchdog with a per-tick byte budget (``store.compact`` is a
  registered fault point; the broker's store breaker pauses compaction
  — append-only degraded mode — without touching delivery).
- :class:`MemEngine` — dict-backed, for ``message_store = memory`` /
  an unset spool dir (no crash durability, same interface).

Record framing is the spool journal's proven discipline: ``P`` +
u32 klen + key + u32 vlen + value, ``D`` + u32 klen + key; a torn tail
(crash mid-append) truncates to the last whole record on recovery.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..robustness import faults

log = logging.getLogger("vernemq_tpu.storage")

#: fixed per-record framing overhead (opcode byte + u32 length fields)
_PUT_OVERHEAD = 9   # b"P" + klen:4 + ... + vlen:4
_DEL_OVERHEAD = 5   # b"D" + klen:4

_CKPT_MAGIC = b"VMQCKPT1"


def _seg_name(seg_id: int) -> str:
    return f"seg-{seg_id:08d}.log"


class MemEngine:
    """In-process engine: full interface, no durability (the
    ``message_store = memory`` seat and the dir-less spool journal)."""

    kind = "memory"
    durable = False

    def __init__(self) -> None:
        self._d: Dict[bytes, bytes] = {}

    def put_many(self, pairs) -> None:
        self._d.update(dict(pairs))

    def get(self, key: bytes) -> Optional[bytes]:
        return self._d.get(key)

    def delete(self, key: bytes) -> bool:
        return self._d.pop(key, None) is not None

    def delete_many(self, keys) -> int:
        return sum(1 for k in keys if self._d.pop(k, None) is not None)

    def scan(self, prefix: bytes = b"") -> List[Tuple[bytes, bytes]]:
        return sorted((k, v) for k, v in self._d.items()
                      if k.startswith(prefix))

    def scan_keys(self, prefix: bytes = b"") -> List[bytes]:
        return sorted(k for k in self._d if k.startswith(prefix))

    def count(self) -> int:
        return len(self._d)

    def garbage_bytes(self) -> int:
        return 0

    def compact_step(self, budget: int = 0) -> int:
        return 0

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass

    def stats(self) -> Dict[str, int]:
        return {"keys": len(self._d), "live_bytes":
                sum(len(k) + len(v) for k, v in self._d.items())}


class SegmentLogEngine:
    """Pure-Python segment-compacted log engine (the native kvstore's
    twin — same interface, same crash discipline).

    Thread model: callers on the event loop (writes, point gets) and
    maintenance on executor threads (compaction, batched recovery
    reads) share ``_lock`` for index/accounting mutations; segment
    bytes at a given (segment, offset) are IMMUTABLE once written
    (append-only, compaction copies then unlinks whole files), so value
    reads happen outside the lock via ``os.pread`` — a compaction
    running under an executor never blocks a loop-side read for the
    duration of a file copy.
    """

    kind = "segment"
    durable = True

    def __init__(self, directory: str,
                 segment_max_bytes: int = 8 * 1024 * 1024,
                 checkpoint_every_bytes: int = 32 * 1024 * 1024,
                 compact_dead_ratio: float = 0.5):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.segment_max_bytes = max(256, int(segment_max_bytes))
        self.checkpoint_every_bytes = int(checkpoint_every_bytes)
        self.compact_dead_ratio = compact_dead_ratio
        self._lock = threading.Lock()
        # key -> (segment id, value offset, value length)
        self._index: Dict[bytes, Tuple[int, int, int]] = {}
        self._seg_size: Dict[int, int] = {}   # on-disk bytes per segment
        self._seg_live: Dict[int, int] = {}   # live record bytes per seg
        self._read_fh: Dict[int, object] = {}
        self._active = 1
        self._active_fh = None
        #: recovery/compaction observability (surfaced as broker gauges)
        self.recover_skipped = 0      # corrupt mid-log records skipped
        self.recover_fallbacks = 0    # checkpoint unusable -> full scan
        self.recover_replayed = 0     # records replayed past the frontier
        self.compactions = 0          # segments fully evacuated+unlinked
        self.compacted_bytes = 0      # live bytes copied by compaction
        self.checkpoints = 0
        self._since_checkpoint = 0    # appended bytes since last ckpt
        # in-progress evacuation: (victim seg, remaining keys, bytes
        # copied so far across budgeted ticks)
        self._evac: Optional[Tuple[int, List[bytes], int]] = None
        # serializes maintenance entry points (the periodic tick vs an
        # admin `store compact`) without blocking either
        self._compact_mutex = threading.Lock()
        # segments sealed since the last sync(): their tails are still
        # page-cache-only; a group commit must fsync THEM too or the
        # fsync promise has a hole exactly at every seal boundary
        self._sealed_unsynced: List[int] = []
        self._recover()
        self._open_active()

    # ------------------------------------------------------------ recovery

    def _segments_on_disk(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("seg-") and name.endswith(".log"):
                try:
                    out.append(int(name[4:-4]))
                except ValueError:
                    continue
        return sorted(out)

    def _ckpt_path(self) -> str:
        return os.path.join(self.directory, "CHECKPOINT")

    def _load_checkpoint(self):
        """Parse the checkpoint -> (index, frontier_seg, frontier_off),
        or None when absent/corrupt/stale. ``store.recover`` is the
        injected-fault seam: a drill here exercises the full-scan
        degradation (data still recovers, just slower)."""
        faults.inject("store.recover", max_delay_s=1.0)
        path = self._ckpt_path()
        if not os.path.exists(path):
            return None
        with open(path, "rb") as fh:
            blob = fh.read()
        # minimum = magic + ">IQQ" header (20) + crc (4): an EMPTY
        # index checkpoint (a drained store's clean state) is valid
        if len(blob) < len(_CKPT_MAGIC) + 20 + 4 \
                or not blob.startswith(_CKPT_MAGIC):
            raise ValueError("checkpoint header corrupt")
        body, (crc,) = blob[:-4], struct.unpack(">I", blob[-4:])
        if zlib.crc32(body) != crc:
            raise ValueError("checkpoint crc mismatch")
        pos = len(_CKPT_MAGIC)
        front_seg, front_off, n = struct.unpack(">IQQ", body[pos:pos + 20])
        pos += 20
        index: Dict[bytes, Tuple[int, int, int]] = {}
        for _ in range(n):
            (klen,) = struct.unpack(">I", body[pos:pos + 4])
            pos += 4
            key = body[pos:pos + klen]
            pos += klen
            seg, off, vlen = struct.unpack(">IQI", body[pos:pos + 16])
            pos += 16
            index[key] = (seg, off, vlen)
        return index, front_seg, front_off

    def _recover(self) -> None:
        segs = self._segments_on_disk()
        if not segs:
            return
        ckpt = None
        try:
            ckpt = self._load_checkpoint()
        except Exception as e:
            self.recover_fallbacks += 1
            log.warning("segment engine %s: checkpoint unusable (%s); "
                        "full segment scan", self.directory, e)
        start_seg, start_off = segs[0], 0
        if ckpt is not None:
            index, front_seg, front_off = ckpt
            # every indexed segment and the frontier itself must still
            # exist (a checkpoint written before a compaction unlink
            # can reference nothing that is gone — unlinks happen only
            # AFTER the post-evacuation checkpoint — but be defensive)
            known = set(segs)
            if (front_seg in known or front_seg == segs[-1] + 1) and all(
                    loc[0] in known for loc in index.values()):
                self._index = index
                start_seg, start_off = front_seg, front_off
            else:
                self.recover_fallbacks += 1
                self._index = {}
                log.warning("segment engine %s: checkpoint references "
                            "missing segments; full scan",
                            self.directory)
        for seg in segs:
            if seg < start_seg:
                continue
            self._replay_segment(
                seg, start_off if seg == start_seg else 0,
                truncate_torn=(seg == segs[-1]))
        # rebuild live/size accounting from the recovered index: the
        # index IS the live set, everything else on disk is garbage
        self._seg_size = {
            s: os.path.getsize(os.path.join(self.directory, _seg_name(s)))
            for s in segs}
        self._seg_live = {s: 0 for s in segs}
        for key, (seg, _off, vlen) in self._index.items():
            self._seg_live[seg] = (self._seg_live.get(seg, 0)
                                   + _PUT_OVERHEAD + len(key) + vlen)
        self._active = segs[-1]

    def _replay_segment(self, seg: int, start: int,
                        truncate_torn: bool) -> None:
        path = os.path.join(self.directory, _seg_name(seg))
        with open(path, "rb") as fh:
            if start:
                fh.seek(start)
            blob = fh.read()
        pos = 0
        n = len(blob)
        while pos < n:
            rec_start = pos
            op = blob[pos:pos + 1]
            if op not in (b"P", b"D") or pos + 5 > n:
                break  # torn/garbage tail
            (klen,) = struct.unpack(">I", blob[pos + 1:pos + 5])
            pos += 5
            key = blob[pos:pos + klen]
            pos += klen
            if len(key) != klen:
                pos = rec_start
                break
            if op == b"P":
                if pos + 4 > n:
                    pos = rec_start
                    break
                (vlen,) = struct.unpack(">I", blob[pos:pos + 4])
                pos += 4
                if pos + vlen > n:
                    pos = rec_start
                    break
                self._index[key] = (seg, start + pos, vlen)
                pos += vlen
            else:
                self._index.pop(key, None)
            self.recover_replayed += 1
        if pos < n:
            if truncate_torn:
                log.warning("segment %s: torn tail at +%d of %d bytes "
                            "(truncating)", path, start + pos, start + n)
                with open(path, "r+b") as fh:
                    fh.truncate(start + pos)
            else:
                # a torn record in a SEALED segment is corruption, not a
                # crash artifact: skip the remainder, count it, keep
                # every later segment's records
                self.recover_skipped += 1
                log.warning("segment %s: corrupt record at +%d; skipping "
                            "the remainder of the segment",
                            path, start + pos)

    # ------------------------------------------------------------- append

    def _open_active(self) -> None:
        path = os.path.join(self.directory, _seg_name(self._active))
        self._active_fh = open(path, "ab")
        self._seg_size.setdefault(self._active, self._active_fh.tell())
        self._seg_live.setdefault(self._active, 0)

    def _roll_segment_locked(self) -> None:
        """Seal the active segment and start the next one. Called with
        the lock held; the open is a local file create on the data dir
        — microseconds, not device work."""
        self._active_fh.close()
        self._sealed_unsynced.append(self._active)
        self._active += 1
        path = os.path.join(self.directory, _seg_name(self._active))
        # vmqlint: allow(lock-discipline): sealing must swap the append
        # handle atomically with the segment-id frontier; a local
        # O_APPEND create is a bounded syscall, not device/compile work
        self._active_fh = open(path, "ab")
        self._seg_size[self._active] = 0
        self._seg_live[self._active] = 0

    def put_many(self, pairs) -> None:
        pairs = list(pairs)
        if not pairs:
            return
        with self._lock:
            self._put_many_locked(pairs)

    def _put_many_locked(self, pairs) -> None:
        out = bytearray()
        base = self._seg_size[self._active]
        seg = self._active
        locs: List[Tuple[bytes, Tuple[int, int, int]]] = []
        for k, v in pairs:
            # value starts after P + klen + key + vlen
            voff = base + len(out) + _PUT_OVERHEAD + len(k)
            out += b"P" + struct.pack(">I", len(k)) + k
            out += struct.pack(">I", len(v)) + v
            locs.append((k, (seg, voff, len(v))))
        self._active_fh.write(out)
        self._active_fh.flush()
        self._seg_size[seg] = base + len(out)
        self._since_checkpoint += len(out)
        for k, loc in locs:
            old = self._index.get(k)
            if old is not None:
                self._seg_live[old[0]] -= (_PUT_OVERHEAD + len(k)
                                           + old[2])
            self._index[k] = loc
            self._seg_live[seg] += _PUT_OVERHEAD + len(k) + loc[2]
        if self._seg_size[seg] >= self.segment_max_bytes:
            self._roll_segment_locked()

    def delete(self, key: bytes) -> bool:
        return self.delete_many([key]) == 1

    def delete_many(self, keys) -> int:
        """Batched deletes: ONE append + flush for a whole dequeue
        burst (a delivered offline backlog's i/r/m teardown) — the
        loop-side cost per dequeued message is an index-entry append,
        not a write+flush each."""
        out = bytearray()
        n = 0
        with self._lock:
            for key in keys:
                old = self._index.pop(key, None)
                if old is None:
                    continue
                self._seg_live[old[0]] -= _PUT_OVERHEAD + len(key) + old[2]
                out += b"D" + struct.pack(">I", len(key)) + key
                n += 1
            if not out:
                return 0
            self._active_fh.write(out)
            self._active_fh.flush()
            self._seg_size[self._active] += len(out)
            self._since_checkpoint += len(out)
            if self._seg_size[self._active] >= self.segment_max_bytes:
                self._roll_segment_locked()
            return n

    # -------------------------------------------------------------- reads

    def _read_handle(self, seg: int):
        fh = self._read_fh.get(seg)
        if fh is None:
            fh = open(os.path.join(self.directory, _seg_name(seg)), "rb")
            # loop-side get and executor-side compaction may race the
            # first open of a segment: exactly one handle wins the cache
            won = self._read_fh.setdefault(seg, fh)
            if won is not fh:
                fh.close()
                fh = won
        return fh

    def _read_loc(self, loc: Tuple[int, int, int]) -> bytes:
        seg, off, vlen = loc
        if vlen == 0:
            return b""
        fh = self._read_handle(seg)
        return os.pread(fh.fileno(), vlen, off)

    def get(self, key: bytes) -> Optional[bytes]:
        # bytes at a (segment, offset) never change (append-only;
        # compaction copies then unlinks whole files, and an already-
        # open read handle survives the unlink) — so the read itself
        # runs outside the lock. Retry once if the segment handle
        # raced a compaction unlink before first open.
        for _ in range(3):
            with self._lock:
                loc = self._index.get(key)
            if loc is None:
                return None
            try:
                return self._read_loc(loc)
            except FileNotFoundError:
                with self._lock:
                    self._read_fh.pop(loc[0], None)
                continue
        with self._lock:  # pathological race: serve under the lock
            loc = self._index.get(key)
            return None if loc is None else self._read_loc(loc)

    def scan(self, prefix: bytes = b"") -> List[Tuple[bytes, bytes]]:
        with self._lock:
            items = sorted((k, loc) for k, loc in self._index.items()
                           if k.startswith(prefix))
        out = []
        for k, loc in items:
            try:
                out.append((k, self._read_loc(loc)))
            except FileNotFoundError:
                v = self.get(k)  # re-resolve through the moved index
                if v is not None:
                    out.append((k, v))
        return out

    def scan_keys(self, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return sorted(k for k in self._index if k.startswith(prefix))

    def count(self) -> int:
        with self._lock:
            return len(self._index)

    def live_bytes(self) -> int:
        with self._lock:
            return sum(self._seg_live.values())

    def garbage_bytes(self) -> int:
        with self._lock:
            return max(0, sum(self._seg_size.values())
                       - sum(self._seg_live.values()))

    # --------------------------------------------------------- compaction

    def _pick_victim_locked(self) -> Optional[int]:
        best, best_dead = None, 0
        for seg, size in self._seg_size.items():
            if seg == self._active or size == 0:
                continue
            dead = size - self._seg_live.get(seg, 0)
            if self._seg_live.get(seg, 0) == 0 or (
                    size and dead / size >= self.compact_dead_ratio):
                if dead >= best_dead:
                    best, best_dead = seg, dead
        return best

    def compact_step(self, budget: int = 1 * 1024 * 1024) -> int:
        """One budgeted maintenance step, intended for an executor
        thread: evacuate up to ``budget`` live bytes from the deadest
        sealed segment into the active log (copies are re-appends, so
        logical order is preserved: the copy IS the live value), unlink
        the victim once empty, and refresh the checkpoint when due.
        Returns bytes of garbage reclaimed (0 = nothing to do). Crash
        at ANY point is safe: re-appended copies are idempotent
        last-write-wins on replay, and the victim is unlinked only
        after its records are all duplicated. Entry points are
        serialized (the periodic tick vs an admin `store compact`): a
        concurrent second caller returns 0 instead of racing the
        shared evacuation state."""
        if not self._compact_mutex.acquire(blocking=False):
            return 0
        try:
            return self._compact_step_serialized(budget)
        finally:
            self._compact_mutex.release()

    def _compact_step_serialized(self, budget: int) -> int:
        reclaimed = 0
        if self._evac is None:
            with self._lock:
                victim = self._pick_victim_locked()
                if victim is not None:
                    keys = [k for k, loc in self._index.items()
                            if loc[0] == victim]
                    self._evac = (victim, keys, 0)
        if self._evac is not None:
            victim, keys, total_copied = self._evac
            copied = 0
            while keys and copied < budget:
                # budget checked per record; the lock is held for at
                # most 32 copies so loop-side writers never wait long
                with self._lock:
                    for _ in range(32):
                        if not keys or copied >= budget:
                            break
                        k = keys.pop()
                        loc = self._index.get(k)
                        if loc is None or loc[0] != victim:
                            continue  # deleted/overwritten meanwhile
                        val = self._read_loc(loc)
                        self._put_many_locked([(k, val)])
                        copied += _PUT_OVERHEAD + len(k) + len(val)
            self.compacted_bytes += copied
            total_copied += copied
            if not keys:
                # fully evacuated: drop accounting, close the read
                # handle, unlink the file — reclaiming its dead bytes
                with self._lock:
                    size = self._seg_size.pop(victim, 0)
                    self._seg_live.pop(victim, None)
                    fh = self._read_fh.pop(victim, None)
                self._evac = None
                if fh is not None:
                    fh.close()
                try:
                    os.unlink(os.path.join(self.directory,
                                           _seg_name(victim)))
                except OSError:
                    pass
                self.compactions += 1
                # garbage actually reclaimed = the victim's on-disk
                # bytes minus EVERY live byte copied out of it across
                # all budgeted ticks, not just this tick's share
                reclaimed = max(0, size - total_copied)
                self.checkpoint()
            else:
                self._evac = (victim, keys, total_copied)
        elif self._since_checkpoint >= self.checkpoint_every_bytes:
            self.checkpoint()
        return reclaimed

    def checkpoint(self) -> None:
        """Serialize the index + frontier so the next open replays only
        records appended after this point. Atomic (tmp + rename); the
        snapshot is taken in ONE lock acquisition, the file write runs
        outside it. Segment data is fsynced FIRST — a durable (fsynced)
        checkpoint must never index bytes that only exist in the page
        cache, or power loss leaves it pointing past EOF."""
        self.sync()
        with self._lock:
            front_seg = self._active
            front_off = self._seg_size[self._active]
            entries = list(self._index.items())
            self._since_checkpoint = 0
        body = bytearray(_CKPT_MAGIC)
        body += struct.pack(">IQQ", front_seg, front_off, len(entries))
        for key, (seg, off, vlen) in entries:
            body += struct.pack(">I", len(key)) + key
            body += struct.pack(">IQI", seg, off, vlen)
        tmp = self._ckpt_path() + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(bytes(body) + struct.pack(">I", zlib.crc32(body)))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._ckpt_path())
        self.checkpoints += 1

    # ---------------------------------------------------------- lifecycle

    def sync(self) -> None:
        with self._lock:
            self._active_fh.flush()
            # dup the active fd: a compaction-driven roll may close the
            # handle between lock release and the fsync below — the
            # dup'd descriptor survives that close
            fd = os.dup(self._active_fh.fileno())
            sealed, self._sealed_unsynced = self._sealed_unsynced, []
        try:
            # segments sealed since the last sync first: their tails
            # hold records older than the active segment's
            for seg in sealed:
                try:
                    os.fsync(self._read_handle(seg).fileno())
                except FileNotFoundError:
                    # evacuated + unlinked meanwhile: its live records
                    # were re-appended to the active log, synced below
                    continue
            os.fsync(fd)
        finally:
            os.close(fd)

    def close(self) -> None:
        try:
            self.checkpoint()
        except Exception:
            log.exception("segment engine %s: checkpoint at close "
                          "failed (next open falls back to a full scan)",
                          self.directory)
        with self._lock:
            if self._active_fh is not None:
                self._active_fh.close()
                self._active_fh = None
            for fh in self._read_fh.values():
                fh.close()
            self._read_fh.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            live = sum(self._seg_live.values())
            size = sum(self._seg_size.values())
            nseg = len(self._seg_size)
            keys = len(self._index)
        return {
            "keys": keys, "segments": nseg, "live_bytes": live,
            "garbage_bytes": max(0, size - live),
            "compactions": self.compactions,
            "compacted_bytes": self.compacted_bytes,
            "checkpoints": self.checkpoints,
            "recover_skipped": self.recover_skipped,
            "recover_fallbacks": self.recover_fallbacks,
            "recover_replayed": self.recover_replayed,
        }


class NativeEngine:
    """The C++ kvstore behind the shared engine interface. Recovery and
    incremental compaction are the native engine's own; ``compact_step``
    maps to a full native compaction once garbage crosses the
    threshold (the native store also self-compacts on writes, so the
    broker's budgeted driver is a backstop here, not the only trigger).
    """

    kind = "native"
    durable = True

    def __init__(self, path: str,
                 compact_threshold: int = 64 * 1024 * 1024):
        from ..native.kvstore import KVStore

        self._kv = KVStore(path, compact_threshold=compact_threshold)
        self.path = path
        self.compactions = 0

    def put_many(self, pairs) -> None:
        self._kv.put_many(pairs)

    def get(self, key: bytes) -> Optional[bytes]:
        return self._kv.get(key)

    def delete(self, key: bytes) -> bool:
        return self._kv.delete(key)

    def delete_many(self, keys) -> int:
        return sum(1 for k in keys if self._kv.delete(k))

    def scan(self, prefix: bytes = b"") -> List[Tuple[bytes, bytes]]:
        return self._kv.scan(prefix)

    def scan_keys(self, prefix: bytes = b"") -> List[bytes]:
        return self._kv.scan_keys(prefix)

    def count(self) -> int:
        return self._kv.count()

    def garbage_bytes(self) -> int:
        return self._kv.garbage_bytes()

    def compact_step(self, budget: int = 0) -> int:
        g = self._kv.garbage_bytes()
        if g <= self._kv.compact_threshold:
            return 0
        self._kv.compact()
        self.compactions += 1
        return g

    def sync(self) -> None:
        self._kv.sync()

    def close(self) -> None:
        self._kv.close()

    def stats(self) -> Dict[str, int]:
        return {"keys": self._kv.count(),
                "garbage_bytes": self._kv.garbage_bytes(),
                "compactions": self.compactions}


def open_engine(directory: str, filename: str = "store",
                prefer: str = "auto",
                segment_max_bytes: int = 8 * 1024 * 1024,
                checkpoint_every_bytes: int = 32 * 1024 * 1024):
    """Open the storage engine for ``directory``: the native kvstore
    when the toolchain built it (``prefer`` "auto"/"native"), the
    pure-Python segment twin otherwise (or with ``prefer="segment"``),
    a :class:`MemEngine` when ``directory`` is empty. Same interface
    across all three — callers learn which one served from
    ``engine.kind`` (the bench artifacts record it so partition-storm /
    reconnect-storm numbers are comparable across boxes)."""
    if not directory:
        return MemEngine()
    os.makedirs(directory, exist_ok=True)
    if prefer in ("auto", "native"):
        try:
            return NativeEngine(os.path.join(directory, filename + ".kv"))
        except Exception as e:
            log.warning("native kvstore unavailable for %s (%s); using "
                        "the segment-log engine", directory, e)
    return SegmentLogEngine(
        os.path.join(directory, filename + ".seg"),
        segment_max_bytes=segment_max_bytes,
        checkpoint_every_bytes=checkpoint_every_bytes)
