"""Reconnect-storm resume collector: coalesce concurrent offline-queue
replays into batched store reads.

The storage sibling of ``retained/collector.RetainedBatchCollector``: a
reconnect storm used to cost one loop-side ``msg_store.read_all`` (scan
+ decode of the whole backlog ON the event loop) plus one Python
enqueue loop per session — the last hot path that had never been
batched. Sessions re-registering within ``window_us`` (or until
``max_batch``) now ride ONE executor call (``store.read_many``), so
the scans and payload decodes for a whole storm batch run off the
loop while the loop stages delivery of the previous batch — loop-side
cost per offline message is O(1) small.

The template's guarantees carry over: flushes at or below
``host_threshold`` are served by the exact per-session ``read_all`` on
the loop (a lone reconnect must not pay an executor round trip), the
overload governor's L2 defer gate stretches the window so replay
storms wait out congestion (bounded by ``MAX_DEFERS``), queued resumes
older than ``item_expiry_ms`` are settled by the exact per-session
fallback even with both pipeline slots busy, and ANY batched-read
failure falls back per session — an outage costs latency, never a lost
or reordered replay. Ordering across the replay window is the queue's
job (``SubscriberQueue.begin_resume``/``finish_resume`` park live
publishes until the stored backlog has been delivered).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..observability import histogram as obs

log = logging.getLogger("vernemq_tpu.storage")


class ResumeCollector:
    #: batched reads in flight at once. ONE slot, deliberately unlike
    #: the retained collector's two: the read is GIL-bound Python
    #: decode, so a second in-flight read doesn't overlap device time —
    #: it fights the loop's staged delivery for the interpreter
    #: (measured: 2 slots at 20k sessions = loop-lag p99 ~650ms, 1 slot
    #: ~40ms at equal throughput). Late arrivals still coalesce while
    #: the single slot is busy. Revisit when read_many is native-batch.
    MAX_INFLIGHT = 1

    #: consecutive overload deferrals before a flush goes out anyway
    MAX_DEFERS = 8

    #: per-callback loop-yield grain while staging deliveries
    _CHUNK = 64

    def __init__(self, store, window_us: int = 500,
                 max_batch: int = 512, host_threshold: int = 4,
                 item_expiry_ms: float = 0.0,
                 read_timeout_s: float = 30.0,
                 metrics=None):
        self.store = store
        self.window = window_us / 1e6
        self.max_batch = max_batch
        self.host_threshold = host_threshold
        self.item_expiry = item_expiry_ms / 1e3
        self.read_timeout_s = read_timeout_s
        self.metrics = metrics
        self._pending: List[Tuple] = []  # (sid, fut, expiry)
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._expiry_handle: Optional[asyncio.TimerHandle] = None
        self._inflight = 0
        self._closed = False
        self.defer_gate = None
        self._defers_in_row = 0
        self._defer_armed = False
        # observability (broker gauges / bench artifact)
        self.batched_sessions = 0    # sessions served by a batched read
        self.batched_reads = 0       # executor read_many calls
        self.host_sessions = 0       # small flushes served per-session
        self.expired_sessions = 0    # waited out item_expiry -> fallback
        self.fallback_sessions = 0   # batched read failed -> per-session
        self.deferred_flushes = 0

    def close(self) -> None:
        """Settle every pending resume from the per-session read on the
        loop (the store outlives the collector in the stop order) so no
        future leaks unresolved."""
        self._closed = True
        for h in (self._flush_handle, self._expiry_handle):
            if h is not None:
                h.cancel()
        self._flush_handle = self._expiry_handle = None
        pending, self._pending = self._pending, []
        for sid, fut, _exp in pending:
            self._host_read(sid, fut)

    def submit(self, sid) -> asyncio.Future:
        """One reconnecting session's offline replay; resolves to its
        ``[Msg, ...]`` backlog in enqueue order."""
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        if self._closed:
            self._host_read(sid, fut)
            return fut
        exp = (time.monotonic() + self.item_expiry
               if self.item_expiry > 0 else None)
        self._pending.append((sid, fut, exp))
        if exp is not None and self._expiry_handle is None:
            self._expiry_handle = loop.call_later(self.item_expiry,
                                                  self._expire_sweep)
        if len(self._pending) >= self.max_batch:
            if self._defer_armed:
                # an L2+ deferral is waiting out congestion: storm
                # arrivals must not re-trigger the flush path and burn
                # the MAX_DEFERS budget in microseconds
                return fut
            if self._flush_handle is not None:
                self._flush_handle.cancel()
                self._flush_handle = None
            self._flush()
        elif self._flush_handle is None:
            self._flush_handle = loop.call_later(self.window, self._flush)
        return fut

    def _host_read(self, sid, fut) -> None:
        """The exact per-session fallback (and sub-threshold server)."""
        if fut.done():
            return
        try:
            fut.set_result(self.store.read_all(sid))
        except Exception as e:
            fut.set_exception(e)

    def _expire_sweep(self) -> None:
        self._expiry_handle = None
        if not self._pending:
            return
        now = time.monotonic()
        settled = 0
        keep = []
        for item in self._pending:
            sid, fut, exp = item
            if exp is not None and now >= exp and settled < self._CHUNK:
                self.expired_sessions += 1
                self._host_read(sid, fut)
                settled += 1
            else:
                keep.append(item)
        self._pending = keep
        if self._pending and self._pending[0][2] is not None:
            delay = (0.0 if now >= self._pending[0][2]
                     else max(0.005, self._pending[0][2] - now))
            self._expiry_handle = asyncio.get_event_loop().call_later(
                delay, self._expire_sweep)

    def pressure(self) -> float:
        """Resume-path pressure for the overload governor (same fused
        rule as the publish/retained collectors)."""
        from ..robustness.overload import collector_pressure

        return collector_pressure(
            len(self._pending), self.max_batch * self.MAX_INFLIGHT,
            0.0, 1.0)

    def _flush(self) -> None:
        self._flush_handle = None
        self._defer_armed = False
        if not self._pending:
            return
        if (self.defer_gate is not None
                and self._defers_in_row < self.MAX_DEFERS
                and len(self._pending) > self.host_threshold
                and self.defer_gate()):
            # L2+ deferral: the replay storm re-arms a stretched window
            # instead of competing with live traffic; bounded so a
            # pinned level can't starve resumes forever
            self._defers_in_row += 1
            self.deferred_flushes += 1
            self._defer_armed = True
            self._flush_handle = asyncio.get_event_loop().call_later(
                self.window * 8, self._flush)
            return
        self._defers_in_row = 0
        if len(self._pending) <= self.host_threshold:
            pending, self._pending = self._pending, []
            self.host_sessions += len(pending)
            for sid, fut, _exp in pending:
                self._host_read(sid, fut)
            return
        if self._inflight >= self.MAX_INFLIGHT:
            # both slots busy: leave items pending so late arrivals
            # coalesce into one bigger batch; _on_done flushes the
            # moment a slot frees (bounded self-batching backpressure)
            return
        pending, self._pending = (self._pending[:self.max_batch],
                                  self._pending[self.max_batch:])
        self._inflight += 1
        task = asyncio.get_event_loop().create_task(
            self._flush_async(pending))
        task.add_done_callback(self._on_done)

    def _on_done(self, task) -> None:
        self._inflight -= 1
        if not task.cancelled() and task.exception() is not None:
            log.warning("resume flush task failed: %s", task.exception())
        if self._pending:
            if self._flush_handle is not None:
                self._flush_handle.cancel()
                self._flush_handle = None
            self._flush()

    async def _flush_async(self, pending) -> None:
        loop = asyncio.get_event_loop()
        t0 = time.perf_counter()
        now = time.monotonic()
        live: List[Tuple] = []
        for i, (sid, fut, exp) in enumerate(pending):
            if exp is not None and now >= exp:
                # waited out its expiry behind busy slots: the exact
                # per-session read answers instead of deepening the queue
                self.expired_sessions += 1
                self._host_read(sid, fut)
                if (i + 1) % self._CHUNK == 0:
                    await asyncio.sleep(0)
            else:
                live.append((sid, fut))
        if not live:
            return
        sids = [sid for sid, _ in live]
        try:
            # ONE off-loop call scans + decodes the whole batch while
            # the loop keeps serving; wait_for bounds a wedged disk
            # (the executor thread is abandoned, the exact per-session
            # fallback serves — the sacrificial-dispatch discipline)
            backlogs: Dict = await asyncio.wait_for(
                loop.run_in_executor(None, self.store.read_many, sids),
                timeout=self.read_timeout_s)
        except asyncio.TimeoutError:
            # the read WEDGED (not errored): the abandoned thread may
            # still hold the store lock, so the fallback reads must
            # also run off-loop — an inline read_all here would park
            # the event loop on the exact stall the timeout survived.
            # They settle (or queue behind the wedge) on the executor;
            # the loop stays alive either way.
            log.warning("batched resume read timed out after %.1fs; "
                        "%d session(s) fall back to executor-side "
                        "per-session reads", self.read_timeout_s,
                        len(live))
            self.fallback_sessions += len(live)
            for sid, fut in live:
                task = loop.run_in_executor(
                    None, self.store.read_all, sid)

                def _settle(t, fut=fut):
                    if fut.done():
                        return
                    exc = None if t.cancelled() else t.exception()
                    if exc is not None:
                        fut.set_exception(exc)
                    elif t.cancelled():
                        fut.cancel()
                    else:
                        fut.set_result(t.result())

                task.add_done_callback(_settle)
            return
        except Exception as e:
            log.warning("batched resume read failed (%s); per-session "
                        "fallback serves %d session(s)", e, len(live))
            self.fallback_sessions += len(live)
            for i, (sid, fut) in enumerate(live):
                self._host_read(sid, fut)
                if (i + 1) % self._CHUNK == 0:
                    await asyncio.sleep(0)
            return
        self.batched_reads += 1
        self.batched_sessions += len(live)
        for i, (sid, fut) in enumerate(live):
            if not fut.done():
                fut.set_result(backlogs.get(sid, []))
            if (i + 1) % self._CHUNK == 0:
                # staged delivery: resolving a future fires the queue's
                # finish_resume synchronously — yield between chunks so
                # a 100k-session storm never stalls the loop for its
                # whole duration
                await asyncio.sleep(0)
        obs.observe("stage_resume_replay_ms",
                    (time.perf_counter() - t0) * 1e3)

    def stats(self) -> Dict[str, float]:
        return {
            "resume_batched_sessions": float(self.batched_sessions),
            "resume_batched_reads": float(self.batched_reads),
            "resume_host_sessions": float(self.host_sessions),
            "resume_expired_sessions": float(self.expired_sessions),
            "resume_fallback_sessions": float(self.fallback_sessions),
            "resume_deferred_flushes": float(self.deferred_flushes),
            "resume_pending_sessions": float(len(self._pending)),
        }
