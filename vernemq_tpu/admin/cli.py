"""``vmq-admin`` — operator CLI against a running broker.

The reference CLI (clique, ``vmq_server_cli.erl``) runs inside the target
node via distribution; here the CLI speaks to the broker's HTTP management
API (the same transport ``vmq_http_mgmt_api.erl`` exposes), so
``python -m vernemq_tpu.admin session show`` works against any reachable
node. Tables are pretty-printed like clique's table writer; ``--json``
emits the raw API payload (the ``vmq_cli_json_writer`` switch).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List


def format_table(rows: List[Dict[str, Any]]) -> str:
    if not rows:
        return "(no rows)"
    cols: List[str] = []
    for row in rows:
        for k in row:
            if k not in cols:
                cols.append(k)
    widths = {c: len(c) for c in cols}
    rendered = []
    for row in rows:
        r = {c: _cell(row.get(c)) for c in cols}
        for c in cols:
            widths[c] = max(widths[c], len(r[c]))
        rendered.append(r)
    sep = "+" + "+".join("-" * (widths[c] + 2) for c in cols) + "+"
    out = [sep, "|" + "|".join(f" {c.ljust(widths[c])} " for c in cols) + "|", sep]
    for r in rendered:
        out.append("|" + "|".join(f" {r[c].ljust(widths[c])} " for c in cols) + "|")
    out.append(sep)
    return "\n".join(out)


def _cell(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def run_remote(base_url: str, api_key: str, words: List[str],
               timeout: float = 10.0) -> Dict[str, Any]:
    path_words, query = [], []
    for w in words:
        if "=" in w or w.startswith("--"):
            k, _, v = w.lstrip("-").partition("=")
            query.append((k, v))
        else:
            path_words.append(urllib.parse.quote(w, safe=""))
    if api_key:
        query.append(("api_key", api_key))
    url = (f"{base_url.rstrip('/')}/api/v1/" + "/".join(path_words)
           + ("?" + urllib.parse.urlencode(query) if query else ""))
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read().decode())
        except Exception:
            return {"error": f"HTTP {e.code}"}
    except (urllib.error.URLError, OSError) as e:
        return {"error": f"cannot reach broker at {base_url}: {e}"}


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="vmq-admin",
        description="administer a running vernemq_tpu broker",
        add_help=False)
    parser.add_argument("--node-url", default="http://127.0.0.1:8888",
                        help="broker HTTP endpoint (default %(default)s)")
    parser.add_argument("--api-key", default="",
                        help="management API key (api-key create)")
    parser.add_argument("--json", action="store_true",
                        help="emit raw JSON instead of tables")
    parser.add_argument("-h", "--help", action="store_true")
    args, words = parser.parse_known_args(argv)

    if args.help or not words:
        parser.print_help()
        print("\nExamples:\n"
              "  vmq-admin node status\n"
              "  vmq-admin session show --limit=10\n"
              "  vmq-admin metrics show\n"
              "  vmq-admin cluster join discovery-node=host:24053\n"
              "  vmq-admin api-key create\n")
        return 0

    result = run_remote(args.node_url, args.api_key, words)
    if args.json:
        print(json.dumps(result, indent=2, default=str))
        return 1 if "error" in result else 0
    if "error" in result:
        print(f"error: {result['error']}", file=sys.stderr)
        if result.get("usage"):
            print(result["usage"], file=sys.stderr)
        return 1
    if result.get("type") == "table":
        print(format_table(result.get("table", [])))
    else:
        print(result.get("text", ""))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
