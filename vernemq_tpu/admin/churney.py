"""Churney: built-in session-churn self-test.

Plays the role of ``vmq_churney.erl`` (201 LoC, part of vmq_swc): spawn
one full MQTT session after another against the local broker — connect,
subscribe, publish qos1, receive own message, disconnect — and histogram
the end-to-end latency, bucketing failures by stage. The reference runs
sessions back-to-back and logs a histogram every 10s; here the driver is
an asyncio task and the histogram is pulled via ``vmq-admin churney
report`` (or the returned stats object).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, Optional

BUCKETS_MS = (1, 2, 5, 10, 25, 50, 100, 250, 1000, float("inf"))


class Churney:
    def __init__(self, broker, host: str, port: int, concurrency: int = 1):
        self.broker = broker
        self.host, self.port = host, port
        self.concurrency = concurrency
        self.histogram: Dict[Any, int] = {}
        self.outcomes: Dict[str, int] = {}
        self.sessions = 0
        self.started = time.time()
        self._tasks: list = []
        self._running = False

    def start(self) -> None:
        self._running = True
        loop = asyncio.get_event_loop()
        for i in range(self.concurrency):
            self._tasks.append(loop.create_task(self._churn(i)))

    def stop(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()

    async def _one_session(self, n: int) -> str:
        """One full session life cycle; returns the outcome stage label
        (the reference buckets DOWN reasons the same way)."""
        from ..client import MQTTClient

        c = MQTTClient(self.host, self.port, client_id=f"churney-{n}")
        try:
            # the client's own timeout covers only the CONNACK read; TCP
            # establishment against a black-holed host needs its own bound
            ack = await asyncio.wait_for(c.connect(timeout=5.0), 7.0)
            if getattr(ack, "rc", 1) != 0:
                return "error_connect"
            topic = f"churney/{n}"
            sub = await c.subscribe(topic, qos=1)
            if sub.reason_codes[0] not in (0, 1):
                return "error_subscribe"
            await c.publish(topic, b"churn", qos=1)
            msg = await c.recv(5.0)
            if msg is None or getattr(msg, "payload", None) != b"churn":
                return "error_deliver"
            await c.disconnect()
            return "ok"
        except asyncio.TimeoutError:
            return "error_timeout"
        except ConnectionError:
            return "error_conn"
        except asyncio.CancelledError:
            raise
        except Exception:  # gaierror, codec errors… never kill the worker
            return "error_other"
        finally:
            await c.close()

    async def _churn(self, worker: int) -> None:
        n = worker
        while self._running:
            t0 = time.perf_counter()
            outcome = await self._one_session(n)
            latency_ms = (time.perf_counter() - t0) * 1000
            self.sessions += 1
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
            for b in BUCKETS_MS:
                if latency_ms <= b:
                    self.histogram[b] = self.histogram.get(b, 0) + 1
                    break
            n += self.concurrency
            await asyncio.sleep(0)  # yield; back-to-back like the reference

    def report(self) -> Dict[str, Any]:
        elapsed = max(time.time() - self.started, 1e-9)
        return {
            "sessions": self.sessions,
            "sessions_per_sec": round(self.sessions / elapsed, 1),
            "outcomes": dict(self.outcomes),
            "latency_histogram_ms": {
                ("inf" if b == float("inf") else b): n
                for b, n in sorted(self.histogram.items())
            },
        }
