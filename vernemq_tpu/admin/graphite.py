"""Graphite reporter: periodic plaintext-protocol push of all metrics
(``vmq_graphite.erl:118-130`` — one ``<prefix>vmq.<node>.<metric> <value>
<ts>\\n`` line per metric over TCP, reconnect on failure)."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

log = logging.getLogger("vernemq_tpu.graphite")


class GraphiteReporter:
    def __init__(self, broker):
        self.broker = broker
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._run())
        self.broker._bg_tasks.append(self._task)

    async def _run(self) -> None:
        cfg = self.broker.config
        writer: Optional[asyncio.StreamWriter] = None
        # connect/reconnect pacing follows the graphite_*_timeout knobs
        # (vmq_graphite.erl connect_timeout / reconnect backoff)
        connect_timeout = float(cfg.get("graphite_connect_timeout", 5.0))
        reconnect_wait = float(cfg.get("graphite_reconnect_timeout", 10.0))
        if cfg.graphite_interval <= 0:
            return  # 0 = disabled (reference schema graphite_interval)
        while True:
            await asyncio.sleep(cfg.graphite_interval)
            if writer is None:
                try:
                    _, writer = await asyncio.wait_for(
                        asyncio.open_connection(cfg.graphite_host,
                                                cfg.graphite_port),
                        connect_timeout)
                except (OSError, asyncio.TimeoutError) as e:
                    log.debug("graphite connect failed: %s", e)
                    await asyncio.sleep(
                        max(0.0, reconnect_wait - cfg.graphite_interval))
                    continue
            prefix = cfg.graphite_prefix
            if prefix and not prefix.endswith("."):
                prefix += "."
            # hosted-graphite API key is the leading path segment
            api_key = cfg.get("graphite_api_key", "")
            if api_key:
                prefix = f"{api_key}.{prefix}"
            node = self.broker.node_name
            now = int(time.time())
            lines = [
                f"{prefix}vmq.{node}.{name} {value} {now}\n"
                for name, value in self.broker.metrics.all_metrics().items()
            ]
            # histogram families go out as bucket-derived quantile
            # summaries (<name>.p50/p99/p999) — parity with the
            # Prometheus _bucket surface without shipping 33 bucket
            # series per family over plaintext
            from ..observability import histogram as _hist

            for name, snap in sorted(
                    self.broker.metrics.histogram_snapshot().items()):
                counts, _s, n_obs = snap
                if not n_obs:
                    continue
                for key, q in (("p50", 0.50), ("p99", 0.99),
                               ("p999", 0.999)):
                    v = _hist.quantile(counts, q)
                    if v is not None:
                        lines.append(f"{prefix}vmq.{node}.{name}.{key} "
                                     f"{round(v, 4)} {now}\n")
            try:
                writer.write("".join(lines).encode())
                await writer.drain()
            except (OSError, ConnectionError):
                try:
                    writer.close()
                except Exception:
                    pass
                writer = None
