"""Graphite reporter: periodic plaintext-protocol push of all metrics
(``vmq_graphite.erl:118-130`` — one ``<prefix>vmq.<node>.<metric> <value>
<ts>\\n`` line per metric over TCP, reconnect on failure)."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

log = logging.getLogger("vernemq_tpu.graphite")


class GraphiteReporter:
    def __init__(self, broker):
        self.broker = broker
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._run())
        self.broker._bg_tasks.append(self._task)

    async def _run(self) -> None:
        cfg = self.broker.config
        writer: Optional[asyncio.StreamWriter] = None
        # connect/reconnect pacing follows the graphite_*_timeout knobs
        # (vmq_graphite.erl connect_timeout / reconnect backoff)
        connect_timeout = float(cfg.get("graphite_connect_timeout", 5.0))
        reconnect_wait = float(cfg.get("graphite_reconnect_timeout", 10.0))
        if cfg.graphite_interval <= 0:
            return  # 0 = disabled (reference schema graphite_interval)
        while True:
            await asyncio.sleep(cfg.graphite_interval)
            if writer is None:
                try:
                    _, writer = await asyncio.wait_for(
                        asyncio.open_connection(cfg.graphite_host,
                                                cfg.graphite_port),
                        connect_timeout)
                except (OSError, asyncio.TimeoutError) as e:
                    log.debug("graphite connect failed: %s", e)
                    await asyncio.sleep(
                        max(0.0, reconnect_wait - cfg.graphite_interval))
                    continue
            prefix = cfg.graphite_prefix
            if prefix and not prefix.endswith("."):
                prefix += "."
            # hosted-graphite API key is the leading path segment
            api_key = cfg.get("graphite_api_key", "")
            if api_key:
                prefix = f"{api_key}.{prefix}"
            node = self.broker.node_name
            now = int(time.time())
            lines = [
                f"{prefix}vmq.{node}.{name} {value} {now}\n"
                for name, value in self.broker.metrics.all_metrics().items()
            ]
            try:
                writer.write("".join(lines).encode())
                await writer.drain()
            except (OSError, ConnectionError):
                try:
                    writer.close()
                except Exception:
                    pass
                writer = None
