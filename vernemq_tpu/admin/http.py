"""HTTP endpoints: Prometheus metrics, health, status, management API.

One small asyncio HTTP/1.1 server replaces the reference's cowboy
listeners; the module set per listener is configurable the way
``vmq_http_config.erl:8`` assembles a cowboy dispatch from the
``http_modules`` config:

- ``metrics`` → ``GET /metrics`` Prometheus text (vmq_metrics_http.erl:42-84)
- ``health``  → ``GET /health`` cluster+listener checks (vmq_health_http.erl)
- ``status``  → ``GET /status.json`` node/cluster stats (vmq_status_http.erl)
- ``mgmt``    → ``GET|POST /api/v1/<cmd>/<sub>?flags`` mapped onto the
  vmq-admin command tree with api-key Basic auth (vmq_http_mgmt_api.erl)
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qsl, unquote, urlsplit

from .commands import CommandError, CommandRegistry, register_core_commands, valid_api_key

log = logging.getLogger("vernemq_tpu.http")

MAX_HEADER = 65536
DEFAULT_MODULES = ("metrics", "health", "status", "mgmt")


class HttpServer:
    def __init__(self, broker, host: str = "127.0.0.1", port: int = 8888,
                 modules: Tuple[str, ...] = DEFAULT_MODULES,
                 registry: Optional[CommandRegistry] = None,
                 ssl_context=None):
        self.broker = broker
        self.host = host
        self.port = port
        self.modules = modules
        self.registry = registry or register_core_commands(CommandRegistry())
        self.ssl_context = ssl_context
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, ssl=self.ssl_context)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        self.broker._servers.append(self._server)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # ------------------------------------------------------------- plumbing

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    head = await asyncio.wait_for(
                        reader.readuntil(b"\r\n\r\n"), 30.0)
                except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                        asyncio.LimitOverrunError):
                    return
                if len(head) > MAX_HEADER:
                    return
                request = head.decode("latin1")
                lines = request.split("\r\n")
                try:
                    method, target, _version = lines[0].split(" ", 2)
                except ValueError:
                    return
                headers: Dict[str, str] = {}
                for ln in lines[1:]:
                    if ":" in ln:
                        k, _, v = ln.partition(":")
                        headers[k.strip().lower()] = v.strip()
                body = b""
                clen = int(headers.get("content-length", 0) or 0)
                if clen:
                    if clen > MAX_HEADER:
                        # drain and refuse; close so the stream can't desync
                        remaining = clen
                        while remaining > 0:
                            chunk = await reader.read(min(remaining, 65536))
                            if not chunk:
                                break
                            remaining -= len(chunk)
                        writer.write(
                            b"HTTP/1.1 413 Payload Too Large\r\n"
                            b"Content-Length: 0\r\nConnection: close\r\n\r\n")
                        await writer.drain()
                        return
                    body = await reader.readexactly(clen)
                status, ctype, payload = self._dispatch(
                    method.upper(), target, headers, body)
                keep = headers.get("connection", "").lower() != "close"
                writer.write(
                    b"HTTP/1.1 " + status.encode() + b"\r\n"
                    b"Content-Type: " + ctype.encode() + b"\r\n"
                    b"Content-Length: " + str(len(payload)).encode() + b"\r\n"
                    b"Connection: " + (b"keep-alive" if keep else b"close") +
                    b"\r\n\r\n" + payload)
                await writer.drain()
                if not keep:
                    return
        except (ConnectionError, asyncio.CancelledError):
            pass
        except Exception:
            log.exception("http handler crashed")
        finally:
            try:
                writer.close()
            except Exception:
                pass

    # ------------------------------------------------------------- routing

    def _dispatch(self, method: str, target: str, headers: Dict[str, str],
                  body: bytes) -> Tuple[str, str, bytes]:
        parts = urlsplit(target)
        path = unquote(parts.path)
        qs = dict(parse_qsl(parts.query, keep_blank_values=True))
        if path == "/metrics" and "metrics" in self.modules:
            return ("200 OK", "text/plain; version=0.0.4",
                    self.broker.metrics.prometheus_text(
                        self.broker.node_name).encode())
        if path == "/health" and "health" in self.modules:
            return self._health()
        if path in ("/status", "/status.json") and "status" in self.modules:
            return ("200 OK", "application/json",
                    json.dumps(self._status()).encode())
        if path.startswith("/api/v1/") and "mgmt" in self.modules:
            return self._mgmt(path[len("/api/v1/"):], qs, headers)
        if path.startswith("/api/v1") and "mgmt" in self.modules:
            return self._mgmt("", qs, headers)
        return ("404 Not Found", "text/plain", b"not found\n")

    def _health(self) -> Tuple[str, str, bytes]:
        """OK when the cluster is ready and listeners are up
        (vmq_health_http.erl:30-60)."""
        problems: List[str] = []
        if not self.broker.cluster_ready():
            problems.append("cluster_not_ready")
        if problems:
            return ("503 Service Unavailable", "application/json",
                    json.dumps({"status": "DOWN", "problems": problems}).encode())
        return ("200 OK", "application/json",
                json.dumps({"status": "OK"}).encode())

    def _status(self) -> Dict[str, Any]:
        b = self.broker
        nodes = [{"node": b.node_name, "running": True}]
        if b.cluster is not None:
            nodes = [{"node": n, "running": up} for n, up in b.cluster.status()]
        m = b.metrics.all_metrics()
        return {
            "node": b.node_name,
            "ready": b.cluster_ready(),
            "nodes": nodes,
            "active_sessions": m.get("active_sessions", 0),
            "router_subscriptions": m.get("router_subscriptions", 0),
            "retain_messages": m.get("retain_messages", 0),
            "publish_received": m.get("mqtt_publish_received", 0),
            "publish_sent": m.get("mqtt_publish_sent", 0),
            **({"sysmon": b.sysmon.status()} if b.sysmon is not None else {}),
        }

    # ----------------------------------------------------------- mgmt API

    def _authorized(self, headers: Dict[str, str], qs: Dict[str, str]) -> bool:
        if not self.broker.config.get("http_mgmt_api_auth", True):
            return True
        key = qs.get("api_key")
        auth = headers.get("authorization", "")
        if key is None and auth.lower().startswith("basic "):
            try:
                decoded = base64.b64decode(auth[6:]).decode()
                key = decoded.partition(":")[0]
            except Exception:
                key = None
        return key is not None and valid_api_key(self.broker, key)

    def _mgmt(self, cmd_path: str, qs: Dict[str, str],
              headers: Dict[str, str]) -> Tuple[str, str, bytes]:
        if not self._authorized(headers, qs):
            return ("401 Unauthorized", "application/json",
                    json.dumps({"error": "unauthorized"}).encode())
        words = [w for w in cmd_path.split("/") if w]
        words += [f"{k}={v}" if v != "" else k for k, v in qs.items()
                  if k != "api_key"]
        try:
            result = self.registry.run(self.broker, words)
        except CommandError as e:
            return ("400 Bad Request", "application/json",
                    json.dumps({"error": e.message, "usage": e.usage}).encode())
        except Exception as e:  # command crashed
            log.exception("mgmt command failed: %s", words)
            return ("500 Internal Server Error", "application/json",
                    json.dumps({"error": str(e)}).encode())
        if isinstance(result, dict) and "table" in result:
            payload = {"type": "table", "table": result["table"]}
        else:
            payload = {"type": "text", "text": result}
        return ("200 OK", "application/json",
                json.dumps(payload, default=str).encode())
